"""Fused training kernels beyond attention/CE.

Reference: operators/fused/fused_layernorm_residual_dropout_bias.h (the
dropout→residual-add→LayerNorm epilogue fused into one CUDA kernel) and
operators/optimizers/distributed_fused_lamb / multi_tensor_adam (one kernel
applying the optimizer update to many tensors).

TPU-native design: XLA already fuses elementwise chains into neighboring
matmuls, so these Pallas kernels are *opt-in* (FLAGS_use_fused_ln /
FLAGS_use_fused_adamw, both off by default).  tools/fused_probe.py measures
the XLA roofline on each pattern; flip the flag only where the probe shows
XLA leaving >5% of step time on the table (VERDICT r2 item 9 — a
profile-driven decision, not cargo-cult fusion).

Semantics (matching the reference header):
    residual_out = residual + dropout(x + bias)
    out          = LayerNorm(residual_out) * ln_scale + ln_bias
Both outputs are returned (the transformer consumes residual_out as the next
skip connection).  The backward recomputes the dropout keep-mask from the
same position-hash used in ops/attention.py, so no (N, H) mask is stored;
saved state is residual_out plus the per-row (mu, rstd) pair.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.flags import flag


def _use_pallas() -> bool:
    return flag("FLAGS_use_pallas_kernels") and jax.default_backend() == "tpu"


def _keep_mask(seed, row0, shape, dropout_p):
    """Position-hash keep mask (rows are global row ids, cols feature ids) —
    identical bits in forward and backward by construction; one shared hash
    pipeline with the attention kernels (ops/attention.py)."""
    from .attention import position_hash_keep
    mixed = seed.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    return position_hash_keep(mixed, row0, 0, shape, dropout_p)


# ---------------------------------------------------------------------------
# Pallas kernels (rows blocked; weights broadcast to every block)
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(seed_ref, x_ref, res_ref, w_ref, b_ref, bias_ref,
                   out_ref, rout_ref, mu_ref, rstd_ref, *, block_rows,
                   dropout_p, eps, has_bias):
    import jax.experimental.pallas as pl
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    if has_bias:
        x = x + bias_ref[...].astype(jnp.float32)
    if dropout_p > 0.0:
        keep = _keep_mask(seed_ref[0], i * block_rows, x.shape, dropout_p)
        x = jnp.where(keep, x / (1.0 - dropout_p), 0.0)
    y = res_ref[...].astype(jnp.float32) + x
    mu = jnp.mean(y, axis=-1, keepdims=True)          # (br, 1)
    var = jnp.mean(jnp.square(y), axis=-1, keepdims=True) - jnp.square(mu)
    rstd = lax.rsqrt(var + eps)
    xhat = (y - mu) * rstd
    out = xhat * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    out_ref[...] = out.astype(out_ref.dtype)
    rout_ref[...] = y.astype(rout_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _ln_bwd_kernel(seed_ref, dout_ref, drout_ref, rout_ref, mu_ref, rstd_ref,
                   w_ref, dx_ref, dres_ref, dw_ref, db_ref, dbias_ref, *,
                   block_rows, dropout_p, has_bias):
    import jax.experimental.pallas as pl
    i = pl.program_id(0)
    H = rout_ref.shape[-1]
    y = rout_ref[...].astype(jnp.float32)
    mu = mu_ref[...]                                    # (br, 1)
    rstd = rstd_ref[...]
    xhat = (y - mu) * rstd
    dout = dout_ref[...].astype(jnp.float32)
    g = dout * w_ref[...].astype(jnp.float32)
    gm = jnp.mean(g, axis=-1, keepdims=True)
    gxm = jnp.mean(g * xhat, axis=-1, keepdims=True)
    dy = (g - gm - xhat * gxm) * rstd
    dy = dy + drout_ref[...].astype(jnp.float32)
    dres_ref[...] = dy.astype(dres_ref.dtype)
    if dropout_p > 0.0:
        keep = _keep_mask(seed_ref[0], i * block_rows, y.shape, dropout_p)
        dx = jnp.where(keep, dy / (1.0 - dropout_p), 0.0)
    else:
        dx = dy
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # per-block partial reductions over rows; summed in XLA afterwards
    dw_ref[...] = jnp.sum(dout * xhat, axis=0)[None]
    db_ref[...] = jnp.sum(dout, axis=0)[None]
    if has_bias:
        dbias_ref[...] = jnp.sum(dx, axis=0)[None]
    else:
        dbias_ref[...] = jnp.zeros((1, H), dbias_ref.dtype)


def _pick_block_rows(n):
    for b in (256, 128, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return None


def _ln_pallas_fwd(x2, res2, w, b, bias, seed, dropout_p, eps, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H = x2.shape
    br = _pick_block_rows(N)
    grid = (N // br,)
    # weights as (1, H), per-row stats as (N, 1): 2D blocks everywhere for
    # Mosaic-friendliness (1D iota/outputs don't lower)
    wspec = pl.BlockSpec((1, H), lambda i: (0, 0))
    rspec = pl.BlockSpec((br, H), lambda i: (i, 0))
    vspec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    kern = functools.partial(_ln_fwd_kernel, block_rows=br,
                             dropout_p=dropout_p, eps=eps,
                             has_bias=bias is not None)
    w2, b2 = w.reshape(1, H), b.reshape(1, H)
    bias2 = bias.reshape(1, H) if bias is not None else w2
    out, rout, mu, rstd = pl.pallas_call(
        kern, grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  rspec, rspec, wspec, wspec, wspec],
        out_specs=[rspec, rspec, vspec, vspec],
        out_shape=[jax.ShapeDtypeStruct((N, H), x2.dtype),
                   jax.ShapeDtypeStruct((N, H), x2.dtype),
                   jax.ShapeDtypeStruct((N, 1), jnp.float32),
                   jax.ShapeDtypeStruct((N, 1), jnp.float32)],
        interpret=interpret,
    )(seed, x2, res2, w2, b2, bias2)
    return out, rout, mu, rstd


def _ln_pallas_bwd(dout2, drout2, rout2, mu, rstd, w, seed, dropout_p,
                   has_bias, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H = rout2.shape
    br = _pick_block_rows(N)
    grid = (N // br,)
    nb = N // br
    wspec = pl.BlockSpec((1, H), lambda i: (0, 0))
    rspec = pl.BlockSpec((br, H), lambda i: (i, 0))
    vspec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    pspec = pl.BlockSpec((1, H), lambda i: (i, 0))
    kern = functools.partial(_ln_bwd_kernel, block_rows=br,
                             dropout_p=dropout_p, has_bias=has_bias)
    dx, dres, dwp, dbp, dbiasp = pl.pallas_call(
        kern, grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  rspec, rspec, rspec, vspec, vspec, wspec],
        out_specs=[rspec, rspec, pspec, pspec, pspec],
        out_shape=[jax.ShapeDtypeStruct((N, H), dout2.dtype),
                   jax.ShapeDtypeStruct((N, H), dout2.dtype),
                   jax.ShapeDtypeStruct((nb, H), jnp.float32),
                   jax.ShapeDtypeStruct((nb, H), jnp.float32),
                   jax.ShapeDtypeStruct((nb, H), jnp.float32)],
        interpret=interpret,
    )(seed, dout2, drout2, rout2, mu, rstd, w.reshape(1, H))
    return dx, dres, dwp.sum(0), dbp.sum(0), dbiasp.sum(0)


# ---------------------------------------------------------------------------
# custom_vjp plumbing
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _fused_ln_core(x2, res2, w, b, bias, seed, dropout_p, eps, interpret):
    out, rout, _, _ = _ln_pallas_fwd(x2, res2, w, b, bias, seed, dropout_p,
                                     eps, interpret)
    return out, rout


def _fused_ln_fwd(x2, res2, w, b, bias, seed, dropout_p, eps, interpret):
    out, rout, mu, rstd = _ln_pallas_fwd(x2, res2, w, b, bias, seed,
                                         dropout_p, eps, interpret)
    return (out, rout), (rout, mu, rstd, w, seed, bias is not None)


def _fused_ln_bwd(dropout_p, eps, interpret, res, cts):
    rout, mu, rstd, w, seed, has_bias = res
    dout2, drout2 = cts
    dx, dres, dw, db, dbias = _ln_pallas_bwd(
        dout2, drout2, rout, mu, rstd, w, seed, dropout_p, has_bias, interpret)
    return (dx, dres, dw.astype(w.dtype), db.astype(w.dtype),
            dbias.astype(w.dtype) if has_bias else None, None)


_fused_ln_core.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def _dense_ln_residual_dropout(x, residual, ln_scale, ln_bias, bias, seed,
                               dropout_p, eps):
    """XLA fallback / test oracle — same math, plain jnp."""
    x32 = x.astype(jnp.float32)
    if bias is not None:
        x32 = x32 + bias.astype(jnp.float32)
    if dropout_p > 0.0:
        flat = x32.reshape(-1, x32.shape[-1])
        keep = _keep_mask(jnp.asarray(seed, jnp.uint32).reshape(()), 0,
                          flat.shape, dropout_p).reshape(x32.shape)
        x32 = jnp.where(keep, x32 / (1.0 - dropout_p), 0.0)
    y = residual.astype(jnp.float32) + x32
    mu = y.mean(-1, keepdims=True)
    var = y.var(-1, keepdims=True)
    out = (y - mu) * lax.rsqrt(var + eps) * ln_scale.astype(jnp.float32) \
        + ln_bias.astype(jnp.float32)
    return out.astype(x.dtype), y.astype(x.dtype)


def fused_ln_residual_dropout(x, residual, ln_scale, ln_bias, bias=None,
                              dropout_p=0.0, dropout_seed=None, eps=1e-5):
    """residual_out = residual + dropout(x + bias);
    out = LayerNorm(residual_out)·ln_scale + ln_bias.  Returns
    (out, residual_out); shapes (..., H).

    ≙ reference fused_layernorm_residual_dropout_bias.h.  Pallas path gated
    on FLAGS_use_fused_ln (plus the global FLAGS_use_pallas_kernels + TPU
    backend); XLA fallback is bit-identical in fp32 and serves as the test
    oracle.
    """
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed (vary per step)")
    H = x.shape[-1]
    N = int(x.size // H)
    lead = x.shape[:-1]
    # the interpret arm only applies off-TPU: on TPU the global
    # FLAGS_use_pallas_kernels kill switch must stay authoritative
    use_kernel = (flag("FLAGS_use_fused_ln") and
                  (_use_pallas() or (flag("FLAGS_fused_ln_interpret") and
                                     jax.default_backend() != "tpu")) and
                  _pick_block_rows(N) is not None)
    if not use_kernel:
        return _dense_ln_residual_dropout(
            x, residual, ln_scale, ln_bias, bias,
            0 if dropout_seed is None else dropout_seed, dropout_p, eps)
    seed = (jnp.zeros((1,), jnp.uint32) if dropout_seed is None
            else jnp.asarray(dropout_seed, jnp.uint32).reshape(1))
    interpret = jax.default_backend() != "tpu"
    out2, rout2 = _fused_ln_core(
        x.reshape(N, H), residual.reshape(N, H), ln_scale, ln_bias, bias,
        seed, float(dropout_p), float(eps), interpret)
    return out2.reshape(lead + (H,)), rout2.reshape(lead + (H,))


# ---------------------------------------------------------------------------
# Fused AdamW update (≙ multi_tensor_adam / distributed_fused_lamb: one
# kernel sweep instead of one op-chain per tensor)
# ---------------------------------------------------------------------------

def _adamw_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref,
                  po_ref, mo_ref, vo_ref):
    lr, b1, b2, eps, wd, bc1, bc2 = (scal_ref[k] for k in range(7))
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * g * g
    mhat = m / bc1
    vhat = v / bc2
    p = p_ref[...].astype(jnp.float32)
    p = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
    po_ref[...] = p.astype(po_ref.dtype)
    mo_ref[...] = m.astype(mo_ref.dtype)
    vo_ref[...] = v.astype(vo_ref.dtype)


def fused_adamw_flat(p, g, m, v, step, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                     weight_decay=0.01, block=8192):
    """One Pallas sweep of the AdamW update over a flat (n,) buffer.

    Callers flatten-and-concat the param tree once (the reference's
    distributed_fused_lamb flattens into one contiguous grad buffer the same
    way), so the optimizer is a single kernel launch regardless of tensor
    count.  step is the 1-based step AFTER increment.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p.shape[0]
    pad = (-n) % block
    padded = [jnp.pad(t, (0, pad)) for t in (p, g, m, v)]
    rows = (n + pad) // block
    shaped = [t.reshape(rows, block) for t in padded]
    step_f = jnp.asarray(step, jnp.float32)
    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(beta1, jnp.float32),
                      jnp.asarray(beta2, jnp.float32),
                      jnp.asarray(eps, jnp.float32),
                      jnp.asarray(weight_decay, jnp.float32),
                      1.0 - jnp.asarray(beta1, jnp.float32) ** step_f,
                      1.0 - jnp.asarray(beta2, jnp.float32) ** step_f])
    rspec = pl.BlockSpec((1, block), lambda i: (i, 0))
    po, mo, vo = pl.pallas_call(
        _adamw_kernel, grid=(rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [rspec] * 4,
        out_specs=[rspec] * 3,
        out_shape=[jax.ShapeDtypeStruct((rows, block), p.dtype),
                   jax.ShapeDtypeStruct((rows, block), m.dtype),
                   jax.ShapeDtypeStruct((rows, block), v.dtype)],
        interpret=jax.default_backend() != "tpu",
    )(scal, *shaped)
    unpad = lambda t: t.reshape(-1)[:n]
    return unpad(po), unpad(mo), unpad(vo)
