"""Mixture-of-Experts: gating, capacity dispatch, expert parallelism.

Reference capability: expert parallelism via ``global_scatter``/``global_gather``
(python/paddle/distributed/utils.py:57,179 — ragged ncclSend/Recv loops keyed
by per-expert counts, operators/collective/global_scatter_op.cu.cc) plus
``alltoall`` (collective.py:1488).  The gating network itself lives in
downstream repos (SURVEY.md §2.4 EP row).

TPU-native design: XLA requires static shapes, so the ragged count-driven
exchange becomes **capacity-based dispatch** (GShard/Switch style): each
expert receives at most ``capacity`` tokens; dispatch/combine are one-hot
einsum contractions; expert layout rides a mesh axis and the cross-device
exchange is the all_to_all GSPMD infers from the sharding constraint on the
``(E, C, H)`` dispatched tensor (≙ the whole global_scatter/gather machinery).
Overflowed tokens pass through the residual connection (standard practice).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _resolve_capacity(T, E, k, capacity, capacity_factor):
    if capacity is None:
        capacity = int(math.ceil(k * T / E * capacity_factor))
        capacity = max(4, -(-capacity // 4) * 4)  # multiple of 4 for tiling
    return capacity


def _gating_rounds(logits, k, C, jitter_key):
    """The shared top-k selection loop (single source of truth for gating
    semantics — both the mask-building and index-building wrappers consume
    it).  Yields per-round (idx, pos, keep, gate) plus final (probs, ce_acc,
    denom): idx (T,) chosen expert, pos (T,) slot in that expert's buffer,
    keep (T,) within-capacity, gate (T,) raw selected prob."""
    T, E = logits.shape
    if jitter_key is not None:
        logits = logits + jax.random.uniform(jitter_key, logits.shape,
                                             logits.dtype, -1e-2, 1e-2)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)
    fill = jnp.zeros((E,), jnp.int32)
    masked = probs
    ce_acc = jnp.zeros((E,), jnp.float32)  # dispatched-token fractions
    denom = jnp.zeros((T,), jnp.float32)   # Σ of the k selected gate probs
    rounds = []
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)                    # (T,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)     # (T, E)
        # position of each token within its chosen expert's buffer
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot       # (T, E)
        pos = jnp.sum(pos_in_e * onehot, axis=-1) + fill[idx]  # (T,)
        keep = pos < C
        gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        rounds.append((idx, pos, keep, gate))
        fill = fill + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)
        ce_acc = ce_acc + jnp.mean(onehot.astype(jnp.float32), axis=0)
        denom = denom + gate
        masked = jnp.where(onehot.astype(bool), -jnp.inf, masked)
    return rounds, probs, ce_acc, denom


def topk_gating(logits, k: int = 2, capacity: Optional[int] = None,
                capacity_factor: float = 1.25, jitter_key=None):
    """Top-k gating with static per-expert capacity.

    Args:
      logits: (T, E) raw gate scores.
      k: experts per token (1 = Switch, 2 = GShard default).
      capacity: tokens per expert; default ceil(k * T / E * capacity_factor),
        rounded up to a multiple of 4 for TPU-friendly tiling.
    Returns:
      combine:  (T, E, C) float — combine weights (gate probs at slot).
      dispatch: (T, E, C) bool  — dispatch mask.
      aux_loss: scalar load-balancing loss (Switch §2.2: E * Σ_e m_e · c_e).
    """
    T, E = logits.shape
    C = _resolve_capacity(T, E, k, capacity, capacity_factor)
    rounds, probs, ce_acc, denom = _gating_rounds(logits, k, C, jitter_key)
    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), bool)
    for idx, pos, keep, gate in rounds:
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        slot = jax.nn.one_hot(pos, C, dtype=jnp.float32) * keep[:, None]
        contrib = onehot[:, :, None] * slot[:, None, :]
        combine = combine + gate[:, None, None] * contrib
        dispatch = dispatch | (contrib > 0)
    if k > 1:
        # GShard renormalization: selected gates sum to 1 over the chosen k
        # (k=1 keeps the raw prob — Switch convention)
        combine = combine / jnp.maximum(denom, 1e-9)[:, None, None]
    me = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(me * ce_acc / k)
    return combine, dispatch, aux_loss


def moe_dispatch(x, dispatch):
    """x: (T, H), dispatch: (T, E, C) → (E, C, H)."""
    return jnp.einsum("th,tec->ech", x.astype(jnp.float32),
                      dispatch.astype(jnp.float32)).astype(x.dtype)


def moe_combine(expert_out, combine, dtype=None):
    """expert_out: (E, C, H), combine: (T, E, C) → (T, H)."""
    out = jnp.einsum("ech,tec->th", expert_out.astype(jnp.float32), combine)
    return out.astype(dtype or expert_out.dtype)


def expert_ffn(expert_in, w1, b1, w2, b2, activation=jax.nn.gelu):
    """Per-expert FFN. expert_in: (E, C, H); w1: (E, H, I); w2: (E, I, H)."""
    dt = expert_in.dtype
    h = jnp.einsum("ech,ehi->eci", expert_in, w1.astype(dt)) + \
        b1.astype(dt)[:, None, :]
    h = activation(h)
    return jnp.einsum("eci,eih->ech", h, w2.astype(dt)) + \
        b2.astype(dt)[:, None, :]


def moe_ffn(x, gate_w, w1, b1, w2, b2, k: int = 2,
            capacity_factor: float = 1.25, mesh=None, expert_axis: str = "data",
            jitter_key=None, activation=jax.nn.gelu):
    """Full MoE FFN over tokens, with optional expert parallelism.

    x: (T, H) tokens.  Experts sharded over ``expert_axis`` when ``mesh`` is
    given: the sharding constraint on the (E, C, H) dispatched tensor makes
    GSPMD emit the token all_to_all (≙ global_scatter), and the constraint
    back to token layout after the expert FFN emits the reverse exchange
    (≙ global_gather).

    Returns (out (T, H), aux_loss).
    """
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # (T, E)
    combine, dispatch, aux = topk_gating(logits, k=k,
                                         capacity_factor=capacity_factor,
                                         jitter_key=jitter_key)
    expert_in = moe_dispatch(x, dispatch)                        # (E, C, H)
    if mesh is not None and mesh.shape.get(expert_axis, 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = NamedSharding(mesh, P(expert_axis, None, None))
        expert_in = lax.with_sharding_constraint(expert_in, spec)
    expert_out = expert_ffn(expert_in, w1, b1, w2, b2, activation)
    if mesh is not None and mesh.shape.get(expert_axis, 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        expert_out = lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(expert_axis, None, None)))
    out = moe_combine(expert_out, combine, dtype=x.dtype)
    return out, aux


# ---------------------------------------------------------------------------
# Index-based dispatch (gather/scatter) — O(T·H) data movement instead of the
# GShard einsum's O(T·E·C·H) matmul.  At ERNIE bench shapes (T=4096, E=8,
# C≈1280, H=768) the einsum dispatch+combine costs ~2x the expert FFN's own
# FLOPs and materializes (T, E, C) fp32 masks (~170MB); the index path moves
# each token once.  ≙ the reference's global_scatter/global_gather, which is
# likewise an index exchange, not a matmul
# (operators/collective/global_scatter_op.cu.cc).
# ---------------------------------------------------------------------------

def topk_gating_indices(logits, k: int = 2, capacity: Optional[int] = None,
                        capacity_factor: float = 1.25, jitter_key=None):
    """Top-k gating that returns slot indices instead of (T, E, C) masks.

    Returns:
      expert_idx (T, k) int32 — chosen expert per token/choice
      slot_idx   (T, k) int32 — position in that expert's buffer; == C when
        the token overflowed capacity (dropped)
      gates      (T, k) f32  — combine weights (GShard-renormalized over the
        selected k when k > 1; zero for dropped slots)
      aux_loss   scalar load-balance loss (same formula as topk_gating)
      capacity   the static per-expert capacity C used
    """
    T, E = logits.shape
    C = _resolve_capacity(T, E, k, capacity, capacity_factor)
    rounds, probs, ce_acc, denom = _gating_rounds(logits, k, C, jitter_key)
    expert_idx = jnp.stack([r[0].astype(jnp.int32) for r in rounds], axis=1)
    slot_idx = jnp.stack([jnp.where(r[2], r[1], C).astype(jnp.int32)
                          for r in rounds], axis=1)
    gate_k = jnp.stack([jnp.where(r[2], r[3], 0.0) for r in rounds], axis=1)
    if k > 1:
        # same denominator as topk_gating: all selected probs incl. dropped
        gate_k = gate_k / jnp.maximum(denom, 1e-9)[:, None]
    me = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(me * ce_acc / k)
    return expert_idx, slot_idx, gate_k, aux_loss, C


def moe_ffn_indices(x, gate_w, w1, b1, w2, b2, k: int = 2,
                    capacity_factor: float = 1.25, mesh=None,
                    expert_axis: str = "data", jitter_key=None,
                    activation=jax.nn.gelu):
    """moe_ffn with gather/scatter dispatch — numerically equivalent to the
    einsum path (see tests), O(T·H) data movement."""
    T, H = x.shape
    E = gate_w.shape[-1]
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    expert_idx, slot_idx, gates, aux, C = topk_gating_indices(
        logits, k=k, capacity_factor=capacity_factor, jitter_key=jitter_key)

    # flat slot id e*C + c; dropped slots land in a trash row at E*C
    flat = jnp.where(slot_idx < C, expert_idx * C + slot_idx, E * C)  # (T, k)
    buf = jnp.zeros((E * C + 1, H), x.dtype)
    # slots are unique by construction (cumsum positions), so .set is exact;
    # only the trash row sees duplicate writes (value irrelevant)
    buf = buf.at[flat.reshape(-1)].set(
        jnp.repeat(x, k, axis=0), unique_indices=False)
    expert_in = buf[:E * C].reshape(E, C, H)
    if mesh is not None and mesh.shape.get(expert_axis, 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        expert_in = lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(expert_axis, None, None)))
    expert_out = expert_ffn(expert_in, w1, b1, w2, b2, activation)
    if mesh is not None and mesh.shape.get(expert_axis, 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        expert_out = lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(expert_axis, None, None)))
    out_flat = jnp.concatenate(
        [expert_out.reshape(E * C, H), jnp.zeros((1, H), expert_out.dtype)])
    picked = out_flat[flat]                                   # (T, k, H)
    out = jnp.sum(picked.astype(jnp.float32)
                  * gates[..., None], axis=1).astype(x.dtype)
    return out, aux


def moe_ffn_gather(x, gate_w, w1, b1, w2, b2, k: int = 2,
                   activation=jax.nn.gelu):
    """Capacity-FREE MoE FFN via per-token expert-weight gather — the
    inference/decode dispatch (≙ the reference's no-drop serving path).

    No (E, C, H) buffer and no wasted rows: expert FLOPs are exactly O(k·T)
    at the price of gathering k weight slices per token, which wins when T
    is small (the per-token decode loop).  Numerically equal to
    ``moe_ffn_indices`` at a no-drop capacity (same renormalized top-k
    combine weights); returns the output only — the aux load-balance loss is
    a training quantity.
    """
    T, H = x.shape
    logits32 = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits32, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, k)              # (T, k)
    if k > 1:  # GShard renormalization over the selected k
        gates = top_p / jnp.maximum(jnp.sum(top_p, -1, keepdims=True), 1e-9)
    else:
        gates = top_p
    w1s = jnp.take(w1, top_e, axis=0)                   # (T, k, H, I)
    b1s = jnp.take(b1, top_e, axis=0)                   # (T, k, I)
    h1 = activation(jnp.einsum("th,tkhi->tki", x, w1s.astype(x.dtype))
                    + b1s.astype(x.dtype))
    w2s = jnp.take(w2, top_e, axis=0)                   # (T, k, I, H)
    b2s = jnp.take(b2, top_e, axis=0)                   # (T, k, H)
    out = jnp.einsum("tki,tkih->tkh", h1, w2s.astype(x.dtype)) \
        + b2s.astype(x.dtype)
    # combine in fp32 like moe_ffn_indices — the numerical-equality contract
    # must hold at bf16 compute dtype too
    return jnp.sum(out.astype(jnp.float32) * gates[..., None],
                   axis=1).astype(x.dtype)
