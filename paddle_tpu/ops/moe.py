"""Mixture-of-Experts: gating, capacity dispatch, expert parallelism.

Reference capability: expert parallelism via ``global_scatter``/``global_gather``
(python/paddle/distributed/utils.py:57,179 — ragged ncclSend/Recv loops keyed
by per-expert counts, operators/collective/global_scatter_op.cu.cc) plus
``alltoall`` (collective.py:1488).  The gating network itself lives in
downstream repos (SURVEY.md §2.4 EP row).

TPU-native design: XLA requires static shapes, so the ragged count-driven
exchange becomes **capacity-based dispatch** (GShard/Switch style): each
expert receives at most ``capacity`` tokens; dispatch/combine are one-hot
einsum contractions; expert layout rides a mesh axis and the cross-device
exchange is the all_to_all GSPMD infers from the sharding constraint on the
``(E, C, H)`` dispatched tensor (≙ the whole global_scatter/gather machinery).
Overflowed tokens pass through the residual connection (standard practice).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def topk_gating(logits, k: int = 2, capacity: Optional[int] = None,
                capacity_factor: float = 1.25, jitter_key=None):
    """Top-k gating with static per-expert capacity.

    Args:
      logits: (T, E) raw gate scores.
      k: experts per token (1 = Switch, 2 = GShard default).
      capacity: tokens per expert; default ceil(k * T / E * capacity_factor),
        rounded up to a multiple of 4 for TPU-friendly tiling.
    Returns:
      combine:  (T, E, C) float — combine weights (gate probs at slot).
      dispatch: (T, E, C) bool  — dispatch mask.
      aux_loss: scalar load-balancing loss (Switch §2.2: E * Σ_e m_e · c_e).
    """
    T, E = logits.shape
    if capacity is None:
        capacity = int(math.ceil(k * T / E * capacity_factor))
        capacity = max(4, -(-capacity // 4) * 4)
    C = capacity
    if jitter_key is not None:
        logits = logits + jax.random.uniform(jitter_key, logits.shape,
                                             logits.dtype, -1e-2, 1e-2)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)

    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), bool)
    # running number of tokens already assigned to each expert
    fill = jnp.zeros((E,), jnp.int32)
    masked = probs
    ce_acc = jnp.zeros((E,), jnp.float32)  # dispatched-token fractions
    denom = jnp.zeros((T,), jnp.float32)   # Σ of the k selected gate probs
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)                    # (T,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)     # (T, E)
        # position of each token within its chosen expert's buffer
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot       # (T, E)
        pos = jnp.sum(pos_in_e * onehot, axis=-1) + fill[idx]  # (T,)
        keep = pos < C
        gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        slot = jax.nn.one_hot(pos, C, dtype=jnp.float32) * keep[:, None]
        contrib = onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        combine = combine + gate[:, None, None] * contrib
        dispatch = dispatch | (contrib > 0)
        fill = fill + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)
        ce_acc = ce_acc + jnp.mean(onehot.astype(jnp.float32), axis=0)
        denom = denom + gate
        masked = jnp.where(onehot.astype(bool), -jnp.inf, masked)
    if k > 1:
        # GShard renormalization: selected gates sum to 1 over the chosen k
        # (k=1 keeps the raw prob — Switch convention)
        combine = combine / jnp.maximum(denom, 1e-9)[:, None, None]
    me = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(me * ce_acc / k)
    return combine, dispatch, aux_loss


def moe_dispatch(x, dispatch):
    """x: (T, H), dispatch: (T, E, C) → (E, C, H)."""
    return jnp.einsum("th,tec->ech", x.astype(jnp.float32),
                      dispatch.astype(jnp.float32)).astype(x.dtype)


def moe_combine(expert_out, combine, dtype=None):
    """expert_out: (E, C, H), combine: (T, E, C) → (T, H)."""
    out = jnp.einsum("ech,tec->th", expert_out.astype(jnp.float32), combine)
    return out.astype(dtype or expert_out.dtype)


def expert_ffn(expert_in, w1, b1, w2, b2, activation=jax.nn.gelu):
    """Per-expert FFN. expert_in: (E, C, H); w1: (E, H, I); w2: (E, I, H)."""
    dt = expert_in.dtype
    h = jnp.einsum("ech,ehi->eci", expert_in, w1.astype(dt)) + \
        b1.astype(dt)[:, None, :]
    h = activation(h)
    return jnp.einsum("eci,eih->ech", h, w2.astype(dt)) + \
        b2.astype(dt)[:, None, :]


def moe_ffn(x, gate_w, w1, b1, w2, b2, k: int = 2,
            capacity_factor: float = 1.25, mesh=None, expert_axis: str = "data",
            jitter_key=None, activation=jax.nn.gelu):
    """Full MoE FFN over tokens, with optional expert parallelism.

    x: (T, H) tokens.  Experts sharded over ``expert_axis`` when ``mesh`` is
    given: the sharding constraint on the (E, C, H) dispatched tensor makes
    GSPMD emit the token all_to_all (≙ global_scatter), and the constraint
    back to token layout after the expert FFN emits the reverse exchange
    (≙ global_gather).

    Returns (out (T, H), aux_loss).
    """
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # (T, E)
    combine, dispatch, aux = topk_gating(logits, k=k,
                                         capacity_factor=capacity_factor,
                                         jitter_key=jitter_key)
    expert_in = moe_dispatch(x, dispatch)                        # (E, C, H)
    if mesh is not None and mesh.shape.get(expert_axis, 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = NamedSharding(mesh, P(expert_axis, None, None))
        expert_in = lax.with_sharding_constraint(expert_in, spec)
    expert_out = expert_ffn(expert_in, w1, b1, w2, b2, activation)
    if mesh is not None and mesh.shape.get(expert_axis, 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        expert_out = lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(expert_axis, None, None)))
    out = moe_combine(expert_out, combine, dtype=x.dtype)
    return out, aux
