"""Sequence/context-parallel attention: ring attention + Ulysses.

The reference snapshot has NO sequence/context parallelism (SURVEY.md §2.4:
"SP/CP/ring-attention/Ulysses — ABSENT"); its longest-context assets are the
fused FMHA (operators/fused/fmha_ref.h:57) and block-sparse attention
(python/paddle/nn/functional/sparse_attention.py).  This module designs
long-context from first principles for TPU:

- **Ring attention** (`ring_attention`): sequence is sharded over a mesh axis
  (the "sep" axis of the hybrid topology); K/V blocks rotate around the ring
  with ``jax.lax.ppermute`` while each device accumulates its queries'
  online-softmax partials.  Memory per device is O(L/sp); comm rides ICI
  neighbor links (a ppermute per step overlaps with the block matmul under
  XLA's async collectives).
- **Ulysses** (`ulysses_attention`): all_to_all swaps the sharded dimension
  from sequence to heads, runs dense/flash attention on the full sequence with
  H/sp local heads, then swaps back.  Two all_to_alls per call; preferable
  when heads divide the sep degree and L is moderate.

Both are pure SPMD functions meant to run inside ``shard_map`` over the
hybrid mesh.  Ring attention differentiates through a HAND-WRITTEN
custom_vjp (flash-bwd identities; dk/dv travel with their k/v block around
the ring) — plain JAX AD of the forward scan would stack every received
k/v block as a residual, i.e. the full global K/V on every device, which is
the exact memory blow-up ring attention exists to avoid.  Per-device memory
is O(L/sp) in both directions and the L×L score matrix never exists.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _local_scores(q, k, scale):
    """q: (B, Lq, H, D), k: (B, Lk, H, D) → (B, H, Lq, Lk) fp32 scores."""
    return jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale


def _varying(x, axis_name):
    # new-style shard_map typing: scan carries must keep the same
    # varying-axes set each iteration
    try:
        return lax.pcast(x, (axis_name,), to="varying")
    except (AttributeError, TypeError):
        return x


def _ring_perm(sp):
    return [(i, (i + 1) % sp) for i in range(sp)]


def _ring_fwd_pass(q, k, v, axis_name, causal, scale):
    """One full ring: returns (out fp32 (B,H,Lc,D), lse (B,H,Lc,1))."""
    B, Lc, H, D = q.shape
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    row_g = idx * Lc + lax.broadcasted_iota(jnp.int32, (Lc, Lc), 0)

    def accumulate(k_blk, v_blk, blk_idx, m, l, acc):
        s = _local_scores(q, k_blk, scale)  # (B,H,Lc,Lc)
        if causal:
            col_g = blk_idx * Lc + lax.broadcasted_iota(jnp.int32, (Lc, Lc), 1)
            s = jnp.where(col_g <= row_g, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # (B,H,Lc,1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhlm,bmhd->bhld", p, v_blk.astype(jnp.float32))
        return m_new, l_new, acc_new

    def maybe(k_blk, v_blk, blk_idx, m, l, acc):
        if causal:
            # skip blocks entirely in the masked future (blk_idx > idx):
            # on average (sp-1)/2 of sp blocks — halves the wasted FLOPs.
            return lax.cond(
                blk_idx <= idx,
                lambda a, b, c_, d, e: accumulate(a, b, blk_idx, c_, d, e),
                lambda a, b, c_, d, e: (c_, d, e),
                k_blk, v_blk, m, l, acc)
        return accumulate(k_blk, v_blk, blk_idx, m, l, acc)

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        blk_idx = (idx - t) % sp       # home rank of this block after t hops
        m, l, acc = maybe(k_blk, v_blk, blk_idx, m, l, acc)
        # rotate k/v to the next rank (ring over ICI neighbors)
        perm = _ring_perm(sp)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = _varying(jnp.full((B, H, Lc, 1), _NEG_INF, jnp.float32), axis_name)
    l0 = _varying(jnp.zeros((B, H, Lc, 1), jnp.float32), axis_name)
    acc0 = _varying(jnp.zeros((B, H, Lc, D), jnp.float32), axis_name)
    # sp-1 (compute + rotate) steps, then a final compute with no rotation —
    # the last ppermute's payload would otherwise be exchanged and discarded
    if sp > 1:
        (k_last, v_last, m, l, acc), _ = lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(sp - 1))
    else:
        k_last, v_last, m, l, acc = k, v, m0, l0, acc0
    last_idx = (idx - (sp - 1)) % sp
    m, l, acc = maybe(k_last, v_last, last_idx, m, l, acc)
    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe
    lse = m + jnp.log(l_safe)
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention(q, k, v, axis_name, causal, scale):
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal, scale)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B,Lc,H,D)


def _ring_attention_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, scale)
    # residuals are O(L/sp) per device: inputs + output + softmax stats.
    # JAX AD of the fwd scan would instead stack every RECEIVED k/v block
    # ((sp-1) x shard = the full global K/V on every device) — the exact
    # memory blow-up ring attention exists to avoid.
    return (out.transpose(0, 2, 1, 3).astype(q.dtype),
            (q, k, v, out, lse))


def _ring_attention_bwd(axis_name, causal, scale, res, g):
    """Backward re-runs the ring: k/v blocks rotate again, each device
    accumulates its local dq, while dk/dv partials travel WITH their block
    and come home after a final hop.  Per-device memory stays O(L/sp)."""
    q, k, v, out, lse = res
    B, Lc, H, D = q.shape
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    row_g = idx * Lc + lax.broadcasted_iota(jnp.int32, (Lc, Lc), 0)

    do = g.transpose(0, 2, 1, 3).astype(jnp.float32)        # (B,H,Lc,D)
    # delta_i = sum_d dO_i * O_i  (flash bwd identity)
    delta = jnp.sum(do * out, axis=-1, keepdims=True)       # (B,H,Lc,1)
    qf = q.astype(jnp.float32)

    def block_grads(k_blk, v_blk, blk_idx):
        s = _local_scores(q, k_blk, scale)                  # (B,H,Lc,Lc)
        if causal:
            col_g = blk_idx * Lc + lax.broadcasted_iota(jnp.int32, (Lc, Lc), 1)
            s = jnp.where(col_g <= row_g, s, _NEG_INF)
        p = jnp.exp(s - lse)                                # (B,H,Lc,Lc)
        kf = k_blk.astype(jnp.float32)
        vf = v_blk.astype(jnp.float32)
        dv_b = jnp.einsum("bhlm,bhld->bmhd", p, do)         # (B,Lc,H,D)
        dp = jnp.einsum("bhld,bmhd->bhlm", do, vf)
        ds = p * (dp - delta) * scale
        dq_b = jnp.einsum("bhlm,bmhd->blhd", ds, kf)        # (B,Lc,H,D)
        dk_b = jnp.einsum("bhlm,blhd->bmhd", ds, qf)        # (B,Lc,H,D)
        return dq_b, dk_b, dv_b

    def maybe_grads(k_blk, v_blk, blk_idx):
        if causal:
            zero = _varying(jnp.zeros((B, Lc, H, D), jnp.float32), axis_name)
            return lax.cond(
                blk_idx <= idx,
                lambda a, b: block_grads(a, b, blk_idx),
                lambda a, b: (zero, zero, zero),
                k_blk, v_blk)
        return block_grads(k_blk, v_blk, blk_idx)

    def step(carry, t):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        blk_idx = (idx - t) % sp
        dq_b, dk_b, dv_b = maybe_grads(k_blk, v_blk, blk_idx)
        dq = dq + dq_b
        dk_blk = dk_blk + dk_b
        dv_blk = dv_blk + dv_b
        perm = _ring_perm(sp)
        return (lax.ppermute(k_blk, axis_name, perm),
                lax.ppermute(v_blk, axis_name, perm),
                lax.ppermute(dk_blk, axis_name, perm),
                lax.ppermute(dv_blk, axis_name, perm), dq), None

    zero = jnp.zeros((B, Lc, H, D), jnp.float32)
    dq0 = _varying(zero, axis_name)
    dk0 = _varying(zero, axis_name)
    dv0 = _varying(zero, axis_name)
    if sp > 1:
        (k_last, v_last, dk_t, dv_t, dq), _ = lax.scan(
            step, (k, v, dk0, dv0, dq0), jnp.arange(sp - 1))
    else:
        k_last, v_last, dk_t, dv_t, dq = k, v, dk0, dv0, dq0
    last_idx = (idx - (sp - 1)) % sp
    dq_b, dk_b, dv_b = maybe_grads(k_last, v_last, last_idx)
    dq = dq + dq_b
    dk_t = dk_t + dk_b
    dv_t = dv_t + dv_b
    if sp > 1:
        # the block at step sp-1 sits one hop short of home: block j rests on
        # rank j-1, so one more rotation returns every dk/dv to its owner
        perm = _ring_perm(sp)
        dk_t = lax.ppermute(dk_t, axis_name, perm)
        dv_t = lax.ppermute(dv_t, axis_name, perm)
    return (dq.astype(q.dtype), dk_t.astype(k.dtype), dv_t.astype(v.dtype))


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale=None):
    """Ring flash attention over sequence shards.

    Args:
      q, k, v: local shards ``(B, L_local, H, D)`` — the global sequence is
        the concatenation of shards along the ``axis_name`` mesh axis.
      axis_name: mesh axis the sequence is sharded over (the hybrid "sep"
        axis). Must be called inside shard_map/pjit over that axis.
      causal: apply a causal mask in *global* sequence coordinates.
    Returns:
      local output shard (B, L_local, H, D), same dtype as q.

    Differentiable via a custom ring backward (flash bwd identities with
    dk/dv traveling alongside their k/v block) — per-device memory is
    O(L/sp) in BOTH directions; plain JAX AD of the forward scan would stack
    every received k/v shard (O(L) per device).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    try:
        scale = float(scale)  # static: goes through nondiff_argnums
    except TypeError:
        # traced/learned scale (e.g. temperature): absorb into q so the
        # gradient flows through the product instead of nondiff_argnums
        q = q * jnp.asarray(scale, q.dtype)
        scale = 1.0
    return _ring_attention(q, k, v, axis_name, causal, scale)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      scale=None, attention_fn=None):
    """Ulysses sequence parallelism: head-scatter all_to_all.

    Local shards (B, L_local, H, D) with H divisible by the sep degree.
    all_to_all re-shards from sequence to heads, computes attention over the
    FULL sequence with H/sp heads per device, then re-shards back.
    """
    from .attention import flash_attention
    sp = lax.psum(1, axis_name)
    H = q.shape[2]
    if H % sp != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({H}) divisible by the "
            f"'{axis_name}' mesh-axis size ({sp}); use ring attention instead")
    if attention_fn is None:
        # flash_attention itself falls back to dense off-TPU / odd shapes
        attention_fn = partial(flash_attention, causal=causal, scale=scale)

    def seq_to_heads(x):  # (B, Lc, H, D) -> (B, L, H/sp, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # inverse
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_fn(qh, kh, vh)
    return heads_to_seq(out)


def sequence_parallel_attention(q, k, v, axis_name: str = "sep",
                                causal: bool = False, scale=None,
                                mode: str = "ring"):
    """Dispatch between ring and Ulysses context parallelism.

    Ulysses needs ``H % sp == 0`` and moves full K/V twice; ring moves K/V
    sp-1 times in L/sp blocks but keeps everything neighbor-local. Default
    ring (scales to any head count and rides ICI)."""
    if mode == "ulysses":
        return ulysses_attention(q, k, v, axis_name, causal=causal, scale=scale)
    if mode != "ring":
        raise ValueError(f"unknown sequence-parallel mode {mode!r}")
    return ring_attention(q, k, v, axis_name, causal=causal, scale=scale)
