"""Sequence/context-parallel attention: ring attention + Ulysses.

The reference snapshot has NO sequence/context parallelism (SURVEY.md §2.4:
"SP/CP/ring-attention/Ulysses — ABSENT"); its longest-context assets are the
fused FMHA (operators/fused/fmha_ref.h:57) and block-sparse attention
(python/paddle/nn/functional/sparse_attention.py).  This module designs
long-context from first principles for TPU:

- **Ring attention** (`ring_attention`): sequence is sharded over a mesh axis
  (the "sep" axis of the hybrid topology); K/V blocks rotate around the ring
  with ``jax.lax.ppermute`` while each device accumulates its queries'
  online-softmax partials.  Memory per device is O(L/sp); comm rides ICI
  neighbor links (a ppermute per step overlaps with the block matmul under
  XLA's async collectives).
- **Ulysses** (`ulysses_attention`): all_to_all swaps the sharded dimension
  from sequence to heads, runs dense/flash attention on the full sequence with
  H/sp local heads, then swaps back.  Two all_to_alls per call; preferable
  when heads divide the sep degree and L is moderate.

Both are pure SPMD functions meant to run inside ``shard_map`` over the
hybrid mesh, and are differentiable via JAX AD (the ring scan's backward
re-runs the ring in reverse — the L×L score matrix is never materialized).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _local_scores(q, k, scale):
    """q: (B, Lq, H, D), k: (B, Lk, H, D) → (B, H, Lq, Lk) fp32 scores."""
    return jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale=None):
    """Ring flash attention over sequence shards.

    Args:
      q, k, v: local shards ``(B, L_local, H, D)`` — the global sequence is
        the concatenation of shards along the ``axis_name`` mesh axis.
      axis_name: mesh axis the sequence is sharded over (the hybrid "sep"
        axis). Must be called inside shard_map/pjit over that axis.
      causal: apply a causal mask in *global* sequence coordinates.
    Returns:
      local output shard (B, L_local, H, D), same dtype as q.
    """
    B, Lc, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    row_g = idx * Lc + lax.broadcasted_iota(jnp.int32, (Lc, Lc), 0)

    def accumulate(k_blk, v_blk, blk_idx, m, l, acc):
        s = _local_scores(q, k_blk, scale)  # (B,H,Lc,Lc)
        if causal:
            col_g = blk_idx * Lc + lax.broadcasted_iota(jnp.int32, (Lc, Lc), 1)
            s = jnp.where(col_g <= row_g, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # (B,H,Lc,1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhlm,bmhd->bhld", p, v_blk.astype(jnp.float32))
        return m_new, l_new, acc_new

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        # which global chunk this k/v block came from after t rotations
        blk_idx = (idx - t) % sp
        if causal:
            # skip blocks entirely in the masked future (blk_idx > idx):
            # on average (sp-1)/2 of sp blocks — halves the wasted FLOPs.
            # (Load stays imbalanced across ranks within a step; a zigzag
            # block order would fix that too — future work.)
            m, l, acc = lax.cond(
                blk_idx <= idx,
                lambda a, b, c_, d, e: accumulate(a, b, blk_idx, c_, d, e),
                lambda a, b, c_, d, e: (c_, d, e),
                k_blk, v_blk, m, l, acc)
        else:
            m, l, acc = accumulate(k_blk, v_blk, blk_idx, m, l, acc)
        # rotate k/v to the next rank (ring over ICI neighbors)
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    # accumulators start device-varying over the ring axis (new-style shard_map
    # typing: scan carries must keep the same varying-axes set each iteration)
    def _varying(x):
        try:
            return lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            return x
    m0 = _varying(jnp.full((B, H, Lc, 1), _NEG_INF, jnp.float32))
    l0 = _varying(jnp.zeros((B, H, Lc, 1), jnp.float32))
    acc0 = _varying(jnp.zeros((B, H, Lc, D), jnp.float32))
    # sp-1 (compute + rotate) steps, then a final compute with no rotation —
    # the last ppermute's payload would otherwise be exchanged and discarded
    if sp > 1:
        (k_last, v_last, m, l, acc), _ = lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(sp - 1))
    else:
        k_last, v_last, m, l, acc = k, v, m0, l0, acc0
    last_idx = (idx - (sp - 1)) % sp
    if causal and sp > 1:
        m, l, acc = lax.cond(
            last_idx <= idx,
            lambda a, b, c_, d, e: accumulate(a, b, last_idx, c_, d, e),
            lambda a, b, c_, d, e: (c_, d, e),
            k_last, v_last, m, l, acc)
    else:
        m, l, acc = accumulate(k_last, v_last, last_idx, m, l, acc)
    out = acc / jnp.maximum(l, 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B,Lc,H,D)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      scale=None, attention_fn=None):
    """Ulysses sequence parallelism: head-scatter all_to_all.

    Local shards (B, L_local, H, D) with H divisible by the sep degree.
    all_to_all re-shards from sequence to heads, computes attention over the
    FULL sequence with H/sp heads per device, then re-shards back.
    """
    from .attention import flash_attention
    sp = lax.psum(1, axis_name)
    H = q.shape[2]
    if H % sp != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({H}) divisible by the "
            f"'{axis_name}' mesh-axis size ({sp}); use ring attention instead")
    if attention_fn is None:
        # flash_attention itself falls back to dense off-TPU / odd shapes
        attention_fn = partial(flash_attention, causal=causal, scale=scale)

    def seq_to_heads(x):  # (B, Lc, H, D) -> (B, L, H/sp, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # inverse
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_fn(qh, kh, vh)
    return heads_to_seq(out)


def sequence_parallel_attention(q, k, v, axis_name: str = "sep",
                                causal: bool = False, scale=None,
                                mode: str = "ring"):
    """Dispatch between ring and Ulysses context parallelism.

    Ulysses needs ``H % sp == 0`` and moves full K/V twice; ring moves K/V
    sp-1 times in L/sp blocks but keeps everything neighbor-local. Default
    ring (scales to any head count and rides ICI)."""
    if mode == "ulysses":
        return ulysses_attention(q, k, v, axis_name, causal=causal, scale=scale)
    if mode != "ring":
        raise ValueError(f"unknown sequence-parallel mode {mode!r}")
    return ring_attention(q, k, v, axis_name, causal=causal, scale=scale)
