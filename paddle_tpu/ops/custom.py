"""Custom-operator registration (reference: framework/custom_operator.cc,
python/paddle/utils/cpp_extension — the user-facing "bring your own kernel"
runtime).

TPU-native design: a custom op is a pure jnp/Pallas function of raw arrays.
Registration wires it into the framework exactly like a built-in:

- dispatched through the eager ``apply`` (tape Node recorded, AMP lists,
  nan/inf checks, hooks all apply);
- traceable under ``jax.jit`` (the op IS jax-traceable code — the reference
  needs a compiled .so per device; here Mosaic compiles Pallas kernels for
  TPU at trace time);
- optional custom backward installed as a ``jax.custom_vjp`` with the
  reference grad-op convention: ``backward(grads, inputs, outputs)`` sees
  dOut, X, Out and returns dX per differentiable input (≙ the GradOpMaker
  contract: grad kernels take {X, Out, Out@GRAD} → X@GRAD).

Example::

    @custom_op(backward=lambda g, ins, outs: (g[0] * 2.0,))
    def double(x):
        return x * 2.0

    y = double(paddle.to_tensor([1.0]))        # eager, taped
    jax.jit(lambda a: double._raw(a))(...)      # inside jit
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Sequence

import jax

__all__ = ["register_op", "custom_op", "get_op", "list_ops", "CustomOp"]

_REGISTRY: Dict[str, "CustomOp"] = {}


class CustomOp:
    """A registered custom operator.

    ``forward``: pure function of raw arrays (jnp/Pallas), may return one
    array or a tuple.  ``backward(grads, inputs, outputs)``: receives the
    output cotangents tuple, the primal inputs tuple and the primal outputs
    tuple; returns one gradient per input (None → zero).
    """

    def __init__(self, name: str, forward: Callable,
                 backward: Optional[Callable] = None):
        self.name = name
        self.forward = forward
        self.backward = backward
        self._raw = self._build_raw()

    def _build_raw(self):
        fwd = self.forward
        if self.backward is None:
            return fwd  # native jax AD differentiates straight through

        user_bwd = self.backward

        @jax.custom_vjp
        def op(*args):
            return fwd(*args)

        def op_fwd(*args):
            outs = fwd(*args)
            return outs, (args, outs)

        def op_bwd(res, gs):
            args, outs = res
            outs_t = outs if isinstance(outs, tuple) else (outs,)
            gs_t = gs if isinstance(gs, tuple) else (gs,)
            grads = user_bwd(gs_t, args, outs_t)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            if len(grads) > len(args):
                raise ValueError(
                    f"custom op {self.name!r}: backward returned {len(grads)} "
                    f"gradients for {len(args)} inputs")
            import jax.numpy as jnp
            filled = tuple(
                jnp.zeros_like(a) if g is None else
                jnp.asarray(g).astype(a.dtype).reshape(a.shape)
                for g, a in zip(list(grads) + [None] * (len(args) - len(grads)),
                                args))
            return filled

        op.defvjp(op_fwd, op_bwd)
        return op

    def __call__(self, *args, **kwargs):
        from ..core.tensor import apply
        if kwargs:
            raw = functools.partial(self._raw, **kwargs) \
                if self.backward is None else None
            if raw is None:
                raise ValueError(
                    f"custom op {self.name!r} with a custom backward takes "
                    f"positional tensor args only (close over statics when "
                    f"registering)")
            return apply(raw, *args, name=self.name)
        return apply(self._raw, *args, name=self.name)

    def __repr__(self):
        return (f"CustomOp({self.name!r}, "
                f"backward={'custom' if self.backward else 'autodiff'})")


def register_op(name: str, forward: Callable,
                backward: Optional[Callable] = None) -> CustomOp:
    """Register (or replace) a custom op under ``name``.

    Reference analog: RegisterOperatorWithMetaInfo (custom_operator.cc) —
    but where the reference demands a compiled kernel per device type, any
    jax-traceable function here runs on every XLA backend, and a Pallas
    ``pallas_call`` inside ``forward`` becomes a real TPU kernel.
    """
    op = CustomOp(name, forward, backward)
    _REGISTRY[name] = op
    return op


def custom_op(fn=None, *, name: Optional[str] = None,
              backward: Optional[Callable] = None):
    """Decorator form of :func:`register_op`."""
    def deco(f):
        return register_op(name or f.__name__, f, backward)
    if fn is not None:
        return deco(fn)
    return deco


def get_op(name: str) -> CustomOp:
    if name not in _REGISTRY:
        raise KeyError(f"no custom op registered under {name!r}; "
                       f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_ops() -> Sequence[str]:
    return sorted(_REGISTRY)
