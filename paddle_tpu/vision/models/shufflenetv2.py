"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py —
shuffle_net_v2_x0_25 … x2_0, swish variant).

Channel shuffle is a reshape-transpose-reshape — pure data movement XLA
folds into the surrounding convs.
"""

from __future__ import annotations

from ... import nn


def _channel_shuffle(x, groups):
    import paddle_tpu as paddle
    B, C, H, W = x.shape
    x = paddle.reshape(x, [B, groups, C // groups, H, W])
    x = paddle.transpose(x, [0, 2, 1, 3, 4])
    return paddle.reshape(x, [B, C, H, W])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch // 2, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
                nn.Conv2D(branch, branch, 3, stride=1, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act))
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
                nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act))

    def forward(self, x):
        import paddle_tpu as paddle
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_STAGE_OUT = {
    "0.25": (24, 24, 48, 96, 512), "0.33": (24, 32, 64, 128, 512),
    "0.5": (24, 48, 96, 192, 1024), "1.0": (24, 116, 232, 464, 1024),
    "1.5": (24, 176, 352, 704, 1024), "2.0": (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        key = scale if isinstance(scale, str) else format(float(scale), "g")
        key = {"1": "1.0", "2": "2.0"}.get(key, key)
        if key not in _STAGE_OUT:
            raise ValueError(f"unsupported scale {scale!r}; "
                             f"choose from {sorted(_STAGE_OUT)}")
        chs = _STAGE_OUT[key]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chs[0]), _act(act))
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        stages = []
        in_ch = chs[0]
        for out_ch, n in zip(chs[1:4], _REPEATS):
            stages.append(_InvertedResidual(in_ch, out_ch, 2, act))
            for _ in range(n - 1):
                stages.append(_InvertedResidual(out_ch, out_ch, 1, act))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, chs[4], 1, bias_attr=False),
            nn.BatchNorm2D(chs[4]), _act(act))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are unavailable (zero-egress "
                         "build); load a local state_dict instead")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet("0.25", "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet("0.33", "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet("0.5", "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet("1.0", "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet("1.5", "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet("2.0", "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet("1.0", "swish", pretrained, **kwargs)
