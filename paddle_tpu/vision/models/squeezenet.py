"""SqueezeNet (reference: python/paddle/vision/models/squeezenet.py —
squeezenet1_0 / squeezenet1_1)."""

from __future__ import annotations

from ... import nn


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, expand1, expand3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze, expand1, 1)
        self.expand3 = nn.Conv2D(squeeze, expand3, 3, padding=1)

    def forward(self, x):
        import paddle_tpu as paddle
        s = self.relu(self.squeeze(x))
        return paddle.concat([self.relu(self.expand1(s)),
                              self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(512, 64, 256, 256))
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1), nn.ReLU(),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        else:
            raise ValueError(f"unsupported version {version!r}")
        if num_classes > 0:
            self.drop = nn.Dropout(0.5)
            self.final_conv = nn.Conv2D(512, num_classes, 1)
            self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.relu(self.final_conv(self.drop(x)))
        if self.with_pool:
            x = self.pool(x)
            if self.num_classes > 0:
                x = x.flatten(1)
        return x


def _squeezenet(version, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are unavailable (zero-egress "
                         "build); load a local state_dict instead")
    return SqueezeNet(version, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
