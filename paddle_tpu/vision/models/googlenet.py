"""GoogLeNet / Inception v1 (reference: python/paddle/vision/models/googlenet.py)."""

from __future__ import annotations

from ... import nn
from ._utils import ConvBNReLU as _ConvBN


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBN(in_ch, c1, 1)
        self.b2 = nn.Sequential(_ConvBN(in_ch, c3r, 1), _ConvBN(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_ConvBN(in_ch, c5r, 1), _ConvBN(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(kernel_size=3, stride=1, padding=1),
                                _ConvBN(in_ch, proj, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                             axis=1)


class GoogLeNet(nn.Layer):
    """Returns (main, aux1, aux2) logits in train mode like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1),
            _ConvBN(64, 64, 1), _ConvBN(64, 192, 3, padding=1),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        self.drop = nn.Dropout(0.4)
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (reference keeps them for training)
            self.aux1 = nn.Sequential(nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                                      nn.Linear(512 * 16, 1024), nn.ReLU(),
                                      nn.Dropout(0.7), nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                                      nn.Linear(528 * 16, 1024), nn.ReLU(),
                                      nn.Dropout(0.7), nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        x = self.i4a(x)
        a1 = self.aux1(x) if (self.num_classes > 0 and self.training) else None
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x) if (self.num_classes > 0 and self.training) else None
        x = self.i5b(self.i5a(self.pool4(self.i4e(x))))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
            if self.training:
                return x, a1, a2
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are unavailable (zero-egress "
                         "build); load a local state_dict instead")
    return GoogLeNet(**kwargs)
