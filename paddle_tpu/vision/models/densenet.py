"""DenseNet (reference: python/paddle/vision/models/densenet.py —
densenet121/161/169/201/264)."""

from __future__ import annotations

from ... import nn


class _DenseLayer(nn.Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(num_channels)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_channels, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        import paddle_tpu as paddle
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, num_channels, num_output):
        super().__init__()
        self.bn = nn.BatchNorm2D(num_channels)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_channels, num_output, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(kernel_size=2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init, growth, block_cfg = _CFG[layers]
        self.features = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1))
        ch = num_init
        blocks = []
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch = ch // 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(ch)
        self.relu_last = nn.ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu_last(self.bn_last(self.blocks(self.features(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are unavailable (zero-egress "
                         "build); load a local state_dict instead")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
