"""Shared building blocks for the vision model zoo."""

from __future__ import annotations

from ... import nn


class ConvBNReLU(nn.Layer):
    """conv → batch-norm → relu, the stem block every inception-family model
    repeats (single definition so BN hyperparams stay in sync)."""

    def __init__(self, in_ch, out_ch, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=padding,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))
