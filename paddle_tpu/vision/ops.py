"""Vision ops (reference: python/paddle/vision/ops.py — yolo_box, nms,
roi_align, deform_conv2d subset; operators/detection/ corpus)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply


def box_area(boxes):
    return apply(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), boxes)


def box_iou(boxes1, boxes2):
    def f(b1, b2):
        a1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        a2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (a1[:, None] + a2[None, :] - inter)
    return apply(f, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Greedy NMS.  Data-dependent output size → host computation (the
    reference's nms op is likewise CPU-side in inference postprocessing)."""
    b = np.asarray(getattr(boxes, "_data", boxes))
    s = np.asarray(getattr(scores, "_data", scores)) if scores is not None \
        else np.arange(len(b), 0, -1, dtype="float32")
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0) * np.clip(yy2 - yy1, 0)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference: roi_align_op)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bxs):
        N, C, H, W = feat.shape
        off = 0.5 if aligned else 0.0

        def one_roi(box):
            x1, y1, x2, y2 = box * spatial_scale - off if aligned else box * spatial_scale
            if not aligned:
                x1, y1, x2, y2 = box * spatial_scale
            w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
            h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
            ys = y1 + (jnp.arange(oh) + 0.5) * h / oh - 0.5
            xs = x1 + (jnp.arange(ow) + 0.5) * w / ow - 0.5
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")

            def sample(img2d):
                y0 = jnp.floor(gy).astype(jnp.int32)
                x0 = jnp.floor(gx).astype(jnp.int32)
                y1i, x1i = y0 + 1, x0 + 1
                wy = gy - y0
                wx = gx - x0
                def g(yy, xx):
                    yy = jnp.clip(yy, 0, H - 1)
                    xx = jnp.clip(xx, 0, W - 1)
                    return img2d[yy, xx]
                return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1i) * (1 - wy) * wx
                        + g(y1i, x0) * wy * (1 - wx) + g(y1i, x1i) * wy * wx)
            return jax.vmap(sample)(feat[0])  # (C, oh, ow) assuming batch 1 per roi
        return jax.vmap(one_roi)(bxs)
    return apply(f, x, boxes)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """YOLO box decoding (reference: yolo_box_op)."""
    na = len(anchors) // 2

    def f(feat, imgs):
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, -1, H, W)
        grid_x = jnp.arange(W).reshape(1, 1, 1, W)
        grid_y = jnp.arange(H).reshape(1, 1, H, 1)
        anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        bx = (jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y
              - 0.5 * (scale_x_y - 1) + grid_x) / W
        by = (jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y
              - 0.5 * (scale_x_y - 1) + grid_y) / H
        bw = jnp.exp(feat[:, :, 2]) * anc[None, :, 0, None, None] / (W * downsample_ratio)
        bh = jnp.exp(feat[:, :, 3]) * anc[None, :, 1, None, None] / (H * downsample_ratio)
        conf = jax.nn.sigmoid(feat[:, :, 4])
        probs = jax.nn.sigmoid(feat[:, :, 5:5 + class_num]) * conf[:, :, None]
        img_h = imgs[:, 0].reshape(N, 1, 1, 1)
        img_w = imgs[:, 1].reshape(N, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
        mask = conf.reshape(N, -1, 1) > conf_thresh
        boxes = boxes * mask
        return boxes, scores
    return apply(f, x, img_size)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale)
