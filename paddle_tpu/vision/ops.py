"""Vision ops (reference: python/paddle/vision/ops.py — yolo_box, nms,
roi_align, deform_conv2d subset; operators/detection/ corpus)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply


def box_area(boxes):
    return apply(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), boxes)


def box_iou(boxes1, boxes2):
    def f(b1, b2):
        a1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        a2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (a1[:, None] + a2[None, :] - inter)
    return apply(f, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Greedy NMS.  Data-dependent output size → host computation (the
    reference's nms op is likewise CPU-side in inference postprocessing)."""
    b = np.asarray(getattr(boxes, "_data", boxes))
    s = np.asarray(getattr(scores, "_data", scores)) if scores is not None \
        else np.arange(len(b), 0, -1, dtype="float32")
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0) * np.clip(yy2 - yy1, 0)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference: roi_align_op)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bxs):
        N, C, H, W = feat.shape
        off = 0.5 if aligned else 0.0

        def one_roi(box):
            x1, y1, x2, y2 = box * spatial_scale - off if aligned else box * spatial_scale
            if not aligned:
                x1, y1, x2, y2 = box * spatial_scale
            w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
            h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
            ys = y1 + (jnp.arange(oh) + 0.5) * h / oh - 0.5
            xs = x1 + (jnp.arange(ow) + 0.5) * w / ow - 0.5
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")

            def sample(img2d):
                y0 = jnp.floor(gy).astype(jnp.int32)
                x0 = jnp.floor(gx).astype(jnp.int32)
                y1i, x1i = y0 + 1, x0 + 1
                wy = gy - y0
                wx = gx - x0
                def g(yy, xx):
                    yy = jnp.clip(yy, 0, H - 1)
                    xx = jnp.clip(xx, 0, W - 1)
                    return img2d[yy, xx]
                return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1i) * (1 - wy) * wx
                        + g(y1i, x0) * wy * (1 - wx) + g(y1i, x1i) * wy * wx)
            return jax.vmap(sample)(feat[0])  # (C, oh, ow) assuming batch 1 per roi
        return jax.vmap(one_roi)(bxs)
    return apply(f, x, boxes)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """YOLO box decoding (reference: yolo_box_op)."""
    na = len(anchors) // 2

    def f(feat, imgs):
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, -1, H, W)
        grid_x = jnp.arange(W).reshape(1, 1, 1, W)
        grid_y = jnp.arange(H).reshape(1, 1, H, 1)
        anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        bx = (jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y
              - 0.5 * (scale_x_y - 1) + grid_x) / W
        by = (jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y
              - 0.5 * (scale_x_y - 1) + grid_y) / H
        bw = jnp.exp(feat[:, :, 2]) * anc[None, :, 0, None, None] / (W * downsample_ratio)
        bh = jnp.exp(feat[:, :, 3]) * anc[None, :, 1, None, None] / (H * downsample_ratio)
        conf = jax.nn.sigmoid(feat[:, :, 4])
        probs = jax.nn.sigmoid(feat[:, :, 5:5 + class_num]) * conf[:, :, None]
        img_h = imgs[:, 0].reshape(N, 1, 1, 1)
        img_w = imgs[:, 1].reshape(N, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
        mask = conf.reshape(N, -1, 1) > conf_thresh
        boxes = boxes * mask
        return boxes, scores
    return apply(f, x, img_size)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def _roi_batch_index(boxes_num, n_rois):
    """Map each RoI row to its source image (eager: boxes_num is a small
    host-side count vector, ≙ the reference's RoisNum input)."""
    if boxes_num is None:
        return jnp.zeros((n_rois,), jnp.int32)
    bn = np.asarray(getattr(boxes_num, "_data", boxes_num)).astype(np.int64)
    if int(bn.sum()) != n_rois:
        raise ValueError(f"sum(boxes_num)={int(bn.sum())} must equal the "
                         f"number of RoIs {n_rois}")
    return jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Quantized max RoI pooling (reference: roi_pool_op, vision/ops.py:1022).

    Bin edges follow the reference kernel: rounded roi corners, width/height
    floored at 1, per-bin max over the integer grid; empty bins yield 0."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bxs, batch_idx):
        N, C, H, W = feat.shape
        rows = jnp.arange(H, dtype=jnp.float32)
        cols = jnp.arange(W, dtype=jnp.float32)

        def one_roi(box, bi):
            x1 = jnp.round(box[0] * spatial_scale)
            y1 = jnp.round(box[1] * spatial_scale)
            x2 = jnp.round(box[2] * spatial_scale)
            y2 = jnp.round(box[3] * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
            rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
            bh, bw = rh / oh, rw / ow
            img = feat[bi]  # (C, H, W)

            def one_bin(i, j):
                hs = jnp.floor(i * bh) + y1
                he = jnp.ceil((i + 1) * bh) + y1
                ws = jnp.floor(j * bw) + x1
                we = jnp.ceil((j + 1) * bw) + x1
                rmask = (rows >= jnp.clip(hs, 0, H)) & (rows < jnp.clip(he, 0, H))
                cmask = (cols >= jnp.clip(ws, 0, W)) & (cols < jnp.clip(we, 0, W))
                m = rmask[:, None] & cmask[None, :]
                vals = jnp.where(m[None], img, -jnp.inf)
                mx = jnp.max(vals, axis=(1, 2))
                return jnp.where(jnp.any(m), mx, 0.0)

            ii, jj = jnp.meshgrid(jnp.arange(oh, dtype=jnp.float32),
                                  jnp.arange(ow, dtype=jnp.float32), indexing="ij")
            out = jax.vmap(jax.vmap(one_bin))(ii, jj)  # (oh, ow, C)
            return jnp.transpose(out, (2, 0, 1))
        return jax.vmap(one_roi)(bxs, batch_idx)

    n_rois = getattr(boxes, "shape", np.shape(boxes))[0]
    bi = _roi_batch_index(boxes_num, n_rois)
    return apply(lambda fe, bx: f(fe, bx, bi), x, boxes)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pooling (reference: psroi_pool_op,
    vision/ops.py:911).  Input channels C must equal out_ch * oh * ow; output
    channel c at bin (i, j) averages input channel (c*oh + i)*ow + j."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bxs, batch_idx):
        N, C, H, W = feat.shape
        assert C % (oh * ow) == 0, \
            f"psroi_pool needs channels divisible by {oh * ow}, got {C}"
        out_ch = C // (oh * ow)
        rows = jnp.arange(H, dtype=jnp.float32)
        cols = jnp.arange(W, dtype=jnp.float32)

        def one_roi(box, bi):
            x1 = jnp.round(box[0]) * spatial_scale
            y1 = jnp.round(box[1]) * spatial_scale
            x2 = jnp.round(box[2] + 1.0) * spatial_scale
            y2 = jnp.round(box[3] + 1.0) * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bh, bw = rh / oh, rw / ow
            img = feat[bi].reshape(out_ch, oh, ow, H, W)

            def one_bin(i, j):
                hs = jnp.floor(y1 + i * bh)
                he = jnp.ceil(y1 + (i + 1) * bh)
                ws = jnp.floor(x1 + j * bw)
                we = jnp.ceil(x1 + (j + 1) * bw)
                rmask = (rows >= jnp.clip(hs, 0, H)) & (rows < jnp.clip(he, 0, H))
                cmask = (cols >= jnp.clip(ws, 0, W)) & (cols < jnp.clip(we, 0, W))
                m = (rmask[:, None] & cmask[None, :]).astype(feat.dtype)
                ii = i.astype(jnp.int32)
                jj = j.astype(jnp.int32)
                chans = img[:, ii, jj]  # (out_ch, H, W)
                s = jnp.sum(chans * m[None], axis=(1, 2))
                cnt = jnp.sum(m)
                return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1.0), 0.0)

            ii, jj = jnp.meshgrid(jnp.arange(oh, dtype=jnp.float32),
                                  jnp.arange(ow, dtype=jnp.float32), indexing="ij")
            out = jax.vmap(jax.vmap(one_bin))(ii, jj)  # (oh, ow, out_ch)
            return jnp.transpose(out, (2, 0, 1))
        return jax.vmap(one_roi)(bxs, batch_idx)

    n_rois = getattr(boxes, "shape", np.shape(boxes))[0]
    bi = _roi_batch_index(boxes_num, n_rois)
    return apply(lambda fe, bx: f(fe, bx, bi), x, boxes)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference: deformable_conv_op,
    vision/ops.py:423).  ``mask=None`` → v1; with mask → modulated (v2).

    offset: (N, 2*dg*kh*kw, Hout, Wout), (dy, dx) pairs per kernel point;
    mask: (N, dg*kh*kw, Hout, Wout).  Implemented as bilinear gather of the
    kh*kw deformed patches followed by a grouped einsum — the sampling is
    bandwidth-bound gather (no MXU), the contraction runs on the MXU."""
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)

    def f(xx, off, w, *rest):
        m = rest[0] if mask is not None else None
        N, C, H, W = xx.shape
        Cout, Cg, kh, kw = w.shape
        K = kh * kw
        dg = deformable_groups
        Hout = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wout = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        off = off.reshape(N, dg, K, 2, Hout, Wout)

        # per kernel point k = i*kw + j: sample position
        ky = jnp.repeat(jnp.arange(kh), kw)                  # (K,)
        kx = jnp.tile(jnp.arange(kw), kh)

        def sample_image(img, off_i, mask_i):
            # img: (C, H, W); off_i: (dg, K, 2, Hout, Wout); mask_i: (dg,K,Hout,Wout)
            py = (jnp.arange(Hout) * sh - ph)[None, None, :, None] \
                + (ky * dh)[None, :, None, None] + off_i[:, :, 0]   # (dg,K,Hout,Wout)
            px = (jnp.arange(Wout) * sw - pw)[None, None, None, :] \
                + (kx * dw)[None, :, None, None] + off_i[:, :, 1]
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy = py - y0
            wx = px - x0

            def g(yy, xx_):
                yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
                xi = jnp.clip(xx_.astype(jnp.int32), 0, W - 1)
                inb = ((yy >= 0) & (yy <= H - 1) & (xx_ >= 0) & (xx_ <= W - 1))
                cpg = C // dg  # channels per deformable group
                imgg = img.reshape(dg, cpg, H, W)
                # gather per deformable group: (dg, cpg, K, Hout, Wout)
                vals = jax.vmap(lambda im, yg, xg: im[:, yg, xg])(imgg, yi, xi)
                return vals * inb[:, None].astype(img.dtype)

            patches = (g(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
                       + g(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
                       + g(y0 + 1, x0) * (wy * (1 - wx))[:, None]
                       + g(y0 + 1, x0 + 1) * (wy * wx)[:, None])
            if mask_i is not None:
                patches = patches * mask_i[:, None]
            return patches.reshape(C, K, Hout, Wout)

        if m is not None:
            patches = jax.vmap(sample_image)(xx, off, m.reshape(N, dg, K, Hout, Wout))
        else:
            patches = jax.vmap(lambda im, of: sample_image(im, of, None))(xx, off)
        # grouped contraction on the MXU
        wk = w.reshape(groups, Cout // groups, Cg, K)
        pat = patches.reshape(N, groups, Cg, K, Hout, Wout)
        out = jnp.einsum("ngckhw,gock->ngohw", pat, wk).reshape(N, Cout, Hout, Wout)
        return out

    args = [x, offset, weight] + ([mask] if mask is not None else [])
    out = apply(f, *args)
    if bias is not None:
        out = apply(lambda o, b: o + jnp.asarray(b)[None, :, None, None], out, bias)
    return out


from ..nn.layer.base import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Deformable conv layer (reference: vision/ops.py:626 DeformConv2D).
    Trainable weight/bias created through the Layer parameter machinery so
    weight_attr/bias_attr initializers and state_dict work as usual."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        from ..nn.initializer import Uniform
        bound = 1.0 / float(np.sqrt(in_channels * kh * kw))
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw), attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: yolo_loss_op / vision/ops.py:42).

    x: (N, S*(5+cls), H, W); gt_box: (N, B, 4) center-form, normalized to the
    input image; gt_label: (N, B) int; returns (N,) per-image loss.  Matches
    the reference formulation: BCE on x/y, L1 on w/h (scaled by 2-w*h), BCE
    objectness with ignore region (pred/gt IoU > ignore_thresh), BCE class
    with optional label smoothing.  Each gt picks its best anchor over ALL
    anchors; only gts whose best anchor is in ``anchor_mask`` are assigned."""
    an_all = np.asarray(anchors, "float32").reshape(-1, 2)
    an_mask = list(anchor_mask)
    S = len(an_mask)

    def f(feat, gtb, gtl, *rest):
        gts = rest[0] if gt_score is not None else None
        N, C, H, W = feat.shape
        B = gtb.shape[1]
        assert C == S * (5 + class_num), (C, S, class_num)
        img_w = W * downsample_ratio
        img_h = H * downsample_ratio
        p = feat.reshape(N, S, 5 + class_num, H, W)
        anc = jnp.asarray(an_all)                    # (A, 2) in input-image px
        anc_sel = jnp.asarray(an_all[an_mask])       # (S, 2)

        valid = (gtb[:, :, 2] > 1e-8) & (gtb[:, :, 3] > 1e-8)  # (N, B)
        # best anchor per gt via shape-only IoU (both centered at origin)
        gw = gtb[:, :, 2] * img_w
        gh = gtb[:, :, 3] * img_h
        inter = jnp.minimum(gw[..., None], anc[None, None, :, 0]) * \
            jnp.minimum(gh[..., None], anc[None, None, :, 1])
        union = gw[..., None] * gh[..., None] + \
            anc[None, None, :, 0] * anc[None, None, :, 1] - inter
        best = jnp.argmax(inter / (union + 1e-10), axis=-1)     # (N, B)
        in_mask = jnp.zeros_like(best, dtype=bool)
        s_of_best = jnp.zeros_like(best)
        for si, ai in enumerate(an_mask):
            hit = best == ai
            in_mask = in_mask | hit
            s_of_best = jnp.where(hit, si, s_of_best)
        pos = valid & in_mask                                   # (N, B)

        gi = jnp.clip((gtb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
        tx = gtb[:, :, 0] * W - gi
        ty = gtb[:, :, 1] * H - gj
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(anc_sel[s_of_best, 0], 1e-8), 1e-8))
        th = jnp.log(jnp.maximum(gh / jnp.maximum(anc_sel[s_of_best, 1], 1e-8), 1e-8))
        box_w = 2.0 - gtb[:, :, 2] * gtb[:, :, 3]
        score_w = gts if gts is not None else jnp.ones_like(tx)

        # scatter gt targets into a flat (N, S*H*W + 1) table; invalid gts go
        # to the trailing trash slot so static shapes survive ragged gt counts
        flat_idx = jnp.where(pos, (s_of_best * H + gj) * W + gi, S * H * W)

        def scat(vals):
            z = jnp.zeros((N, S * H * W + 1), vals.dtype)
            return jax.vmap(lambda zz, ii, vv: zz.at[ii].set(vv))(
                z, flat_idx, vals)[:, :-1].reshape(N, S, H, W)

        t_pos = scat(jnp.where(pos, 1.0, 0.0))
        # objectness target IS the gt score (mixup-style soft labels), and the
        # class loss is score-weighted — reference yolov3_loss semantics
        t_obj = scat(jnp.where(pos, score_w, 0.0))
        t_x = scat(tx)
        t_y = scat(ty)
        t_w = scat(tw)
        t_h = scat(th)
        t_bw = scat(jnp.where(pos, box_w * score_w, 0.0))
        t_cls = scat(jnp.where(pos, gtl.astype(jnp.float32), 0.0)).astype(jnp.int32)

        # ignore region: decoded pred boxes with IoU > thresh vs any valid gt
        grid_x = jnp.arange(W).reshape(1, 1, 1, W)
        grid_y = jnp.arange(H).reshape(1, 1, H, 1)
        sig = jax.nn.sigmoid
        bx = (sig(p[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_x) / W
        by = (sig(p[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_y) / H
        bw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) * anc_sel[None, :, 0, None, None] / img_w
        bh = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) * anc_sel[None, :, 1, None, None] / img_h

        def iou_vs_gts(bx_, by_, bw_, bh_, gtb_, valid_):
            # (S,H,W) pred vs (B,) gts → max IoU (S,H,W)
            px1 = bx_ - bw_ / 2; px2 = bx_ + bw_ / 2
            py1 = by_ - bh_ / 2; py2 = by_ + bh_ / 2
            gx1 = gtb_[:, 0] - gtb_[:, 2] / 2; gx2 = gtb_[:, 0] + gtb_[:, 2] / 2
            gy1 = gtb_[:, 1] - gtb_[:, 3] / 2; gy2 = gtb_[:, 1] + gtb_[:, 3] / 2
            ix = jnp.clip(jnp.minimum(px2[..., None], gx2) -
                          jnp.maximum(px1[..., None], gx1), 0)
            iy = jnp.clip(jnp.minimum(py2[..., None], gy2) -
                          jnp.maximum(py1[..., None], gy1), 0)
            inter_ = ix * iy
            uni = (bw_ * bh_)[..., None] + gtb_[:, 2] * gtb_[:, 3] - inter_
            iou = jnp.where(valid_, inter_ / (uni + 1e-10), 0.0)
            return jnp.max(iou, axis=-1)

        best_iou = jax.vmap(iou_vs_gts)(bx, by, bw, bh, gtb, valid)
        ignore = (best_iou > ignore_thresh) & (t_pos < 0.5)

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))

        l_xy = (bce(p[:, :, 0], t_x) + bce(p[:, :, 1], t_y)) * t_bw
        l_wh = (jnp.abs(p[:, :, 2] - t_w) + jnp.abs(p[:, :, 3] - t_h)) * t_bw
        obj_w = jnp.where(ignore, 0.0, 1.0)
        l_obj = bce(p[:, :, 4], t_obj) * jnp.where(t_pos > 0.5, 1.0, obj_w)
        smooth_pos = 1.0 - 1.0 / class_num if use_label_smooth else 1.0
        smooth_neg = 1.0 / class_num if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(t_cls, class_num, axis=2)  # (N,S,cls,H,W)
        cls_target = onehot * smooth_pos + (1 - onehot) * smooth_neg
        l_cls = jnp.sum(bce(p[:, :, 5:5 + class_num], cls_target), axis=2) \
            * t_obj
        per_img = jnp.sum(l_xy + l_wh + l_obj + l_cls, axis=(1, 2, 3))
        return per_img

    args = [x, gt_box, gt_label] + ([gt_score] if gt_score is not None else [])
    return apply(f, *args)


def read_file(filename, name=None):
    """Read raw file bytes as a uint8 1-D tensor (reference: vision/ops.py:819)."""
    with open(filename, "rb") as fh:
        data = fh.read()
    return Tensor(jnp.asarray(np.frombuffer(data, dtype=np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to (C, H, W) uint8 (reference:
    vision/ops.py:864; the CUDA build uses nvjpeg — here PIL on host, since
    decode is an input-pipeline op that belongs off-device anyway)."""
    import io as _io
    from PIL import Image

    raw = bytes(np.asarray(getattr(x, "_data", x)).astype(np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode != "unchanged":
        img = img.convert({"gray": "L", "rgb": "RGB"}.get(mode, mode.upper()))
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
