"""Vision transforms (reference: python/paddle/vision/transforms/).

Numpy-based host-side preprocessing (runs in DataLoader workers; the device
never sees unbatched images)."""

from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence

import numpy as np

from ...core.tensor import Tensor


def _hwc(img):
    arr = np.asarray(img)
    return arr


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 → CHW float32 / 255 (reference transforms.ToTensor)."""

    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _hwc(img).astype("float32")
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, dtype="float32").reshape(-1)
        self.std = np.asarray(std, dtype="float32").reshape(-1)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype="float32")
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(arr, size, interpolation="bilinear"):
    import jax
    import jax.numpy as jnp
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic",
              "lanczos": "lanczos3"}.get(interpolation, "linear")
    out_shape = (oh, ow) + arr.shape[2:]
    return np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape,
                                       method=method)).astype(arr.dtype)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return _resize_np(_hwc(img), self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = _hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def _apply_image(self, img):
        arr = _hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            pad = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad, constant_values=self.fill)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed:
            if h < th or w < tw:
                ph, pw = max(th - h, 0), max(tw - w, 0)
                arr = np.pad(arr, [(0, ph), (0, pw)] + [(0, 0)] * (arr.ndim - 2),
                             constant_values=self.fill)
                h, w = arr.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _hwc(img)
        if random.random() < self.prob:
            return arr[:, ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _hwc(img)
        if random.random() < self.prob:
            return arr[::-1].copy()
        return arr


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = arr[i:i + th, j:j + tw]
                return _resize_np(crop, self.size, self.interpolation)
        return _resize_np(arr, self.size, self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        self.p = p
        self.fill = fill
        self.mode = padding_mode

    def _apply_image(self, img):
        arr = _hwc(img)
        pad = [(self.p[1], self.p[3]), (self.p[0], self.p[2])] + \
            [(0, 0)] * (arr.ndim - 2)
        if self.mode == "constant":
            return np.pad(arr, pad, constant_values=self.fill)
        return np.pad(arr, pad, mode={"reflect": "reflect", "edge": "edge",
                                      "symmetric": "symmetric"}[self.mode])


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        arr = _hwc(img).astype("float32")
        if arr.ndim == 2:
            g = arr
        else:
            g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
        out = np.repeat(g[..., None], self.n, axis=-1)
        return out


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype("float32")
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * f, 0, 255 if arr.max() > 1.5 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype("float32")
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.mean()
        return np.clip(mean + (arr - mean) * f, 0, 255 if arr.max() > 1.5 else 1.0)


class SaturationTransform(BaseTransform):
    """Random saturation jitter (reference transforms.py SaturationTransform):
    blend between the grayscale image and the original."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype("float32")
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = (arr[..., :3] @ np.asarray([0.299, 0.587, 0.114],
                                          "float32"))[..., None]
        out = arr.copy()
        out[..., :3] = gray + (arr[..., :3] - gray) * f
        return np.clip(out, 0, 255 if arr.max() > 1.5 else 1.0)


class HueTransform(BaseTransform):
    """Random hue rotation (reference transforms.py HueTransform): shift the
    hue channel in HSV space by a uniform offset in [-value, value]·0.5."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        arr = _hwc(img).astype("float32")
        scale = 255.0 if arr.max() > 1.5 else 1.0
        rgb = arr[..., :3] / scale
        mx = rgb.max(-1)
        mn = rgb.min(-1)
        diff = mx - mn + 1e-12
        r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
        h = np.where(mx == r, (g - b) / diff % 6,
                     np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6.0
        s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
        v = mx
        h = (h + random.uniform(-self.value, self.value)) % 1.0
        i = np.floor(h * 6).astype("int32")
        f = h * 6 - i
        p = v * (1 - s)
        q = v * (1 - f * s)
        t = v * (1 - (1 - f) * s)
        i = i % 6
        choices = [np.stack(c, -1) for c in
                   ((v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v),
                    (v, p, q))]
        out_rgb = np.select([(i == k)[..., None] for k in range(6)], choices) * scale
        out = arr.copy()
        out[..., :3] = out_rgb
        return np.clip(out, 0, scale)


class RandomRotation(BaseTransform):
    """Random rotation by a degree in [-degrees, degrees] (reference
    transforms.py RandomRotation; nearest resampling, center pivot)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        arr = _hwc(img)
        angle = np.deg2rad(random.uniform(*self.degrees))
        H, W = arr.shape[:2]
        ca, sa = np.cos(angle), np.sin(angle)
        if self.expand:
            # enlarged canvas holding the whole rotated image
            Ho = int(np.ceil(abs(H * ca) + abs(W * sa)))
            Wo = int(np.ceil(abs(W * ca) + abs(H * sa)))
        else:
            Ho, Wo = H, W
        cy, cx = ((H - 1) / 2.0, (W - 1) / 2.0) if self.center is None \
            else (self.center[1], self.center[0])
        oy, ox = (Ho - 1) / 2.0, (Wo - 1) / 2.0
        yy, xx = np.mgrid[0:Ho, 0:Wo]
        # inverse map: output pixel ← source pixel
        sx = ca * (xx - ox) + sa * (yy - oy) + cx
        sy = -sa * (xx - ox) + ca * (yy - oy) + cy
        if self.interpolation == "bilinear":
            x0 = np.floor(sx).astype("int64")
            y0 = np.floor(sy).astype("int64")
            wx = (sx - x0)[..., None] if arr.ndim == 3 else (sx - x0)
            wy = (sy - y0)[..., None] if arr.ndim == 3 else (sy - y0)

            def g(yi, xi):
                ok = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
                v = arr[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)].astype(
                    "float32")
                m = ok[..., None] if arr.ndim == 3 else ok
                return np.where(m, v, float(self.fill))

            out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x0 + 1) * (1 - wy) * wx
                   + g(y0 + 1, x0) * wy * (1 - wx) + g(y0 + 1, x0 + 1) * wy * wx)
            return out.astype(arr.dtype)
        sxi = np.round(sx).astype("int64")
        syi = np.round(sy).astype("int64")
        inb = (sxi >= 0) & (sxi < W) & (syi >= 0) & (syi < H)
        out = np.full((Ho, Wo) + arr.shape[2:], self.fill, arr.dtype)
        out[inb] = arr[np.clip(syi, 0, H - 1), np.clip(sxi, 0, W - 1)][inb]
        return out


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        arr = img
        order = list(self.ts)
        random.shuffle(order)
        for t in order:
            arr = t(arr)
        return arr


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _hwc(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


# functional namespace (reference transforms/functional.py)
def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return _resize_np(_hwc(img), size, interpolation)


def hflip(img):
    return _hwc(img)[:, ::-1].copy()


def vflip(img):
    return _hwc(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _hwc(img)[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)

__all__ = ["Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "Pad", "Grayscale",
           "BrightnessTransform", "ContrastTransform", "ColorJitter",
           "Transpose", "to_tensor", "normalize", "resize", "hflip", "vflip",
           "center_crop", "crop", "pad", "to_grayscale"]
