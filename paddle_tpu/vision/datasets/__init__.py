"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST, Cifar,
FashionMNIST, Flowers).  Zero-egress build: readers consume locally provided
files (paths must be given; downloading is not available in this image)."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    """IDX-format MNIST reader (reference vision/datasets/mnist.py)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        if image_path is None or label_path is None:
            raise ValueError(
                "this build has no network egress: pass image_path/label_path "
                "to local IDX files (train-images-idx3-ubyte.gz etc.)")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 python-pickle tar reader (reference vision/datasets/cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend="cv2"):
        if data_file is None:
            raise ValueError("zero-egress build: pass data_file to the local "
                             "cifar-10-python.tar.gz")
        self.transform = transform
        self.data = []
        self.labels = []
        want = "data_batch" if mode == "train" else "test_batch"
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if want in member.name:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    self.data.append(d[b"data"])
                    self.labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.data = np.concatenate(self.data).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        self.labels = np.asarray(self.labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass
