"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST, Cifar,
FashionMNIST, Flowers).  Zero-egress build: readers consume locally provided
files (paths must be given; downloading is not available in this image)."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    """IDX-format MNIST reader (reference vision/datasets/mnist.py)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        if image_path is None or label_path is None:
            raise ValueError(
                "this build has no network egress: pass image_path/label_path "
                "to local IDX files (train-images-idx3-ubyte.gz etc.)")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 python-pickle tar reader (reference vision/datasets/cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend="cv2"):
        if data_file is None:
            raise ValueError("zero-egress build: pass data_file to the local "
                             "cifar-10-python.tar.gz")
        self.transform = transform
        self.data = []
        self.labels = []
        want = "data_batch" if mode == "train" else "test_batch"
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if want in member.name:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    self.data.append(d[b"data"])
                    self.labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.data = np.concatenate(self.data).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        self.labels = np.asarray(self.labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """Directory-per-class dataset (reference datasets/folder.py): root/
    class_x/sample.ext → (loaded sample, class index).  ``loader`` defaults
    to vision.image_load; ``extensions`` filters files."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self._custom_loader = loader is not None
        if loader is None:
            from .. import image_load
            loader = image_load
        self.loader = loader
        exts = tuple(e.lower() for e in (extensions or
                     (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class folders found under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    path = os.path.join(dirpath, f)
                    ok = is_valid_file(path) if is_valid_file is not None \
                        else f.lower().endswith(exts)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no valid files found under {root!r}")

    def __getitem__(self, idx):
        import numpy as np
        path, target = self.samples[idx]
        # a user-supplied loader always wins; the np.load shortcut only
        # covers the default-loader case (case-insensitive like the filter)
        if not self._custom_loader and path.lower().endswith(".npy"):
            sample = np.load(path)
        else:
            sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image-folder dataset, samples only (reference ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self._custom_loader = loader is not None
        if loader is None:
            from .. import image_load
            loader = image_load
        self.loader = loader
        exts = tuple(e.lower() for e in (extensions or
                     (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                path = os.path.join(dirpath, f)
                ok = is_valid_file(path) if is_valid_file is not None \
                    else f.lower().endswith(exts)
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"no valid files found under {root!r}")

    def __getitem__(self, idx):
        import numpy as np
        path = self.samples[idx]
        if not self._custom_loader and path.lower().endswith(".npy"):
            sample = np.load(path)
        else:
            sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference datasets/flowers.py): local 102flowers image
    archive/dir + imagelabels.mat + setid.mat (zero-egress build: all three
    paths are required; the reference downloads the same files)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, backend=None):
        import os
        import tarfile
        for nm, f in (("data_file", data_file), ("label_file", label_file),
                      ("setid_file", setid_file)):
            if f is None or not os.path.exists(f):
                raise ValueError(
                    f"Flowers requires a local {nm} (no downloader in this "
                    f"zero-egress build); got {f!r}")
        from scipy.io import loadmat
        labels = loadmat(label_file)["labels"].ravel().astype("int64") - 1
        setid = loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self._ids = setid[key].ravel().astype("int64")
        self._labels = labels
        self.transform = transform
        self._tar = None
        self._dir = None
        if os.path.isdir(data_file):
            self._dir = data_file
        else:
            self._tar = tarfile.open(data_file)

    def _read(self, image_id: int):
        import io
        import numpy as np
        name = f"image_{image_id:05d}.jpg"
        if self._dir is not None:
            import os
            path = os.path.join(self._dir, "jpg", name)
            if not os.path.exists(path):
                path = os.path.join(self._dir, name)
            data = open(path, "rb").read()
        else:
            member = next((m for m in (f"jpg/{name}", name)
                           if self._member(m) is not None), None)
            if member is None:
                # a bare StopIteration from __getitem__ would silently end
                # sequence-protocol for-loops mid-epoch
                raise FileNotFoundError(
                    f"{name} not found in the 102flowers archive")
            data = self._tar.extractfile(self._member(member)).read()
        try:
            from PIL import Image
            return np.asarray(Image.open(io.BytesIO(data)))
        except ImportError:
            raise ModuleNotFoundError(
                "JPEG decode needs PIL; extract to .npy arrays or install "
                "a decoder host-side") from None

    def _member(self, name):
        try:
            return self._tar.getmember(name)
        except KeyError:
            return None

    def __getitem__(self, idx):
        image_id = int(self._ids[idx])
        img = self._read(image_id)
        label = self._labels[image_id - 1]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._ids)


__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "Flowers"]
