from . import datasets, models, transforms  # noqa: F401
from . import ops  # noqa: F401
from .models import *  # noqa: F401,F403
