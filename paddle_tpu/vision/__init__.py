"""``paddle_tpu.vision`` (reference: python/paddle/vision/__init__.py — which
re-exports the datasets, models and transforms at this level too)."""

from . import datasets, models, transforms  # noqa: F401
from . import ops  # noqa: F401
from .datasets import *  # noqa: F401,F403
from .models import *  # noqa: F401,F403
from .transforms import *  # noqa: F401,F403


def set_image_backend(backend: str):
    """Reference image.py set_image_backend — numpy-only build ('cv2'/'pil'
    decode backends are a host-side concern; arrays in, arrays out)."""
    if backend not in ("pil", "cv2", "numpy"):
        raise ValueError(f"unknown image backend {backend!r}")


def get_image_backend() -> str:
    return "numpy"


def image_load(path, backend=None):
    """Load an image file to an ndarray (reference image.py image_load)."""
    import numpy as np
    try:
        from PIL import Image
        return np.asarray(Image.open(path))
    except ImportError:
        raise ModuleNotFoundError(
            "image decoding needs PIL, which is not in this build; decode "
            "host-side and feed arrays") from None
