"""Serving telemetry: a bounded ring-buffer structured-event tracer for the
continuous-batching engines.

The serving engines (``paddle_tpu.serving`` / ``serving_paged``) accept a
``tracer=Tracer()`` at construction and then emit host-side events on every
scheduler tick, compile-cache access, and request state transition.  The
tracer is a pure observer: it adds NO operands to any compiled program, and
with ``tracer=None`` (the default) the engines' hot path performs a single
attribute check — no event allocation, no lock.

Event kinds (each event is one flat JSON-serializable dict):

``tick``     one scheduler round.  Fields: ``engine`` (class name),
             ``dur_s`` (host wall time), ``queue_depth``, ``active``
             (decoding slots), ``filling`` (prompts mid-prefill), per-tick
             deltas of the engine counters (``tokens_emitted``,
             ``requests_finished``, and for paged engines
             ``blocks_allocated``/``blocks_released``/``preemptions``/
             ``prefix_hits``), plus whatever the engine packed this tick:
             ``decode_rows``, ``prefill_tokens``, ``budget_used``/
             ``token_budget`` (ragged), ``programs`` (short labels of the
             compiled programs dispatched, e.g. ``ragged_step:12:4``), and
             ``compiles`` (program-cache misses paid inside the tick).
``compile``  one program-cache MISS: ``key`` (short label), ``wall_s``
             (host wall time of the program's first dispatch — trace +
             XLA compile + first execution), ``engine``, ``provenance``
             (``cold`` = paid an XLA compile, ``disk`` = served by the
             persistent compilation cache, ``warm`` = already in process
             — jit/aot.py), and ``expected`` (True inside an
             ``expected_compiles`` warmup window, where misses never arm
             the recompile-storm warning).  Hits are counter-only
             (``compile_hits`` in the registry, plus the tick's
             ``programs`` labels) so steady-state fetches cannot evict
             tick/request history from the ring.
``request``  one request state transition: ``rid`` plus ``what`` in
             ``queued`` → ``admitted`` → ``first_token`` → ``token`` →
             (``preempted`` → ``admitted`` → …) → ``retired`` |
             ``cancelled`` (``engine.cancel(rid)`` — terminal, closes the
             timeline without a TTFT histogram sample).
``gateway``  one serving-gateway action (``paddle_tpu.gateway``): ``what``
             in ``shed`` / ``expired`` / ``dispatch`` / ``reroute`` /
             ``quarantine`` / ``drain_start`` / ``drain_done`` /
             ``cancel``, with per-kind fields (priority, queue depths,
             replica, deadline kind); queue waits feed the registry's
             ``gateway_queue_seconds`` histogram.

Exports:

- ``dump_jsonl(path)``            one event per line, replayable;
- ``to_chrome_trace()``           Chrome-trace JSON (``{"traceEvents":…}``,
  the same output contract as ``tools/trace_to_chrome.py``'s XPlane
  conversion, so engine spans and device traces merge in one Perfetto view
  — ``tools/trace_to_chrome.py --engine-trace`` does the merge);
- ``prometheus_text()``           text exposition of the tracer registry
  (tick/TTFT/inter-token/compile histograms + counters) built on
  ``utils/stats.py``;
- ``request_summary()``           exact p50/p95/p99 TTFT and inter-token
  latency over the retained per-request timelines;
- ``summary()``                   one JSON-able snapshot (tick histogram,
  compile counts, request percentiles) — what ``bench.py`` attaches to
  BENCH rounds.

Recompile visibility: every program-cache miss after the engine's first
completed tick is counted as a *post-warmup* recompile; once
``recompile_warn_threshold`` of them accumulate the tracer logs ONE warning
(the recompile-storm dial that has repeatedly eaten bench rounds —
HEALTH.log).

Training side (``TrainMonitor``, built on the same ring-buffer Tracer —
the Paddle-profiler/fleet-metrics role for the TRAIN loop):

``train_step``  one optimizer step: ``trainer`` (builder name), ``dur_s``
                (host dispatch wall — the step chain is async), ``step``,
                ``examples``/``tokens``.
``sync``        one host↔device synchronization (the loss fetch): ``dur_s``
                is the device-blocked host wait, ``loss`` the fetched value
                — the numerics watchdog piggybacks HERE, on the value that
                was being fetched anyway (no extra device syncs).
``watchdog``    a numerics alarm: ``what`` in ``non_finite`` (NaN/Inf
                loss) / ``loss_spike`` (loss > spike_factor × its EMA).
``amp``         a GradScaler event: ``what`` in ``found_inf`` /
                ``scale_change``, with the current ``scale``.
``hbm``         one live-array census: byte counts split
                params / opt-state / other, with peak gauges.
``aggregate``   one cross-host reduction of the step counters
                (``fleet.metrics.all_reduce_metrics`` — global throughput
                + per-replica straggler skew).
``comm``        one gradient-communication accounting event (the
                ``distributed.grad_comm`` policy layer): ``policy``,
                ``pre_bytes`` (fp32-baseline wire bytes for the step's
                reduction), ``post_bytes`` (the policy's), ``savings``
                (pre/post) — host-side estimates from the grad-tree
                shapes, never a device sync.
``train_resilience``  one crash-consistency decision
                (``paddle_tpu.train_resilience``): ``what`` in
                ``save_commit`` / ``save_abandon`` / ``restore`` /
                ``restart`` / ``corrupt_skip`` / ``preempt_request`` /
                ``preempt_save`` / ``elastic_exit`` / ``fault_inject`` /
                ``rules_mismatch`` / ``give_up`` / ``gc``, with ``step``
                and per-kind fields (reason, bytes, backoff).

Goodput accounting: a ``telemetry_ledger.RunLedger`` attaches to either
layer via ``set_ledger`` — tick/compile/train_step/sync durations forward
into its exhaustive wall-clock buckets behind one attribute check (off by
default), and ``ops_server.OpsServer`` serves the merged picture live.

No single reference counterpart: this is the serving-shaped composition of
the reference's profiler ``RecordEvent`` (platform/profiler.h:130),
``monitor.h`` StatRegistry, and ``tools/timeline.py`` chrome-trace export.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from .utils.stats import (DEFAULT_TIME_BUCKETS, StatRegistry,
                          prometheus_text as _stats_prometheus_text)

__all__ = ["Tracer", "RequestTimeline", "RequestTraceIndex", "TraceContext",
           "TrainMonitor", "program_label", "chrome_trace_from_jsonl",
           "instrument_train_step", "set_active_monitor", "current_monitor"]

_PCTS = (50.0, 95.0, 99.0)


class TraceContext:
    """W3C-style trace identity for ONE request across sources.

    ``trace_id`` names the whole end-to-end request (minted once, at the
    gateway's ``submit()``); ``span_id`` names one unit of work under it
    (the gateway's root request span, or one engine attempt); ``parent_
    span_id`` links a child span to its parent.  The context is pure
    host-side metadata: it rides tracer events (``Tracer.bind_trace``
    attaches it to every request-timeline event for a rid) and NEVER
    becomes an operand of a compiled program — lowerings are byte-
    identical with or without one (pinned by test).

    The gateway mints the root at admission and a fresh CHILD per engine
    dispatch (including quarantine-reroute re-dispatches), so a request
    that crosses replicas leaves one trace with one span per attempt —
    :class:`RequestTraceIndex` stitches them back together."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_span_id = (None if parent_span_id is None
                               else str(parent_span_id))

    @classmethod
    def root(cls) -> "TraceContext":
        """Mint a fresh root context (new trace_id, no parent)."""
        return cls(uuid.uuid4().hex[:16], uuid.uuid4().hex[:8], None)

    def child(self) -> "TraceContext":
        """Mint a child span under this one (same trace_id)."""
        return TraceContext(self.trace_id, uuid.uuid4().hex[:8],
                            self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id}

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, "
                f"parent_span_id={self.parent_span_id!r})")


def program_label(key) -> str:
    """Short display label for an engine program-cache key: the kind tag
    plus its leading shape/bucket ints — the full key embeds the engine
    signature tuple, which is noise at event granularity."""
    if not isinstance(key, tuple) or not key:
        return str(key)
    parts = [str(key[0])]
    for k in key[1:]:
        if isinstance(k, bool) or not isinstance(k, int):
            break
        parts.append(str(k))
    return ":".join(parts)


def _percentiles(samples) -> Optional[Dict[str, float]]:
    if not samples:
        return None
    import numpy as np
    arr = np.asarray(samples, dtype=float)
    out = {f"p{int(p)}": float(np.percentile(arr, p)) for p in _PCTS}
    out["mean"] = float(arr.mean())
    out["max"] = float(arr.max())
    out["count"] = int(arr.size)
    return out


class RequestTimeline:
    """Host-side latency timeline of ONE request.  All timestamps are the
    tracer's monotonic clock (seconds since tracer construction).  A
    preemption closes the current attempt: streamed tokens are discarded
    (mirroring the engine's documented ``on_token(rid, None, False)``
    reset signal) and TTFT restarts measuring at the ORIGINAL queued_at —
    the replayed prefill is not double-counted, the request simply has one
    TTFT: queued → the first token that was never rolled back."""

    __slots__ = ("rid", "prompt_len", "queued_at", "admitted_at",
                 "first_token_at", "token_times", "preempted_spans",
                 "retired_at", "replays", "tokens_delivered")

    def __init__(self, rid: int, queued_at: float, prompt_len: int = 0):
        self.rid = rid
        self.prompt_len = prompt_len
        self.queued_at = queued_at
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.token_times: List[float] = []
        self.preempted_spans: List[List[Optional[float]]] = []
        self.retired_at: Optional[float] = None
        self.replays = 0
        self.tokens_delivered = 0

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.queued_at

    def inter_token_s(self) -> List[float]:
        return [b - a for a, b in zip(self.token_times,
                                      self.token_times[1:])]

    def spans(self) -> List[Dict[str, Any]]:
        """Named (start, end) spans for trace export; open spans end at
        the last known timestamp."""
        out = []
        last = max([self.queued_at] + self.token_times
                   + [t for t in (self.admitted_at, self.first_token_at,
                                  self.retired_at) if t is not None]
                   + [s[1] for s in self.preempted_spans
                      if s[1] is not None])

        def span(name, a, b):
            if a is not None:
                out.append({"name": name, "start": a,
                            "end": b if b is not None else last})

        span("queued", self.queued_at, self.admitted_at)
        span("prefill", self.admitted_at, self.first_token_at)
        span("decode", self.first_token_at, self.retired_at)
        for s in self.preempted_spans:
            span("preempted", s[0], s[1])
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"rid": self.rid, "prompt_len": self.prompt_len,
                "queued_at": self.queued_at, "admitted_at": self.admitted_at,
                "first_token_at": self.first_token_at,
                "retired_at": self.retired_at, "replays": self.replays,
                "tokens_delivered": self.tokens_delivered,
                "ttft_s": self.ttft_s,
                "preempted_spans": [list(s) for s in self.preempted_spans]}


class Tracer:
    """Bounded structured-event tracer (see module docstring).

    ``capacity`` bounds the event ring buffer (oldest events drop;
    ``events_dropped`` counts them) and the retained COMPLETED request
    timelines.  All mutation happens under one lock — engines only touch it
    when a tracer is attached, so the acceptance contract "``step()`` takes
    no tracer lock when tracing is off" holds by construction.
    """

    def __init__(self, capacity: int = 4096,
                 registry: Optional[StatRegistry] = None,
                 recompile_warn_threshold: int = 8,
                 logger: Optional[logging.Logger] = None,
                 attribute_cost: bool = False,
                 peak_flops: Optional[float] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._live: Dict[int, RequestTimeline] = {}
        self._done: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.registry = registry if registry is not None else StatRegistry()
        self._t0 = time.monotonic()
        self.events_dropped = 0
        self.recompile_warn_threshold = int(recompile_warn_threshold)
        self._post_warm_misses = 0
        self._warned_storm = False
        self._ticks = 0
        self._warmup_depth = 0            # expected_compiles nesting
        self._prov_resolver = None        # compile provenance (jit/aot.py)
        self._expected_keys = None        # warmup-grid labels, or None=all
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        # optional goodput ledger (telemetry_ledger.RunLedger): event
        # durations forward into its wall-clock buckets behind ONE
        # attribute check — None (the default) adds nothing.
        # _ledger_compiles logs (ts, wall) of forwarded compile misses so
        # tick() can subtract compile wall paid INSIDE the tick from its
        # compute attribution (the buckets must stay non-overlapping)
        self._ledger = None
        self._ledger_compiles: List[Tuple[float, float]] = []
        # optional SLO monitor (telemetry_slo.SLOMonitor): TTFT/inter-token
        # samples and terminal counts forward into its windowed stores
        # behind ONE attribute check — None (the default) adds nothing
        self._slo = None
        # request-trace plumbing: rid -> TraceContext, attached to every
        # request-timeline event while bound (gateway.submit mints the
        # trace, engine.add_request binds it here)
        self._trace_binds: Dict[int, TraceContext] = {}
        # MFU/roofline attribution: program label -> {"flops", "bytes"}
        # from XLA cost_analysis at the compile seams.  attribute_cost
        # opts the ENGINES into probing cost on program fetches (one
        # extra .lower().compile() per program family, digest-cached
        # process-wide — hapi/dynamic_flops.py); compile_aot attaches
        # cost for free either way.  peak_flops (default from
        # PADDLE_TPU_PEAK_FLOPS) turns model FLOPs/s into MFU.
        self.attribute_cost = bool(attribute_cost)
        if peak_flops is None:
            env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
            peak_flops = float(env) if env else None
        self.peak_flops = (None if not peak_flops
                           else float(peak_flops))
        self._costs: Dict[str, Dict[str, float]] = {}
        # histograms live in the registry so prometheus_text() exports them
        self.registry.histogram("tick_seconds", DEFAULT_TIME_BUCKETS)
        self.registry.histogram("ttft_seconds", DEFAULT_TIME_BUCKETS)
        self.registry.histogram("inter_token_seconds", DEFAULT_TIME_BUCKETS)
        self.registry.histogram("compile_seconds", DEFAULT_TIME_BUCKETS)

    # ------------------------------------------------------------- clock --

    @property
    def t0(self) -> float:
        """This tracer's epoch on the process ``time.monotonic`` clock.
        Event ``ts`` values are seconds since it — ``t0 + ts`` puts
        events from DIFFERENT tracers (gateway + N engines) on one
        comparable timebase, which is what cross-source trace stitching
        (:class:`RequestTraceIndex`) aligns by."""
        return self._t0

    def now(self) -> float:
        return time.monotonic() - self._t0

    def last_event_age_s(self) -> Optional[float]:
        """Seconds since the newest ring event (None when empty) — an O(1)
        liveness peek for ``ops_server`` that never copies the ring."""
        with self._lock:
            if not self._events:
                return None
            return max(0.0, self.now() - self._events[-1]["ts"])

    # ------------------------------------------------------------ ledger --

    #: event kind → RunLedger bucket for durations forwarded by set_ledger.
    #: sync IS device-blocked wait (the host waited on device compute);
    #: profiler_step is deliberately absent — a loop that is both monitor-
    #: instrumented and profiler-paced must not attribute the same wall
    #: time twice (the same double-count rule the counters follow).
    _LEDGER_BUCKETS = {"train_step": "host_dispatch", "sync": "compute"}

    def set_ledger(self, ledger):
        """Attach (or with None detach) a ``telemetry_ledger.RunLedger``:
        tick walls feed ``compute``, compile-miss walls feed ``compile``,
        train_step dispatch feeds ``host_dispatch`` and sync waits feed
        ``compute`` — the tracer becomes the ledger's event source with no
        new instrumentation and one attribute check when detached."""
        self._ledger = ledger
        return ledger

    def set_slo(self, slo):
        """Attach (or with None detach) a ``telemetry_slo.SLOMonitor``:
        TTFT samples (on retirement — the surviving attempt, the same
        one-sample-per-request semantics the histogram follows),
        inter-token samples, and terminal counts (retired / cancelled /
        preempted) forward into its windowed stores.  One attribute
        check when detached."""
        self._slo = slo
        return slo

    # ----------------------------------------------------- trace context --

    def bind_trace(self, rid: int, ctx: Optional[TraceContext]):
        """Bind a :class:`TraceContext` to a request id: every subsequent
        request-timeline event for ``rid`` carries its trace_id/span_id/
        parent_span_id, so cross-source stitching can reassemble the
        end-to-end request.  The binding is dropped when the timeline
        closes (retired/cancelled); ``ctx=None`` unbinds explicitly."""
        with self._lock:
            if ctx is None:
                self._trace_binds.pop(rid, None)
            else:
                self._trace_binds[rid] = ctx

    def trace_of(self, rid: int) -> Optional[TraceContext]:
        with self._lock:
            return self._trace_binds.get(rid)

    # ---------------------------------------------------- cost / roofline --

    def record_cost(self, label: str, cost: Optional[Dict[str, float]]):
        """Attach XLA cost-analysis numbers ({"flops", "bytes"}) to a
        program label — ticks dispatching that label then accumulate
        model-FLOPs and bytes-accessed, the inputs of the MFU/roofline
        summary.  None is ignored (cost probing is best-effort)."""
        if not cost:
            return
        with self._lock:
            self._costs[str(label)] = {
                "flops": float(cost.get("flops", 0.0)),
                "bytes": float(cost.get("bytes", 0.0))}

    def has_cost(self, label: str) -> bool:
        with self._lock:
            return str(label) in self._costs

    def program_costs(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._costs.items()}

    # ----------------------------------------------------------- ingest --

    def _append(self, ev: Dict[str, Any]):
        if len(self._events) == self.capacity:
            self.events_dropped += 1
        self._events.append(ev)

    def emit(self, kind: str, **fields):
        """Append one structured event (adds ``kind`` and ``ts``)."""
        ev = {"kind": kind, "ts": self.now()}
        ev.update(fields)
        with self._lock:
            self._append(ev)
        led = self._ledger
        if led is not None:
            bucket = self._LEDGER_BUCKETS.get(kind)
            if bucket is not None:
                led.record(bucket, float(fields.get("dur_s", 0.0)))
        return ev

    def tick(self, engine: str, dur_s: float, **fields):
        """One scheduler round; observes the tick-duration histogram and
        arms the post-warmup recompile accounting.  When the dispatched
        program labels (``programs``) have recorded cost-analysis numbers
        the tick additionally carries its model-FLOPs (``flops`` /
        ``bytes``) — the per-tick roofline attribution ``summary()``
        folds into MFU.  Engines add free-form composition fields; the
        ragged spec engine notes ``spec_rows`` (draft+verify rows packed
        this tick) next to ``decode_rows``/``prefill_tokens``, and its
        per-engine registry carries the acceptance counters
        (``tokens_drafted``/``tokens_accepted``) whose per-tick deltas
        ride the tick event — accepted-tokens/s over the same MFU
        attribution is the spec roofline story."""
        self.registry.add("ticks")
        self.registry.observe("tick_seconds", dur_s)
        progs = fields.get("programs")
        if progs:
            flops = byts = 0.0
            with self._lock:
                for lbl in progs:
                    c = self._costs.get(lbl)
                    if c is not None:
                        flops += c["flops"]
                        byts += c["bytes"]
            if flops or byts:
                fields["flops"] = flops
                fields["bytes"] = byts
                reg = self.registry
                reg.add("model_flops_total", flops)
                reg.add("model_bytes_total", byts)
                # only walls that actually dispatched COSTED programs
                # denominate FLOPs/s — idle ticks must not dilute MFU
                reg.add("model_flops_wall_seconds", dur_s)
        with self._lock:
            self._ticks += 1
            ev = {"kind": "tick", "ts": self.now(), "engine": engine,
                  "dur_s": dur_s}
            ev.update(fields)
            self._append(ev)
        led = self._ledger
        if led is not None:
            # a scheduler tick's host wall is device-driving time — the
            # serving-side ``compute`` bucket.  Compile misses paid INSIDE
            # this tick already went to the ``compile`` bucket
            # (compile_event), so their wall is subtracted here — the
            # ledger's buckets are non-overlapping by contract
            now = self.now()
            start = now - dur_s
            with self._lock:
                inside = [w for ts, w in self._ledger_compiles
                          if ts >= start]
                # entries older than this tick happened BETWEEN ticks
                # (warmup etc.) and never overlap a tick wall — drop them
                self._ledger_compiles = []
            led.record(
                "compute",
                max(0.0, dur_s - sum(min(w, dur_s) for w in inside)))
        return ev

    @contextlib.contextmanager
    def expected_compiles(self, provenance_resolver=None, keys=None):
        """Mark a warmup window (``jit/aot.py`` wraps warmup runs in one):
        program-cache misses inside it that belong to the warmup grid are
        EXPECTED — they are tagged ``expected: true``, never count toward
        the recompile-storm warning, and their ``provenance`` resolves
        through ``provenance_resolver`` (a callable returning ``"cold"``
        or ``"disk"``; the aot planner passes a persistent-cache-dir
        prober) instead of defaulting to ``cold``.

        ``keys``: the grid's program labels (``WarmupTask.label``); with
        a background warmup (``warmup_async``) live traffic compiles
        CONCURRENTLY with the window, and only grid programs may be
        excused — a real recompile storm must still arm the warning.
        None = every miss in the window is expected (single-purpose
        tracer, the blocking-warmup case).  Re-entrant; resolver/keys
        installed by the outermost entry win."""
        with self._lock:
            self._warmup_depth += 1
            if provenance_resolver is not None \
                    and self._prov_resolver is None:
                self._prov_resolver = provenance_resolver
                installed = True
            else:
                installed = False
            if keys is not None and self._expected_keys is None:
                self._expected_keys = frozenset(keys)
                keys_installed = True
            else:
                keys_installed = False
        try:
            yield self
        finally:
            with self._lock:
                self._warmup_depth -= 1
                if installed:
                    self._prov_resolver = None
                if keys_installed:
                    self._expected_keys = None

    @staticmethod
    def _in_grid(label: str, keys) -> bool:
        """Whether an event label names a declared warmup task.  Task
        labels may carry MORE trailing segments than program_label keeps
        (e.g. task ``seg:8:01`` vs event label ``seg:8`` — bools end the
        label's int run), so a task extending the label also matches."""
        return label in keys \
            or any(k.startswith(label + ":") for k in keys)

    def compile_event(self, engine: str, key, hit: bool,
                      wall_s: float = 0.0, provenance: Optional[str] = None,
                      cost: Optional[Dict[str, float]] = None):
        """One program-cache access.  HITS are counter-only (several per
        tick at steady state — ring events for them would evict the tick/
        request history that summary() percentiles read); MISSES get a
        ring event, and misses after the first completed tick count toward
        the recompile-storm warning — unless they fall inside an
        ``expected_compiles`` warmup window.  ``provenance``
        (``cold`` = paid an XLA compile, ``disk`` = loaded from the
        persistent cache, ``warm`` = already in process) defaults to
        ``warm`` for hits and ``cold`` for misses; warmup windows resolve
        it through their prober (docs/COMPILATION.md)."""
        reg = self.registry
        if hit:
            reg.add("compile_hits")
            return None
        label = program_label(key)
        with self._lock:
            resolver = self._prov_resolver
            keys = self._expected_keys
            expected = self._warmup_depth > 0 and (
                keys is None or self._in_grid(label, keys))
        if provenance is None:
            provenance = (resolver() if expected and resolver is not None
                          else "cold")
        reg.add("compile_misses")
        reg.add(f"compile_{provenance}")
        reg.observe("compile_seconds", wall_s)
        reg.add("compile_wall_seconds_sum", wall_s)
        if cost:
            self.record_cost(label, cost)
        warn = False
        with self._lock:
            ev = {"kind": "compile", "ts": self.now(), "engine": engine,
                  "key": label, "hit": False, "wall_s": wall_s,
                  "provenance": provenance, "expected": expected}
            if cost:
                ev["flops"] = float(cost.get("flops", 0.0))
                ev["bytes"] = float(cost.get("bytes", 0.0))
            if self._ticks > 0 and not expected:
                self._post_warm_misses += 1
                if (self._post_warm_misses >= self.recompile_warn_threshold
                        and not self._warned_storm):
                    self._warned_storm = True
                    warn = True
            self._append(ev)
        led = self._ledger
        if led is not None:
            led.record("compile", wall_s)
            with self._lock:
                self._ledger_compiles.append((ev["ts"], wall_s))
        if warn:
            self._log.warning(
                "recompile storm: %d program-cache misses after warmup "
                "(latest: %s) — shape/bucket churn is forcing fresh XLA "
                "compiles on the serving path",
                self._post_warm_misses, label)
        return ev

    def request_event(self, rid: int, what: str, **fields):
        """One request state transition (see module docstring for the
        ``what`` vocabulary); maintains the per-request timeline and the
        TTFT / inter-token histograms.  Events for a rid with a bound
        :class:`TraceContext` carry its trace_id/span_id/parent_span_id;
        with an attached SLO monitor, TTFT/inter-token samples and
        terminal counts forward into its windowed stores."""
        ts = self.now()
        slo = self._slo
        slo_obs: List[Tuple[str, float]] = []
        slo_cnt: List[str] = []
        with self._lock:
            ctx = self._trace_binds.get(rid)
            tl = self._live.get(rid)
            if tl is None and what == "queued":
                tl = self._live[rid] = RequestTimeline(
                    rid, ts, fields.get("prompt_len", 0))
            elif tl is None:
                # transition for an untracked request (tracer attached
                # mid-flight): open a timeline so spans stay well-formed
                tl = self._live[rid] = RequestTimeline(rid, ts)
            if what == "admitted":
                tl.admitted_at = ts
                for s in tl.preempted_spans:
                    if s[1] is None:
                        s[1] = ts          # replay wait ends at readmission
            elif what == "first_token":
                # NOT observed into the histogram here: a later preemption
                # would roll this attempt back, and the TTFT histogram must
                # carry one sample per request (the surviving attempt) —
                # observation happens at "retired"
                tl.first_token_at = ts
            elif what == "token":
                # live observation: rolled-back attempts stay in the
                # histogram (the client really waited those intervals);
                # request_summary() excludes them (token_times reset on
                # preemption)
                if tl.token_times:
                    self.registry.observe("inter_token_seconds",
                                          ts - tl.token_times[-1])
                    if slo is not None:
                        slo_obs.append(("itl_s", ts - tl.token_times[-1]))
                tl.token_times.append(ts)
                tl.tokens_delivered += 1
            elif what == "preempted":
                # the engine's on_token(rid, None, False) reset: the
                # streamed prefix is void — spans record the attempt, the
                # timeline's live token state starts over so TTFT and ITL
                # never mix pre- and post-replay attempts
                tl.replays += 1
                tl.preempted_spans.append([ts, None])
                tl.first_token_at = None
                tl.admitted_at = None
                tl.token_times = []
                tl.tokens_delivered = 0
                self.registry.add("requests_preempted")
                if slo is not None:
                    slo_cnt.append("requests_preempted")
            elif what == "retired":
                tl.retired_at = ts
                if tl.ttft_s is not None:
                    self.registry.observe("ttft_seconds", tl.ttft_s)
                    if slo is not None:
                        slo_obs.append(("ttft_s", tl.ttft_s))
                self.registry.add("requests_retired")
                if slo is not None:
                    slo_cnt.append("requests_retired")
                self._live.pop(rid, None)
                self._done.append(tl)
                self._trace_binds.pop(rid, None)
            elif what == "cancelled":
                # engine.cancel(): terminal — the timeline closes like a
                # retirement but contributes NO TTFT histogram sample (the
                # histograms describe completed service; cancels are
                # counted, not averaged in)
                tl.retired_at = ts
                self.registry.add("requests_cancelled")
                if slo is not None:
                    slo_cnt.append("requests_cancelled")
                self._live.pop(rid, None)
                self._done.append(tl)
                self._trace_binds.pop(rid, None)
            ev = {"kind": "request", "ts": ts, "rid": rid, "what": what}
            if ctx is not None:
                ev.update(ctx.to_dict())
            ev.update(fields)
            self._append(ev)
        if slo is not None:
            for metric, v in slo_obs:
                slo.observe(metric, v)
            for metric in slo_cnt:
                slo.count(metric)
        return ev

    # ---------------------------------------------------------- queries --

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def timelines(self, include_live: bool = True) -> List[RequestTimeline]:
        with self._lock:
            out = list(self._done)
            if include_live:
                out.extend(self._live.values())
        return out

    def request_summary(self) -> Dict[str, Any]:
        """Exact percentile summary over the retained timelines: p50/p95/
        p99 TTFT (queued → surviving first token) and inter-token latency
        (consecutive accepted tokens of one request, replay attempts
        excluded)."""
        tls = self.timelines()
        ttfts = [tl.ttft_s for tl in tls if tl.ttft_s is not None]
        itl: List[float] = []
        for tl in tls:
            itl.extend(tl.inter_token_s())
        return {"requests_tracked": len(tls),
                "requests_retired": sum(1 for tl in tls
                                        if tl.retired_at is not None),
                "replays": sum(tl.replays for tl in tls),
                "ttft_s": _percentiles(ttfts),
                "inter_token_s": _percentiles(itl)}

    def summary(self) -> Dict[str, Any]:
        """One JSON-able snapshot: tick histogram, compile counters, and
        request percentiles — the BENCH-round telemetry attachment."""
        ticks = self.events("tick")
        reg = self.registry
        gw = self.events("gateway")
        gw_summary = None
        if gw:
            counts: Dict[str, int] = {}
            for ev in gw:
                counts[ev.get("what", "?")] = \
                    counts.get(ev.get("what", "?"), 0) + 1
            gw_summary = {
                "events": counts,
                "queue_s": _percentiles(
                    [ev["queue_s"] for ev in gw
                     if ev.get("what") == "dispatch"
                     and ev.get("queue_s") is not None]),
            }
        kv = self.events("kvstore")
        kv_summary = None
        if kv:
            kv_counts: Dict[str, int] = {}
            for ev in kv:
                kv_counts[ev.get("what", "?")] = \
                    kv_counts.get(ev.get("what", "?"), 0) + 1
            kv_summary = {
                "events": kv_counts,
                # bytes that completed a migration (the transfer-volume
                # headline; per-chunk accounting rides the gateway's
                # paddle_tpu_kvstore_* counters)
                "migrated_bytes": sum(
                    ev.get("bytes", 0) for ev in kv
                    if ev.get("what") == "migrate_done"),
            }
        tr_ev = self.events("train_resilience")
        tr_summary = None
        if tr_ev:
            tr_counts: Dict[str, int] = {}
            for ev in tr_ev:
                tr_counts[ev.get("what", "?")] = \
                    tr_counts.get(ev.get("what", "?"), 0) + 1
            tr_summary = {
                "events": tr_counts,
                # the newest durably-committed step (resume point truth)
                "last_commit_step": max(
                    (ev.get("step", -1) for ev in tr_ev
                     if ev.get("what") == "save_commit"), default=None),
            }
        out = {
            "ticks": len(ticks),
            "ticks_total": int(reg.value("ticks")),
            "tick_wall_s": _percentiles([e["dur_s"] for e in ticks]),
            "compile": {
                "hits": int(reg.value("compile_hits")),
                "misses": int(reg.value("compile_misses")),
                "wall_s": float(reg.value("compile_wall_seconds_sum")),
                "post_warmup_misses": self._post_warm_misses,
                # provenance split (jit/aot.py): cold = paid XLA, disk =
                # loaded from the persistent cache
                "cold": int(reg.value("compile_cold")),
                "disk": int(reg.value("compile_disk")),
            },
            "requests": self.request_summary(),
            "mfu": self.mfu_summary(),
            "events_dropped": self.events_dropped,
        }
        if gw_summary is not None:     # only gateway-fed tracers carry it
            out["gateway"] = gw_summary
        if kv_summary is not None:     # only kv-tiering-fed tracers
            out["kvstore"] = kv_summary
        if tr_summary is not None:     # only checkpoint/supervisor-fed
            out["train_resilience"] = tr_summary
        return out

    def mfu_summary(self) -> Dict[str, Any]:
        """MFU/roofline attribution from the compile-seam cost analysis:
        total model FLOPs and bytes accessed over costed-program ticks,
        model FLOPs/s over the wall those ticks took, arithmetic
        intensity (FLOPs per byte accessed), and — when ``peak_flops``
        is configured — MFU against it.  All-None/zero when no program
        cost was recorded (``attribute_cost=False`` and no aot seam
        reported)."""
        reg = self.registry
        flops = float(reg.value("model_flops_total"))
        byts = float(reg.value("model_bytes_total"))
        wall = float(reg.value("model_flops_wall_seconds"))
        fps = flops / wall if wall > 0 else None
        return {
            "model_flops_total": flops,
            "model_bytes_total": byts,
            "model_flops_per_s": fps,
            "arithmetic_intensity": (flops / byts if byts > 0 else None),
            "peak_flops": self.peak_flops,
            "mfu": (fps / self.peak_flops
                    if fps is not None and self.peak_flops else None),
            "programs_costed": len(self._costs),
        }

    # ---------------------------------------------------------- exports --

    def dump_jsonl(self, path: str) -> int:
        """One event per line (ring-buffer order), then one ``timeline``
        line per retained request; returns the number of lines written."""
        evs = self.events()
        tls = self.timelines()
        n = 0
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
                n += 1
            for tl in tls:
                f.write(json.dumps({"kind": "timeline", **tl.to_dict()})
                        + "\n")
                n += 1
        return n

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace JSON: scheduler ticks and compiles as complete
        ("X") events, request timelines as per-request span rows — the
        ``{"traceEvents": [...]}`` contract ``tools/trace_to_chrome.py``
        emits for XPlane device traces, so both open in one Perfetto tab."""
        return events_to_chrome(self.events(),
                                [tl for tl in self.timelines()])

    def write_chrome_trace(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)

    def prometheus_text(self, namespace: str = "paddle_tpu_serving") -> str:
        mfu = self.mfu_summary()
        extra = {k: v for k, v in
                 (("model_flops_per_second", mfu["model_flops_per_s"]),
                  ("arithmetic_intensity", mfu["arithmetic_intensity"]),
                  ("mfu", mfu["mfu"]))
                 if v is not None}
        return _stats_prometheus_text(self.registry, namespace=namespace,
                                      extra_gauges=extra or None)


# --------------------------------------------------------------------------
# cross-source request-trace stitching
# --------------------------------------------------------------------------


# gateway event `what` → stitched-trace request status.  ONE map for
# both RequestTraceIndex.recent() and .trace() — a new gateway event
# added here shows the same status on /requests and /request/<id>.
_GATEWAY_STATUS = {"submit": "queued", "dispatch": "dispatched",
                   "shed": "shed", "expired": "expired",
                   "cancel": "cancelled", "failed": "failed",
                   "finish": "finished", "reroute": "queued"}


class RequestTraceIndex:
    """Assemble per-request span trees ACROSS tracer sources.

    A gateway-fronted request leaves fragments in several ring buffers:
    the gateway tracer holds submit/shed/dispatch/reroute/finish events,
    and each replica engine's tracer holds that attempt's request
    timeline (queued → admitted → first_token → token* → retired).  All
    of them carry the same ``trace_id`` (:class:`TraceContext`), and this
    index stitches them back into ONE tree:

    - the **root span** is the gateway request (submit → terminal);
    - each engine **attempt span** (one per dispatch, reroutes included)
      is the child the gateway minted at dispatch time;
    - the attempt's **phase spans** (queued / prefill / decode, plus
      preempt markers) are synthetic children of the attempt.

    The index is a pure PULL reader: it holds references to tracers and
    scans their bounded rings on demand (``ops_server`` serves it live
    as ``GET /requests`` and ``GET /request/<trace_id>``), so it costs
    nothing until queried and is bounded by the rings it reads.
    Timestamps are re-based onto one shared timeline via each tracer's
    ``t0`` epoch; the stitched output reports seconds since the trace's
    first event."""

    def __init__(self, sources=()):
        # attach happens at wiring time but the ops scrape thread scans
        # concurrently; held only for list ops, never across a ring read
        self._sources_lock = threading.Lock()
        self._sources: List[Tuple[str, Any]] = []  # guarded-by: _sources_lock
        for src in sources:
            if isinstance(src, tuple):
                self.add_source(src[1], src[0])
            else:
                self.add_source(src)

    def add_source(self, tracer, name: Optional[str] = None
                   ) -> "RequestTraceIndex":
        """Attach one event source: a ``Tracer`` or anything wrapping one
        (``TrainMonitor``, an engine with ``.tracer``, a gateway)."""
        inner = getattr(tracer, "tracer", tracer)
        if not (hasattr(inner, "events") and hasattr(inner, "t0")):
            raise TypeError(
                f"unsupported trace source: {type(tracer).__name__} "
                f"(want a Tracer or something carrying one)")
        with self._sources_lock:
            self._sources.append(
                (name or f"source{len(self._sources)}", inner))
        return self

    # ------------------------------------------------------------- scans --

    def _scan(self, trace_id: Optional[str] = None
              ) -> List[Tuple[str, Dict[str, Any], float]]:
        """(source, event, absolute_ts) for every ring event carrying a
        trace_id (optionally one specific trace)."""
        out = []
        with self._sources_lock:          # snapshot; ring reads outside
            sources = list(self._sources)
        for name, tr in sources:
            t0 = tr.t0
            for ev in tr.events():
                tid = ev.get("trace_id")
                if tid is None or (trace_id is not None
                                   and tid != trace_id):
                    continue
                out.append((name, ev, t0 + ev["ts"]))
        out.sort(key=lambda x: x[2])
        return out

    def recent(self, n: int = 64) -> List[Dict[str, Any]]:
        """Summaries of the most recent traces (newest first): trace_id,
        gateway id, last-known status, replicas touched, span/event
        counts — the ``GET /requests`` ring."""
        traces: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        for source, ev, ats in self._scan():
            tid = ev["trace_id"]
            t = traces.get(tid)
            if t is None:
                t = traces[tid] = {"trace_id": tid, "first_ts": ats,
                                   "last_ts": ats, "events": 0,
                                   "status": None, "gid": None,
                                   "replicas": []}
                order.append(tid)
            t["events"] += 1
            t["last_ts"] = max(t["last_ts"], ats)
            if ev.get("kind") == "gateway":
                what = ev.get("what")
                if t["gid"] is None and ev.get("gid") is not None:
                    t["gid"] = ev.get("gid")
                rep = ev.get("replica")
                if rep is not None and rep not in t["replicas"]:
                    t["replicas"].append(rep)
                status = _GATEWAY_STATUS.get(what)
                if status is not None:
                    t["status"] = status
        order.sort(key=lambda tid: traces[tid]["last_ts"], reverse=True)
        out = []
        for tid in order[:max(int(n), 1)]:
            t = traces[tid]
            out.append(dict(t, duration_s=t["last_ts"] - t["first_ts"]))
        return out

    def trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The full stitched timeline of one trace: a flat span list
        (every span carries ``span_id`` + ``parent_span_id``; only the
        root has no parent) plus the raw cross-source event sequence.
        None when no source holds any event for the id."""
        scanned = self._scan(trace_id)
        if not scanned:
            return None
        base = scanned[0][2]

        def rel(ats):
            return round(ats - base, 6)

        spans: List[Dict[str, Any]] = []
        events = []
        root_id = None
        root_start = root_end = None
        status = None
        gid = None
        # attempt spans keyed by the dispatch-minted span_id
        attempts: Dict[str, Dict[str, Any]] = {}
        marks: Dict[str, Dict[str, float]] = {}   # span_id -> phase stamps

        for source, ev, ats in scanned:
            events.append(dict(ev, source=source, ts_abs=rel(ats)))
            sid = ev.get("span_id")
            kind = ev.get("kind")
            if kind == "gateway":
                what = ev.get("what")
                if gid is None and ev.get("gid") is not None:
                    gid = ev.get("gid")
                if what == "submit":
                    root_id = sid
                    root_start = ats
                    status = "queued"
                elif what == "dispatch":
                    att = attempts.get(sid)
                    if att is None:
                        att = attempts[sid] = {
                            "name": f"attempt@{ev.get('replica')}",
                            "span_id": sid,
                            "parent_span_id": ev.get("parent_span_id"),
                            "replica": ev.get("replica"),
                            "source": source, "start": ats, "end": ats,
                            "queue_s": ev.get("queue_s")}
                    else:
                        # the engine's own queued event can precede the
                        # gateway's dispatch event on the shared timebase;
                        # the dispatch is still the naming authority
                        att["name"] = f"attempt@{ev.get('replica')}"
                        att["replica"] = ev.get("replica")
                        att["queue_s"] = ev.get("queue_s")
                        att["start"] = min(att["start"], ats)
                    status = "dispatched"
                elif what in _GATEWAY_STATUS:
                    # submit/dispatch were consumed by the branches above
                    status = _GATEWAY_STATUS[what]
                    root_end = ats
                if root_start is None:
                    root_start = ats          # shed-before-submit safety
                root_end = ats if root_end is None else max(root_end, ats)
            elif kind == "request" and sid is not None:
                att = attempts.get(sid)
                if att is None:
                    att = attempts[sid] = {
                        "name": f"attempt@{source}", "span_id": sid,
                        "parent_span_id": ev.get("parent_span_id"),
                        "replica": None, "source": source,
                        "start": ats, "end": ats, "queue_s": None}
                att["source"] = source
                att["start"] = min(att["start"], ats)
                att["end"] = max(att["end"], ats)
                st = marks.setdefault(sid, {})
                what = ev.get("what")
                if what in ("queued", "admitted", "first_token",
                            "retired", "cancelled"):
                    st[what] = ats
                elif what == "preempted":
                    st.setdefault("preempts", []).append(ats)
                root_end = ats if root_end is None else max(root_end, ats)

        if root_id is None:
            # no gateway submit event in scope (engine-only trace): use
            # the attempts' shared parent as the root anchor
            parents = {a["parent_span_id"] for a in attempts.values()}
            root_id = next(iter(parents)) if len(parents) == 1 else None
        spans.append({"name": "request", "span_id": root_id,
                      "parent_span_id": None, "source": "gateway",
                      "start_s": rel(root_start if root_start is not None
                                     else base),
                      "end_s": rel(root_end if root_end is not None
                                   else base)})
        for sid, att in attempts.items():
            spans.append({"name": att["name"], "span_id": sid,
                          "parent_span_id": att["parent_span_id"],
                          "source": att["source"],
                          "replica": att["replica"],
                          "start_s": rel(att["start"]),
                          "end_s": rel(att["end"])})
            st = marks.get(sid, {})
            for phase, a_key, b_key in (("queued", "queued", "admitted"),
                                        ("prefill", "admitted",
                                         "first_token"),
                                        ("decode", "first_token", None)):
                a = st.get(a_key)
                if a is None:
                    continue
                b = st.get(b_key) if b_key is not None else None
                if b is None:
                    b = st.get("retired", st.get("cancelled", att["end"]))
                spans.append({"name": phase,
                              "span_id": f"{sid}:{phase}",
                              "parent_span_id": sid,
                              "source": att["source"],
                              "start_s": rel(a), "end_s": rel(b)})
            for i, p in enumerate(st.get("preempts", [])):
                spans.append({"name": "preempted",
                              "span_id": f"{sid}:preempt{i}",
                              "parent_span_id": sid,
                              "source": att["source"],
                              "start_s": rel(p), "end_s": rel(p)})
        return {
            "trace_id": trace_id,
            "gid": gid,
            "status": status,
            "duration_s": rel(root_end if root_end is not None else base),
            "replicas": sorted({s.get("replica") for s in spans
                                if s.get("replica") is not None}),
            "spans": spans,
            "events": events,
        }


# --------------------------------------------------------------------------
# training-side instrumentation
# --------------------------------------------------------------------------

_active_monitor: Optional["TrainMonitor"] = None


def set_active_monitor(monitor: Optional["TrainMonitor"]
                       ) -> Optional["TrainMonitor"]:
    """Install the process-wide active TrainMonitor (or None) and return the
    previous one.  Consumers that cannot be threaded a handle — GradScaler's
    eager path, ``Profiler.step`` — report through this; everything else
    takes an explicit ``monitor=``."""
    global _active_monitor
    prev = _active_monitor
    _active_monitor = monitor
    return prev


def current_monitor() -> Optional["TrainMonitor"]:
    return _active_monitor


class TrainMonitor:
    """Training-side instrumentation layer over the ring-buffer ``Tracer``.

    One monitor observes ONE training run: per-step host wall vs
    device-blocked time, throughput counters, compile events, a numerics
    watchdog (NaN/Inf + loss-spike, fed from loss values the caller was
    fetching anyway), AMP loss-scale events, a live-array HBM census, and
    cross-host aggregation of the step counters.  It shares the Tracer's
    zero-cost-off contract: every producer guards on ``monitor is None`` /
    ``current_monitor() is None`` — a single attribute/None check — and the
    monitor never adds operands to a compiled program.

    Exports ride the underlying tracer: ``dump_jsonl`` / ``to_chrome_trace``
    (merged by ``tools/trace_to_chrome.py --engine-trace``) /
    ``prometheus_text`` (namespace ``paddle_tpu_train``) / ``summary()``
    (the bench attachment).
    """

    def __init__(self, tracer: Optional[Tracer] = None, capacity: int = 4096,
                 spike_factor: float = 10.0, spike_min_steps: int = 5,
                 ema_decay: float = 0.9,
                 logger: Optional[logging.Logger] = None,
                 attribute_cost: bool = False,
                 peak_flops: Optional[float] = None):
        self.tracer = tracer if tracer is not None else Tracer(
            capacity=capacity, logger=logger,
            attribute_cost=attribute_cost, peak_flops=peak_flops)
        self.registry = self.tracer.registry
        self.spike_factor = float(spike_factor)
        self.spike_min_steps = int(spike_min_steps)
        self.ema_decay = float(ema_decay)
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._step_idx = 0
        self._loss_ema: Optional[float] = None
        self._loss_n = 0
        self.last_loss: Optional[float] = None
        self._last_scale: Optional[float] = None
        self._comm_policy: Optional[str] = None
        self._step_cost: Optional[Dict[str, float]] = None
        self._step_cost_is_step = False
        self._warned_non_finite = False
        self.registry.histogram("step_seconds", DEFAULT_TIME_BUCKETS)
        self.registry.histogram("device_blocked_seconds",
                                DEFAULT_TIME_BUCKETS)
        # bucketize compile attribution baseline (jit/bucketing.py bumps the
        # GLOBAL stat; summary() reports the delta over this run)
        from .utils.stats import get_stat
        self._bucket_compiles0 = int(get_stat("bucketize_bucket_compiles"))

    # --------------------------------------------------------- lifecycle --
    def activate(self) -> "TrainMonitor":
        """Install as the process-wide active monitor (GradScaler/Profiler
        routing).  Also usable as a context manager."""
        self._prev_active = set_active_monitor(self)
        return self

    def deactivate(self):
        set_active_monitor(getattr(self, "_prev_active", None))

    __enter__ = activate

    def __exit__(self, *exc):
        self.deactivate()
        return False

    def set_ledger(self, ledger):
        """Forward this monitor's event durations into a
        ``telemetry_ledger.RunLedger`` (step dispatch → ``host_dispatch``,
        device-blocked syncs → ``compute``, compiles → ``compile``); None
        detaches.  See ``Tracer.set_ledger``."""
        return self.tracer.set_ledger(ledger)

    # ------------------------------------------------------------ ingest --
    def record_step(self, wall_s: float, trainer: str = "train",
                    examples: int = 0, tokens: int = 0,
                    loss: Optional[float] = None, **fields):
        """One train step.  ``wall_s`` is HOST dispatch wall (the chain is
        async — device-blocked time is what ``record_sync`` measures);
        ``loss``, when given, must already be a host scalar (never fetch
        one just to pass it here — that would add the sync this layer is
        contractually not allowed to add)."""
        reg = self.registry
        reg.add("train_steps")
        if examples:
            reg.add("train_examples", int(examples))
        if tokens:
            reg.add("train_tokens", int(tokens))
        reg.observe("step_seconds", wall_s)
        self._step_idx += 1
        ev = self.tracer.emit("train_step", trainer=trainer,
                              step=self._step_idx, dur_s=wall_s,
                              examples=int(examples), tokens=int(tokens),
                              **fields)
        if loss is not None:
            self.observe_loss(loss)
        return ev

    def record_sync(self, wall_s: float, loss: Optional[float] = None):
        """One host↔device synchronization (typically the log-cadence loss
        fetch): ``wall_s`` is the blocked host wait; the fetched ``loss``
        feeds the numerics watchdog for free."""
        self.registry.add("train_syncs")
        self.registry.observe("device_blocked_seconds", wall_s)
        ev = self.tracer.emit("sync", dur_s=wall_s,
                              **({} if loss is None else {"loss": float(loss)}))
        if loss is not None:
            self.observe_loss(loss)
        return ev

    def record_profiler_step(self, wall_s: float, samples: int = 0):
        """One ``Profiler.step`` span.  Kept on SEPARATE counters/kind
        (``profiler_steps``/``profiler_step_seconds``/``profiler_step``
        events) so a loop that is both monitor-instrumented and
        profiler-paced never double-counts into ``train_steps`` or the
        step-wall percentiles."""
        self.registry.add("profiler_steps")
        if samples:
            self.registry.add("profiler_samples", int(samples))
        self.registry.observe("profiler_step_seconds", wall_s)
        return self.tracer.emit("profiler_step", dur_s=wall_s,
                                examples=int(samples))

    def record_compile(self, key, wall_s: float,
                       provenance: Optional[str] = None,
                       cost: Optional[Dict[str, float]] = None):
        """One compiled-program build paid by the training loop (first call
        of an instrumented step, a bucketize miss, an AOT compile).
        ``provenance``: ``cold``/``disk``/``warm`` — ``jit.aot
        .compile_aot`` reports where the executable came from.  ``cost``:
        optional XLA cost-analysis ``{"flops", "bytes"}`` for the program
        (``compile_aot`` attaches it for free from the compiled
        executable) — the per-step model-FLOPs source ``summary()``'s
        ``mfu`` section divides by step wall."""
        if cost:
            last = key[-1] if isinstance(key, (tuple, list)) and key else key
            is_step = str(last).endswith("_step")
            # the instrumented STEP program's cost is the per-step MFU
            # numerator; a later costed compile of an aux/eval program
            # (bucketize miss, AOT-warmed eval) must not clobber it —
            # only another step program may overwrite a step cost
            if is_step or not self._step_cost_is_step:
                self._step_cost = {"flops": float(cost.get("flops", 0.0)),
                                   "bytes": float(cost.get("bytes", 0.0))}
                self._step_cost_is_step = is_step
        return self.tracer.compile_event("train", key, False, wall_s,
                                         provenance=provenance, cost=cost)

    def record_comm(self, policy: str, pre_bytes: int, post_bytes: int,
                    **fields):
        """One gradient-communication accounting event (the
        ``distributed.grad_comm`` policy layer): ``pre_bytes`` is the
        fp32-baseline wire estimate for this step's reduction,
        ``post_bytes`` the active policy's.  Pure host arithmetic from
        tree shapes — never a device sync."""
        pre, post = int(pre_bytes), int(post_bytes)
        reg = self.registry
        reg.add("comm_steps")
        reg.add("comm_pre_bytes", pre)
        reg.add("comm_post_bytes", post)
        self._comm_policy = policy
        return self.tracer.emit(
            "comm", policy=policy, pre_bytes=pre, post_bytes=post,
            savings=(pre / post if post else None), step=self._step_idx,
            **fields)

    # ---------------------------------------------------------- watchdog --
    def observe_loss(self, loss) -> Optional[str]:
        """Numerics watchdog over an already-fetched host loss scalar.
        Returns the alarm kind (``non_finite``/``loss_spike``) or None.
        NaN/Inf logs ONE warning per monitor (the storm-dial convention);
        spikes never fold into the EMA, so a plateau shift re-fires until
        the caller intervenes."""
        loss = float(loss)
        self.last_loss = loss
        if loss != loss or loss in (float("inf"), float("-inf")):
            self.registry.add("watchdog_non_finite")
            self.tracer.emit("watchdog", what="non_finite", loss=loss,
                             step=self._step_idx)
            if not self._warned_non_finite:
                self._warned_non_finite = True
                self._log.warning(
                    "numerics watchdog: non-finite loss (%r) at step %d",
                    loss, self._step_idx)
            return "non_finite"
        ema = self._loss_ema
        if (ema is not None and self._loss_n >= self.spike_min_steps
                and abs(loss) > self.spike_factor * max(abs(ema), 1e-12)):
            self.registry.add("watchdog_loss_spikes")
            self.tracer.emit("watchdog", what="loss_spike", loss=loss,
                             ema=ema, step=self._step_idx)
            return "loss_spike"
        self._loss_ema = loss if ema is None \
            else self.ema_decay * ema + (1.0 - self.ema_decay) * loss
        self._loss_n += 1
        return None

    def observe_scaler(self, scale, found_inf: bool = False):
        """AMP GradScaler event feed: ``found_inf`` steps and loss-scale
        changes become ``amp`` events (both host values — the scaler's
        eager path has them; the functional path reads them only at its
        own sync points)."""
        scale = float(scale)
        if found_inf:
            self.registry.add("amp_found_inf")
            self.tracer.emit("amp", what="found_inf", scale=scale,
                             step=self._step_idx)
        if self._last_scale is not None and scale != self._last_scale:
            self.registry.add("amp_scale_changes")
            self.tracer.emit("amp", what="scale_change", scale=scale,
                             prev_scale=self._last_scale,
                             step=self._step_idx)
        self._last_scale = scale

    # -------------------------------------------------------- HBM census --
    def hbm_census(self, params=None, opt=None) -> Dict[str, int]:
        """Live-array byte census: every live array is classified param /
        opt-state / other by identity against the passed pytrees (logical
        bytes — size × itemsize; sharded arrays count their global
        shape).  The raw ``jax.live_arrays()`` walk lives in
        ``telemetry_memory.live_array_census`` — the single accounting
        point (tpulint ``raw-memory-introspection``).  Gauges land on the
        registry with ``set_max``-tracked peaks; returns the census
        dict."""
        from .telemetry_memory import live_array_census

        walk = live_array_census({"params": params, "opt": opt})
        counts = {"params_bytes": walk["params_bytes"],
                  "opt_bytes": walk["opt_bytes"],
                  "other_bytes": walk["other_bytes"]}
        n_arrays = walk["arrays"]
        total = walk["total_bytes"]
        reg = self.registry
        for k, v in counts.items():
            reg.set(f"hbm_{k}", v)
        reg.set("hbm_live_bytes", total)
        reg.set("hbm_live_arrays", n_arrays)
        reg.set_max("hbm_peak_bytes", total)   # the ONE high-water source
        census = dict(counts, total_bytes=total, arrays=n_arrays,
                      peak_bytes=int(reg.value("hbm_peak_bytes")))
        self.tracer.emit("hbm", step=self._step_idx, **census)
        return census

    # ------------------------------------------------------- aggregation --
    def aggregate(self) -> Dict[str, Any]:
        """Cross-host reduction of the step counters (ONE batched
        collective per reduction op via ``fleet.metrics
        .all_reduce_metrics``): global examples/tokens per second over the
        slowest replica's wall, plus per-replica straggler skew (max
        replica step-wall over the mean).  Identity in a single process
        (skew 1.0)."""
        from .distributed import env
        from .distributed.fleet.metrics.metric import all_reduce_metrics

        reg = self.registry
        wall = float(reg.histogram("step_seconds").snapshot()["sum"])
        local = {"steps": float(reg.value("train_steps")),
                 "examples": float(reg.value("train_examples")),
                 "tokens": float(reg.value("train_tokens")),
                 "step_wall_s": wall}
        sums = all_reduce_metrics(local, "sum")
        maxs = all_reduce_metrics({"step_wall_s": wall}, "max")
        world = max(int(env.get_world_size()), 1)
        wall_max = maxs["step_wall_s"]
        wall_mean = sums["step_wall_s"] / world
        out = {
            "world": world,
            "steps": sums["steps"],
            "examples": sums["examples"],
            "tokens": sums["tokens"],
            "global_examples_per_sec": (sums["examples"] / wall_max
                                        if wall_max > 0 else None),
            "global_tokens_per_sec": (sums["tokens"] / wall_max
                                      if wall_max > 0 else None),
            "straggler_skew": (wall_max / wall_mean
                               if wall_mean > 0 else None),
        }
        self.tracer.emit("aggregate", **out)
        return out

    # ----------------------------------------------------------- queries --
    def events(self, kind: Optional[str] = None):
        return self.tracer.events(kind)

    def summary(self) -> Dict[str, Any]:
        """One JSON-able snapshot — what ``bench.py`` attaches to gpt
        training BENCH rounds: step-wall percentiles, device-blocked
        percentiles, throughput, compile counts, watchdog/AMP counters,
        HBM peaks."""
        from .utils.stats import get_stat
        reg = self.registry
        step_evs = self.events("train_step")
        sync_evs = self.events("sync")
        step_sum = float(reg.histogram("step_seconds").snapshot()["sum"])
        sync_sum = float(
            reg.histogram("device_blocked_seconds").snapshot()["sum"])
        wall = step_sum + sync_sum
        tokens = int(reg.value("train_tokens"))
        examples = int(reg.value("train_examples"))
        return {
            "steps": int(reg.value("train_steps")),
            "step_wall_s": _percentiles([e["dur_s"] for e in step_evs]),
            "device_blocked_s": _percentiles(
                [e["dur_s"] for e in sync_evs]),
            "examples_per_sec": (examples / wall
                                 if wall > 0 and examples else None),
            "tokens_per_sec": (tokens / wall
                               if wall > 0 and tokens else None),
            "compile": {
                "misses": int(reg.value("compile_misses")),
                "hits": int(reg.value("compile_hits")),
                "wall_s": float(reg.value("compile_wall_seconds_sum")),
                "cold": int(reg.value("compile_cold")),
                "disk": int(reg.value("compile_disk")),
                "bucket_compiles": int(
                    get_stat("bucketize_bucket_compiles"))
                - self._bucket_compiles0,
            },
            "watchdog": {
                "non_finite": int(reg.value("watchdog_non_finite")),
                "loss_spikes": int(reg.value("watchdog_loss_spikes")),
                "last_loss": self.last_loss,
                "loss_ema": self._loss_ema,
            },
            "amp": {
                "found_inf": int(reg.value("amp_found_inf")),
                "scale_changes": int(reg.value("amp_scale_changes")),
                "scale": self._last_scale,
            },
            "hbm": {
                "peak_bytes": int(reg.value("hbm_peak_bytes")),
                "params_bytes": int(reg.value("hbm_params_bytes")),
                "opt_bytes": int(reg.value("hbm_opt_bytes")),
                "other_bytes": int(reg.value("hbm_other_bytes")),
            },
            "comm": self._comm_summary(),
            "mfu": self._mfu_summary(step_sum),
            "events_dropped": self.tracer.events_dropped,
        }

    def _mfu_summary(self, step_wall_s: float) -> Optional[Dict[str, Any]]:
        """Training-side MFU from the step program's cost analysis (None
        until a compile seam reported one): per-step model FLOPs × steps
        over the steady-state step wall, arithmetic intensity, and MFU
        against the tracer's configured peak."""
        cost = self._step_cost
        if cost is None:
            return None
        steps = int(self.registry.value("train_steps"))
        fps = (cost["flops"] * steps / step_wall_s
               if step_wall_s > 0 and steps else None)
        peak = self.tracer.peak_flops
        return {
            "model_flops_per_step": cost["flops"],
            "model_flops_per_s": fps,
            "arithmetic_intensity": (cost["flops"] / cost["bytes"]
                                     if cost["bytes"] else None),
            "peak_flops": peak,
            "mfu": (fps / peak if fps is not None and peak else None),
        }

    def _comm_summary(self) -> Optional[Dict[str, Any]]:
        """Aggregate grad-comm accounting (None when no policy reported):
        total pre/post wire bytes over the run and their ratio — the
        bytes-on-wire savings the active ``grad_comm`` policy delivers."""
        if self._comm_policy is None:
            return None
        reg = self.registry
        pre = int(reg.value("comm_pre_bytes"))
        post = int(reg.value("comm_post_bytes"))
        return {
            "policy": self._comm_policy,
            "steps": int(reg.value("comm_steps")),
            "pre_bytes": pre,
            "post_bytes": post,
            "savings": (round(pre / post, 3) if post else None),
        }

    # ----------------------------------------------------------- exports --
    def dump_jsonl(self, path: str) -> int:
        return self.tracer.dump_jsonl(path)

    def to_chrome_trace(self) -> Dict[str, Any]:
        return self.tracer.to_chrome_trace()

    def write_chrome_trace(self, path: str):
        self.tracer.write_chrome_trace(path)

    def prometheus_text(self, namespace: str = "paddle_tpu_train") -> str:
        return self.tracer.prometheus_text(namespace=namespace)


def _default_batch_info(args) -> Tuple[int, int]:
    """(examples, tokens) heuristic for an instrumented step's call args:
    the LARGEST array leaf among the non-state args is the input batch —
    its leading dim is examples; for 2-D (token-id) inputs tokens is
    batch × seq, otherwise 0 (an image batch has no token count)."""
    import jax
    best = None
    for leaf in jax.tree_util.tree_leaves(args[1:]):
        shape = getattr(leaf, "shape", None)
        if shape is None or len(shape) < 1:
            continue
        size = 1
        for d in shape:
            size *= int(d)
        if best is None or size > best[0]:
            best = (size, shape)
    if best is None:
        return 0, 0
    shape = best[1]
    examples = int(shape[0])
    tokens = examples * int(shape[1]) if len(shape) == 2 else 0
    return examples, tokens


def instrument_train_step(step: Callable, monitor: Optional[TrainMonitor],
                          name: str = "train",
                          batch_info: Optional[Callable] = None,
                          comm: Optional[Dict[str, Any]] = None) -> Callable:
    """Wrap a train-step callable with per-call TrainMonitor timing.

    ``monitor=None`` returns ``step`` UNCHANGED — the builders' zero-cost-
    off contract (no wrapper frame, no checks).  With a monitor, each call
    times host dispatch wall; the FIRST call blocks until ready and is
    recorded as this step's compile event ONLY (trace + XLA compile +
    first run — the same convention as ``jit.bucketize``; it never
    pollutes the step_seconds percentiles), so steady-state calls add NO
    synchronization and ``train_steps`` counts post-warmup steps.  The
    jit API surface (``lower`` /
    ``eval_shape`` / ``trace`` / ``clear_cache``) passes through to the
    SAME underlying program — cache keys and lowerings are identical with
    telemetry on or off.

    ``comm``: optional ``{"policy", "pre_bytes", "post_bytes"}`` dict (a
    ``grad_comm`` policy's wire estimate for one step's reduction) — each
    steady-state call additionally records a ``comm`` accounting event.

    With an active ``telemetry_memory.MemoryLedger`` the fresh state is
    re-registered after every call (donated state is rebuilt each step,
    so the previous ids go stale) — one ``is None`` check when no ledger
    is active, a tree flatten when one is."""
    if monitor is None:
        return step
    import jax
    first = [True]

    def _reregister_state(out):
        from .telemetry_memory import current_memory_ledger
        ml = current_memory_ledger()
        if ml is None:
            return
        state = out[0] if isinstance(out, tuple) and out else out
        if isinstance(state, dict):
            ml.register_train_state(state, name=name)

    @functools.wraps(step)
    def wrapped(*args, **kwargs):
        t0 = time.perf_counter()
        out = step(*args, **kwargs)
        _reregister_state(out)
        if first[0]:
            # the first call pays trace + XLA compile inside its dispatch
            # (jit blocks through compilation) — it becomes ONLY the compile
            # event, never a train_step sample, so step percentiles and
            # throughput measure steady state
            first[0] = False
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            cost = None
            if monitor.tracer.attribute_cost and hasattr(step, "lower"):
                # opt-in roofline attribution: the step's cost analysis,
                # digest-cached process-wide (hapi/dynamic_flops) — never
                # allowed to break the training loop
                try:
                    from .hapi.dynamic_flops import cost_of_lowered
                    cost = cost_of_lowered(step.lower(*args, **kwargs))
                except Exception:  # noqa: BLE001 — best-effort telemetry
                    logging.getLogger(__name__).debug(
                        "cost attribution failed for %s", name,
                        exc_info=True)
            monitor.record_compile((f"{name}_step",), dt, cost=cost)
            return out
        examples, tokens = (batch_info(args, kwargs)
                            if batch_info is not None
                            else _default_batch_info(args))
        monitor.record_step(time.perf_counter() - t0, trainer=name,
                            examples=examples, tokens=tokens)
        if comm is not None:
            monitor.record_comm(**comm)
        return out

    from .jit.functional import copy_jit_surface
    return copy_jit_surface(step, wrapped)


_PID = "paddle_tpu.serving"
_TRAIN_PID = "paddle_tpu.train"


def events_to_chrome(events: List[Dict[str, Any]],
                     timelines: Optional[List[Any]] = None
                     ) -> Dict[str, Any]:
    """Convert tracer events (+ optional timelines) to Chrome-trace JSON.
    Used by ``Tracer.to_chrome_trace`` and by ``chrome_trace_from_jsonl``
    for offline conversion of a JSONL dump."""
    out: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": _PID,
         "args": {"name": _PID}},
        {"name": "process_name", "ph": "M", "pid": _TRAIN_PID,
         "args": {"name": _TRAIN_PID}},
    ]
    for ev in events:
        us = ev["ts"] * 1e6
        if ev["kind"] == "tick":
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "dur_s")}
            out.append({"name": "tick", "cat": "scheduler", "ph": "X",
                        "pid": _PID, "tid": "scheduler",
                        "ts": us - ev.get("dur_s", 0.0) * 1e6,
                        "dur": ev.get("dur_s", 0.0) * 1e6, "args": args})
        elif ev["kind"] == "compile":
            if ev.get("hit"):
                continue                      # hits are noise on a timeline
            dur = ev.get("wall_s", 0.0) * 1e6
            out.append({"name": f"compile:{ev.get('key', '?')}",
                        "cat": "compile", "ph": "X", "pid": _PID,
                        "tid": "compile", "ts": us - dur, "dur": dur,
                        "args": {"engine": ev.get("engine")}})
        elif ev["kind"] == "request":
            out.append({"name": ev.get("what", "?"), "cat": "request",
                        "ph": "i", "s": "t", "pid": _PID,
                        "tid": f"req:{ev.get('rid')}", "ts": us,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("kind", "ts")}})
            if ev.get("what") == "admitted" and ev.get("span_id"):
                # flow FINISH: the engine end of the gateway's dispatch
                # arrow — same id (the dispatch-minted span) on both
                # sides, so Perfetto draws gateway row → engine row even
                # across merged multi-replica trace files
                out.append({"name": "request", "cat": "trace", "ph": "f",
                            "bp": "e", "id": ev["span_id"], "pid": _PID,
                            "tid": f"req:{ev.get('rid')}", "ts": us,
                            "args": {"trace_id": ev.get("trace_id")}})
        elif ev["kind"] == "gateway":
            # gateway actions are instants on their own scheduler row —
            # shed/reroute/drain markers line up against ticks and request
            # spans in the same Perfetto view
            out.append({"name": f"gateway:{ev.get('what', '?')}",
                        "cat": "gateway", "ph": "i", "s": "t",
                        "pid": _PID, "tid": "gateway", "ts": us,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("kind", "ts")}})
            if ev.get("what") == "dispatch" and ev.get("span_id"):
                # flow START keyed by the dispatch span id (see the
                # request-event "f" above)
                out.append({"name": "request", "cat": "trace", "ph": "s",
                            "id": ev["span_id"], "pid": _PID,
                            "tid": "gateway", "ts": us,
                            "args": {"trace_id": ev.get("trace_id"),
                                     "replica": ev.get("replica")}})
        elif ev["kind"] == "slo":
            # SLO alert transitions: instants on their own row, lined up
            # against the serving ticks they indict
            out.append({"name": f"slo:{ev.get('what', '?')}"
                        f":{ev.get('objective', '?')}",
                        "cat": "slo", "ph": "i", "s": "t",
                        "pid": _PID, "tid": "slo", "ts": us,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("kind", "ts")}})
        elif ev["kind"] in ("train_step", "sync", "profiler_step"):
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "dur_s")}
            dur = ev.get("dur_s", 0.0) * 1e6
            out.append({"name": ev["kind"], "cat": "train", "ph": "X",
                        "pid": _TRAIN_PID, "tid": ev["kind"],
                        "ts": us - dur, "dur": dur, "args": args})
        elif ev["kind"] in ("watchdog", "amp", "hbm", "aggregate", "comm"):
            name = ev.get("what", ev["kind"])
            out.append({"name": f"{ev['kind']}:{name}"
                        if "what" in ev else ev["kind"],
                        "cat": "train", "ph": "i", "s": "t",
                        "pid": _TRAIN_PID, "tid": ev["kind"], "ts": us,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("kind", "ts")}})
    for tl in timelines or []:
        spans = tl.spans() if hasattr(tl, "spans") else _dict_spans(tl)
        rid = tl.rid if hasattr(tl, "rid") else tl.get("rid")
        for sp in spans:
            out.append({"name": sp["name"], "cat": "request", "ph": "X",
                        "pid": _PID, "tid": f"req:{rid}",
                        "ts": sp["start"] * 1e6,
                        "dur": max(sp["end"] - sp["start"], 0.0) * 1e6,
                        "args": {"rid": rid}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _dict_spans(tl: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Span reconstruction for a ``timeline`` dict read back from JSONL
    (same shape RequestTimeline.spans produces)."""
    stamps = [tl.get("queued_at"), tl.get("admitted_at"),
              tl.get("first_token_at"), tl.get("retired_at")]
    stamps += [t for s in tl.get("preempted_spans", []) for t in s]
    known = [t for t in stamps if t is not None]
    last = max(known) if known else 0.0
    out = []
    for name, a, b in (("queued", tl.get("queued_at"), tl.get("admitted_at")),
                       ("prefill", tl.get("admitted_at"),
                        tl.get("first_token_at")),
                       ("decode", tl.get("first_token_at"),
                        tl.get("retired_at"))):
        if a is not None:
            out.append({"name": name, "start": a,
                        "end": b if b is not None else last})
    for s in tl.get("preempted_spans", []):
        if s and s[0] is not None:
            out.append({"name": "preempted", "start": s[0],
                        "end": s[1] if s[1] is not None else last})
    return out


def chrome_trace_from_jsonl(path: str) -> Dict[str, Any]:
    """Offline conversion: read a ``dump_jsonl`` file back into the same
    Chrome-trace JSON ``Tracer.to_chrome_trace`` produces live (used by
    ``tools/trace_to_chrome.py --engine-trace``)."""
    events: List[Dict[str, Any]] = []
    timelines: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if ev.get("kind") == "timeline":
                timelines.append(ev)
            else:
                events.append(ev)
    return events_to_chrome(events, timelines)
