"""Build/config introspection (reference: python/paddle/sysconfig.py)."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include() -> str:
    """Directory of C headers (the reference returns its bundled
    paddle/include; this build's native surface is the csrc tree)."""
    import paddle_tpu
    return os.path.join(os.path.dirname(paddle_tpu.__file__), "csrc")


def get_lib() -> str:
    """Directory of compiled shared libraries."""
    import paddle_tpu
    return os.path.join(os.path.dirname(paddle_tpu.__file__), "csrc", "_build")
