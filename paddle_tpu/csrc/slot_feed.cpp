// Native dense-slot file parser — the data-feed hot loop.
//
// ≙ reference framework/data_feed.cc MultiSlotDataFeed/InMemoryDataFeed:
// the reference parses example files in C++ worker threads because a Python
// float() per value starves the trainer; the TPU build keeps that division
// of labor (parse natively, batch in Python, compute in XLA).
//
// Format handled here: one example per line, whitespace-separated numbers,
// last column = integer label (paddle_tpu.io.dataset's default).  The file
// is mmap'd and split at line boundaries into one chunk per thread.
//
// Exposed C ABI (ctypes, no pybind11 in the image):
//   slot_feed_dims(path, *rows, *cols)         -> 0 ok / -errno
//   slot_feed_parse(path, feats, labels, rows, cols, threads) -> rows parsed
// feats must hold rows*(cols-1) floats, labels rows int64.

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;

  bool open_file(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size == 0) return false;
    size = static_cast<size_t>(st.st_size);
    void* p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) return false;
    data = static_cast<const char*>(p);
    return true;
  }

  ~Mapped() {
    if (data) munmap(const_cast<char*>(data), size);
    if (fd >= 0) ::close(fd);
  }
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// fast float: sign, integer, fraction; tokens with an exponent (rare in
// slot files) re-parse via strtod on a bounded stack copy
inline const char* parse_num(const char* p, const char* end, double* out) {
  p = skip_ws(p, end);
  if (p >= end || *p == '\n') return nullptr;
  const char* tok = p;
  bool neg = false;
  if (*p == '-' || *p == '+') { neg = (*p == '-'); ++p; }
  double v = 0.0;
  bool saw_digit = false;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10.0 + (*p - '0'); ++p; saw_digit = true;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      v += (*p - '0') * scale; scale *= 0.1; ++p; saw_digit = true;
    }
  }
  if (!saw_digit) return nullptr;  // rejects '+', '-', '.', '' like float()
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    if (p < end && (*p == '-' || *p == '+')) ++p;
    const char* exp_digits = p;
    while (p < end && *p >= '0' && *p <= '9') ++p;
    if (p == exp_digits) return nullptr;  // '1e', '1e+' — float() rejects
    char buf[64];
    size_t n = static_cast<size_t>(p - tok);
    if (n >= sizeof(buf)) return nullptr;
    memcpy(buf, tok, n);
    buf[n] = '\0';
    char* q = nullptr;
    *out = strtod(buf, &q);
    if (q != buf + n) return nullptr;
    return p;
  }
  *out = neg ? -v : v;
  return p;
}

// count columns on the first line
int64_t count_cols(const char* p, const char* end) {
  int64_t cols = 0;
  while (p < end && *p != '\n') {
    p = skip_ws(p, end);
    if (p >= end || *p == '\n') break;
    ++cols;
    while (p < end && *p != ' ' && *p != '\t' && *p != '\r' && *p != '\n') ++p;
  }
  return cols;
}

int64_t count_rows(const char* p, const char* end) {
  int64_t rows = 0;
  const char* q = p;
  while (q < end) {
    const char* nl = static_cast<const char*>(memchr(q, '\n', end - q));
    const char* line_end = nl ? nl : end;
    const char* s = skip_ws(q, line_end);
    if (s < line_end) ++rows;  // non-blank line
    if (!nl) break;
    q = nl + 1;
  }
  return rows;
}

struct ChunkResult {
  int64_t rows = 0;
  int bad = 0;
};

void parse_chunk(const char* p, const char* end, int64_t cols, float* feats,
                 long long* labels, ChunkResult* res) {
  int64_t row = 0;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* s = skip_ws(p, line_end);
    if (s < line_end) {
      double v = 0.0;
      for (int64_t c = 0; c < cols; ++c) {
        const char* next = parse_num(s, line_end, &v);
        if (next == nullptr) { res->bad = 1; return; }
        if (c < cols - 1) {
          feats[row * (cols - 1) + c] = static_cast<float>(v);
        } else {
          labels[row] = static_cast<long long>(v);
        }
        s = next;
      }
      // ragged lines (extra columns) are data corruption, not padding
      if (skip_ws(s, line_end) < line_end) { res->bad = 1; return; }
      ++row;
    }
    if (!nl) break;
    p = nl + 1;
  }
  res->rows = row;
}

}  // namespace

extern "C" {

int slot_feed_dims(const char* path, int64_t* rows, int64_t* cols) {
  Mapped m;
  if (!m.open_file(path)) return -(errno ? errno : 1);
  *cols = count_cols(m.data, m.data + m.size);
  *rows = count_rows(m.data, m.data + m.size);
  return 0;
}

int64_t slot_feed_parse(const char* path, float* feats, long long* labels,
                        int64_t rows, int64_t cols, int threads) {
  Mapped m;
  if (!m.open_file(path)) return -(errno ? errno : 1);
  const char* base = m.data;
  const char* end = m.data + m.size;
  if (threads < 1) threads = 1;
  if (threads > 64) threads = 64;

  // split at line boundaries
  std::vector<const char*> starts{base};
  for (int t = 1; t < threads; ++t) {
    const char* guess = base + (m.size * t) / threads;
    const char* nl = static_cast<const char*>(memchr(guess, '\n', end - guess));
    starts.push_back(nl ? nl + 1 : end);
  }
  starts.push_back(end);

  // each chunk first counts its rows (cheap memchr scan) so outputs land at
  // the right offset without a serial pass
  std::vector<int64_t> chunk_rows(threads);
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < threads; ++t)
      ts.emplace_back([&, t] { chunk_rows[t] = count_rows(starts[t], starts[t + 1]); });
    for (auto& th : ts) th.join();
  }
  std::vector<int64_t> offs(threads + 1, 0);
  for (int t = 0; t < threads; ++t) offs[t + 1] = offs[t] + chunk_rows[t];
  if (offs[threads] > rows) return -E2BIG;

  std::vector<ChunkResult> res(threads);
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < threads; ++t)
      ts.emplace_back([&, t] {
        parse_chunk(starts[t], starts[t + 1], cols,
                    feats + offs[t] * (cols - 1), labels + offs[t], &res[t]);
      });
    for (auto& th : ts) th.join();
  }
  int64_t total = 0;
  for (int t = 0; t < threads; ++t) {
    if (res[t].bad) return -EINVAL;
    total += res[t].rows;
  }
  return total;
}

}  // extern "C"
