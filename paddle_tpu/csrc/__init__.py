"""Native (C++) runtime components.

≙ the reference's C++ runtime layer (SURVEY §2.1): here only the pieces
Python+JAX cannot express well get native code — currently the DataLoader
shared-memory ring (reader_py.cc BlockingQueue + mmap_allocator.cc analog).
Kernels stay Pallas (Python-authored, Mosaic-compiled), per SURVEY §7.

Build model: compiled on first use with g++ into ``_build/`` next to this
file (no pip; the image bans installs), cached by source mtime.  Loading is
ctypes — no pybind11 in the image.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()
_LIBS = {}


class NativeBuildError(RuntimeError):
    pass


def load_library(name: str):
    """Compile (if stale) and dlopen csrc/<name>.cpp -> _build/lib<name>.so."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_HERE, f"{name}.cpp")
        out = os.path.join(_BUILD, f"lib{name}.so")
        if not os.path.exists(out) or \
                os.path.getmtime(out) < os.path.getmtime(src):
            os.makedirs(_BUILD, exist_ok=True)
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
                   "-o", out + ".tmp", "-lpthread", "-lrt"]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"building {name}: {' '.join(cmd)}\n{proc.stderr[-2000:]}")
            os.replace(out + ".tmp", out)
        lib = ctypes.CDLL(out)
        _LIBS[name] = lib
        return lib
