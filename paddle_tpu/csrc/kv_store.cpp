// TCP key-value store for multi-host rendezvous/elastic membership.
//
// ≙ the reference's etcd dependency (fleet/elastic/manager.py:130 uses an
// etcd client for host registration + heartbeat leases; gen_comm_id_helper.cc
// bootstraps NCCL ids over raw TCP).  TPU pods have no ambient etcd, so the
// framework carries its own single-binary store: the launcher's rank-0 hosts
// it in-process, every worker dials it.  Original poll()-based design — one
// thread, no dependencies.
//
// Wire protocol (little-endian, persistent connections; requests on one
// connection must be serialized — a parked WAIT defers its response, so
// pipelining another request behind a WAIT would desequence replies.  The
// Python client enforces this with a per-connection lock):
//   request : u8 op | u32 klen | u32 vlen | key bytes | val bytes
//   response: u8 status (0 ok, 1 missing) | u32 vlen | val bytes
//   ops: 0 SET (resp empty)            1 GET (resp value or missing)
//        2 ADD (val = i64 delta; stored value is i64; resp new i64)
//        3 WAIT (no resp until key exists; resp value once set)
//        4 DEL (resp empty)            5 LIST (key = prefix; resp value is
//              a packed sequence of u32 klen|key|u32 vlen|val entries)
//
// Exported C API (ctypes):
//   void* kv_server_start(int port)   // port 0 = auto-assign
//   int   kv_server_port(void*)
//   void  kv_server_stop(void*)

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace {

struct Conn {
  int fd;
  std::string rbuf;   // bytes read, not yet parsed
  std::string wbuf;   // bytes to write
  bool closing = false;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  pthread_t thread{};
  std::map<int, Conn> conns;
  std::map<std::string, std::string> kv;
  std::map<std::string, std::vector<int>> waiters;  // key -> fds parked in WAIT
};

void put_u32(std::string* s, uint32_t v) { s->append((const char*)&v, 4); }

void respond(Conn* c, uint8_t status, const std::string& val) {
  c->wbuf.push_back((char)status);
  put_u32(&c->wbuf, (uint32_t)val.size());
  c->wbuf += val;
}

// Reply to every connection parked in WAIT(key) with val, then clear them.
void notify_waiters(Server* srv, const std::string& key,
                    const std::string& val) {
  auto w = srv->waiters.find(key);
  if (w == srv->waiters.end()) return;
  for (int wfd : w->second) {
    auto it = srv->conns.find(wfd);
    if (it != srv->conns.end()) respond(&it->second, 0, val);
  }
  srv->waiters.erase(w);
}

// Parse and execute every complete request in c->rbuf.  Returns false on a
// malformed frame (connection is then closed).
bool handle_requests(Server* srv, Conn* c) {
  for (;;) {
    if (c->rbuf.size() < 9) return true;
    const char* p = c->rbuf.data();
    uint8_t op = (uint8_t)p[0];
    uint32_t klen, vlen;
    memcpy(&klen, p + 1, 4);
    memcpy(&vlen, p + 5, 4);
    if (klen > (1u << 20) || vlen > (1u << 26)) return false;  // sanity caps
    size_t need = 9 + (size_t)klen + vlen;
    if (c->rbuf.size() < need) return true;
    std::string key(p + 9, klen);
    std::string val(p + 9 + klen, vlen);
    c->rbuf.erase(0, need);

    switch (op) {
      case 0: {  // SET
        srv->kv[key] = val;
        respond(c, 0, "");
        notify_waiters(srv, key, val);
        break;
      }
      case 1: {  // GET
        auto it = srv->kv.find(key);
        if (it == srv->kv.end()) respond(c, 1, "");
        else respond(c, 0, it->second);
        break;
      }
      case 2: {  // ADD
        if (val.size() != 8) return false;
        int64_t delta;
        memcpy(&delta, val.data(), 8);
        int64_t cur = 0;
        auto it = srv->kv.find(key);
        if (it != srv->kv.end()) {
          // ADD on a key holding a non-counter value (e.g. a string SET by
          // rendezvous) is a protocol error, not a silent reset-to-zero —
          // close the connection as malformed rather than clobber the value.
          if (it->second.size() != 8) return false;
          memcpy(&cur, it->second.data(), 8);
        }
        cur += delta;
        std::string stored((const char*)&cur, 8);
        srv->kv[key] = stored;
        respond(c, 0, stored);
        notify_waiters(srv, key, stored);  // ADD also satisfies waiters
        break;
      }
      case 3: {  // WAIT
        auto it = srv->kv.find(key);
        if (it != srv->kv.end()) respond(c, 0, it->second);
        else srv->waiters[key].push_back(c->fd);
        break;
      }
      case 4: {  // DEL
        srv->kv.erase(key);
        respond(c, 0, "");
        break;
      }
      case 5: {  // LIST by prefix
        std::string out;
        for (auto it = srv->kv.lower_bound(key); it != srv->kv.end(); ++it) {
          if (it->first.compare(0, key.size(), key) != 0) break;
          put_u32(&out, (uint32_t)it->first.size());
          out += it->first;
          put_u32(&out, (uint32_t)it->second.size());
          out += it->second;
        }
        respond(c, 0, out);
        break;
      }
      default:
        return false;
    }
  }
}

void drop_conn(Server* srv, int fd) {
  for (auto& kvp : srv->waiters) {
    auto& v = kvp.second;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  close(fd);
  srv->conns.erase(fd);
}

void* serve_loop(void* arg) {
  Server* srv = (Server*)arg;
  std::vector<pollfd> pfds;
  char buf[65536];
  while (!srv->stop.load()) {
    pfds.clear();
    pfds.push_back({srv->listen_fd, POLLIN, 0});
    for (auto& kvp : srv->conns) {
      short ev = POLLIN;
      if (!kvp.second.wbuf.empty()) ev |= POLLOUT;
      pfds.push_back({kvp.first, ev, 0});
    }
    int rc = poll(pfds.data(), pfds.size(), 200 /*ms: bounded stop latency*/);
    if (rc < 0) continue;
    if (pfds[0].revents & POLLIN) {
      int fd = accept(srv->listen_fd, nullptr, nullptr);
      if (fd >= 0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // non-blocking: a slow client that stops reading must get EAGAIN,
        // not stall the single server thread pod-wide
        fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
        srv->conns[fd] = Conn{fd};
      }
    }
    std::vector<int> dead;
    for (size_t i = 1; i < pfds.size(); ++i) {
      int fd = pfds[i].fd;
      auto it = srv->conns.find(fd);
      if (it == srv->conns.end()) continue;
      Conn* c = &it->second;
      if (pfds[i].revents & (POLLERR | POLLHUP)) { dead.push_back(fd); continue; }
      if (pfds[i].revents & POLLIN) {
        ssize_t n = recv(fd, buf, sizeof(buf), 0);
        if (n == 0 ||
            (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
          dead.push_back(fd);
          continue;
        }
        if (n > 0) {
          c->rbuf.append(buf, n);
          if (!handle_requests(srv, c)) { dead.push_back(fd); continue; }
        }
      }
      if (!c->wbuf.empty()) {
        ssize_t n = send(fd, c->wbuf.data(), c->wbuf.size(), MSG_NOSIGNAL);
        if (n > 0) c->wbuf.erase(0, n);
        else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
          dead.push_back(fd);
          continue;  // don't let the wbuf cap below double-add this fd
        }
      }
      // rbuf is bounded by the frame sanity caps, but wbuf is not: a client
      // that stops reading while piling up LIST/WAIT responses would grow
      // server memory without limit.  4x the max frame size is far beyond
      // any legitimate backlog.
      if (c->wbuf.size() > (4u << 26)) dead.push_back(fd);
    }
    for (int fd : dead) drop_conn(srv, fd);
  }
  for (auto& kvp : srv->conns) close(kvp.first);
  srv->conns.clear();
  return nullptr;
}

}  // namespace

extern "C" {

void* kv_server_start(int port) {
  Server* srv = new Server();
  srv->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) { delete srv; return nullptr; }
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(srv->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(srv->listen_fd, 128) != 0) {
    close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv->listen_fd, (sockaddr*)&addr, &alen);
  srv->port = ntohs(addr.sin_port);
  if (pthread_create(&srv->thread, nullptr, serve_loop, srv) != 0) {
    close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  return srv;
}

int kv_server_port(void* h) { return h ? ((Server*)h)->port : -1; }

void kv_server_stop(void* h) {
  if (!h) return;
  Server* srv = (Server*)h;
  srv->stop.store(true);
  pthread_join(srv->thread, nullptr);
  close(srv->listen_fd);
  delete srv;
}

}  // extern "C"
