// Shared-memory ring buffer for DataLoader worker->main batch transport.
//
// TPU-native runtime analog of the reference's C++ data path:
//   - pybind/reader_py.cc BlockingQueue (bounded, blocking push/pop)
//   - memory/allocation/mmap_allocator.cc (shared-memory tensors between
//     dataloader worker processes and the trainer)
// Here both collapse into one native component: a process-shared ring of
// length-prefixed messages with pthread mutex/condvar synchronization
// (PTHREAD_PROCESS_SHARED), mapped via shm_open/mmap.  Workers serialize
// collated numpy batches into the ring; the main process pops them without
// pickling through a pipe (the multiprocessing.Queue bottleneck).
//
// Message framing: [u64 len][len bytes], contiguous, wrapping at the end of
// the data region via a u64 sentinel len = WRAP_MARK.
//
// Built with: g++ -O2 -shared -fPIC shm_ring.cpp -o libshm_ring.so -lpthread -lrt

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t WRAP_MARK = ~0ull;

struct RingHeader {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;   // bytes in the data region
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes currently stored (incl. framing)
  uint32_t closed;
  uint32_t magic;
};

constexpr uint32_t MAGIC = 0x52494e47;  // "RING"

inline char* data_of(RingHeader* h) {
  return reinterpret_cast<char*>(h) + sizeof(RingHeader);
}

void abstime_in(timespec* ts, long timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a ring of `capacity` data bytes.
// Returns the mapped header or nullptr.
void* shm_ring_open(const char* name, uint64_t capacity, int owner) {
  const uint64_t total = sizeof(RingHeader) + capacity;
  int fd;
  if (owner) {
    shm_unlink(name);  // stale ring from a crashed run
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;
    if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<RingHeader*>(mem);
  if (owner) {
    std::memset(h, 0, sizeof(RingHeader));
    h->capacity = capacity;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->not_empty, &ca);
    pthread_cond_init(&h->not_full, &ca);
    h->magic = MAGIC;
  } else if (h->magic != MAGIC) {
    munmap(mem, total);
    return nullptr;
  }
  return mem;
}

static int lock_robust(RingHeader* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {  // a worker died holding the lock
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// Placement decision under the lock.  The occupied region is
// [head, head+used) mod capacity; tail == (head+used) % capacity.
// rc: 1 = fits at tail, 2 = fits at offset 0 after wasting the end run,
// 0 = does not fit yet.
static int placement(RingHeader* h, uint64_t frame) {
  if (h->used == 0) {
    h->head = h->tail = 0;  // opportunistic reset: whole region contiguous
    return frame <= h->capacity ? 1 : 0;
  }
  const bool split_free = (h->head + h->used) < h->capacity;  // tail >= head
  if (split_free) {
    if (h->capacity - h->tail >= frame) return 1;
    if (h->head >= frame) return 2;
    return 0;
  }
  // free region is the single run [tail, head)
  return (h->head - h->tail >= frame) ? 1 : 0;
}

// rc: 0 ok, -1 timeout, -2 closed, -3 message larger than capacity, -4 error
int shm_ring_push(void* ring, const void* buf, uint64_t len, long timeout_ms) {
  auto* h = static_cast<RingHeader*>(ring);
  const uint64_t frame = len + sizeof(uint64_t);
  if (frame > h->capacity) return -3;
  if (lock_robust(h) != 0) return -4;
  timespec ts;
  abstime_in(&ts, timeout_ms);
  int place;
  while ((place = placement(h, frame)) == 0 && !h->closed) {
    if (timeout_ms < 0) {
      pthread_cond_wait(&h->not_full, &h->mu);
    } else if (pthread_cond_timedwait(&h->not_full, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  char* d = data_of(h);
  uint64_t tail = h->tail;
  if (place == 2) {  // wrap: waste the run [tail, capacity)
    if (h->capacity - tail >= sizeof(uint64_t)) {
      std::memcpy(d + tail, &WRAP_MARK, sizeof(uint64_t));
    }
    h->used += h->capacity - tail;
    tail = 0;
  }
  std::memcpy(d + tail, &len, sizeof(uint64_t));
  std::memcpy(d + tail + sizeof(uint64_t), buf, len);
  h->tail = (tail + frame) % h->capacity;
  h->used += frame;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Returns payload length (>=0); -1 timeout, -2 closed-and-empty,
// -4 error, -5 caller buffer too small (length written to *out_len).
int64_t shm_ring_pop(void* ring, void* buf, uint64_t buflen, long timeout_ms,
                     uint64_t* out_len) {
  auto* h = static_cast<RingHeader*>(ring);
  if (lock_robust(h) != 0) return -4;
  timespec ts;
  abstime_in(&ts, timeout_ms);
  while (h->used == 0 && !h->closed) {
    if (timeout_ms < 0) {
      pthread_cond_wait(&h->not_empty, &h->mu);
    } else if (pthread_cond_timedwait(&h->not_empty, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->used == 0 && h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  char* d = data_of(h);
  uint64_t head = h->head;
  uint64_t len;
  if (h->capacity - head >= sizeof(uint64_t)) {
    std::memcpy(&len, d + head, sizeof(uint64_t));
    if (len == WRAP_MARK) {
      h->used -= h->capacity - head;
      head = 0;
      std::memcpy(&len, d, sizeof(uint64_t));
    }
  } else {  // frame didn't fit at the end: writer wrapped without a marker
    h->used -= h->capacity - head;
    head = 0;
    std::memcpy(&len, d, sizeof(uint64_t));
  }
  if (out_len) *out_len = len;
  if (len > buflen) {
    pthread_mutex_unlock(&h->mu);
    return -5;
  }
  std::memcpy(buf, d + head + sizeof(uint64_t), len);
  h->head = (head + sizeof(uint64_t) + len) % h->capacity;
  h->used -= sizeof(uint64_t) + len;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

void shm_ring_close(void* ring) {
  auto* h = static_cast<RingHeader*>(ring);
  if (lock_robust(h) != 0) return;
  h->closed = 1;
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
  pthread_mutex_unlock(&h->mu);
}

uint64_t shm_ring_used(void* ring) {
  auto* h = static_cast<RingHeader*>(ring);
  if (lock_robust(h) != 0) return 0;
  uint64_t u = h->used;
  pthread_mutex_unlock(&h->mu);
  return u;
}

void shm_ring_detach(void* ring, uint64_t capacity) {
  munmap(ring, sizeof(RingHeader) + capacity);
}

void shm_ring_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
