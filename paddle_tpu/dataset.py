"""Legacy ``paddle.dataset`` namespace (reference: python/paddle/dataset/ —
mnist/cifar/imdb/uci_housing/... reader-creator modules).  The modern
map-style classes live in vision.datasets and text; this module re-exports
them under the legacy names so ``paddle.dataset.<name>`` code resolves."""

from .text import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing,  # noqa: F401
                   WMT14, WMT16)
from .vision.datasets import MNIST, Cifar10, Cifar100  # noqa: F401

__all__ = ["MNIST", "Cifar10", "Cifar100", "Imdb", "Imikolov", "UCIHousing",
           "Conll05st", "Movielens", "WMT14", "WMT16"]
