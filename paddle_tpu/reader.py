"""Reader decorators (reference: python/paddle/reader/decorator.py —
map_readers/shuffle/chain/compose/buffered/cache...).  These operate on
"reader creators": zero-arg callables returning iterators."""

from __future__ import annotations

import queue
import random as _random
import threading
from typing import Callable

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "cache", "firstn", "xmap_readers"]


def map_readers(func: Callable, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def shuffle(reader, buf_size: int):
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    def chained():
        for r in readers:
            yield from r()
    return chained


def compose(*readers, check_alignment: bool = True):
    def composed():
        its = [r() for r in readers]
        while True:
            outs = []
            stopped = 0
            for it in its:
                try:
                    outs.append(next(it))
                except StopIteration:
                    stopped += 1
            if stopped:
                if check_alignment and 0 < stopped < len(its):
                    raise RuntimeError("readers have different lengths")
                return
            yield tuple(o if isinstance(o, tuple) else (o,) for o in outs) \
                if len(outs) > 1 else outs[0]
    return composed


def buffered(reader, size: int):
    """Prefetch up to ``size`` items on a background thread.  The
    consumer-blocked queue wait reports to the active goodput ledger as
    ``data_wait`` (``telemetry_ledger``) — one is-None check per item when
    no ledger is active."""
    import time

    from .telemetry_ledger import current_ledger

    END = object()

    def buffered_reader():
        q: "queue.Queue" = queue.Queue(maxsize=size)

        def fill():
            try:
                for e in reader():
                    q.put(e)
            finally:
                q.put(END)

        threading.Thread(target=fill, daemon=True).start()
        while True:
            led = current_ledger()
            if led is None:
                e = q.get()
            else:
                t0 = time.perf_counter()
                e = q.get()
                led.record("data_wait", time.perf_counter() - t0)
            if e is END:
                return
            yield e

    return buffered_reader


def cache(reader):
    data = []
    filled = [False]

    def cached():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        yield from data
    return cached


def firstn(reader, n: int):
    def firstn_reader():
        for i, e in enumerate(reader()):
            if i >= n:
                return
            yield e
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-parallel map over a reader (reference xmap_readers)."""
    END = object()

    def xreader():
        in_q: "queue.Queue" = queue.Queue(buffer_size)
        out_q: "queue.Queue" = queue.Queue(buffer_size)

        def feed():
            for i, e in enumerate(reader()):
                in_q.put((i, e))
            for _ in range(process_num):
                in_q.put(END)

        def work():
            while True:
                item = in_q.get()
                if item is END:
                    out_q.put(END)
                    return
                i, e = item
                out_q.put((i, mapper(e)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        done = 0
        pending = {}
        next_i = 0
        while done < process_num:
            item = out_q.get()
            if item is END:
                done += 1
                continue
            i, e = item
            if not order:
                yield e
            else:
                pending[i] = e
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader
