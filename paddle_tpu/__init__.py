"""paddle_tpu — a TPU-native deep learning framework.

A from-scratch framework with the capabilities of the reference
(PaddlePaddle, /root/reference) re-designed for TPU: JAX/XLA is the compute
and compilation substrate, Pallas provides fused kernels, and distribution
is expressed as sharding over a ``jax.sharding.Mesh`` rather than explicit
communication ops.

Two execution modes mirror the reference's dygraph/static split:
- **eager**: ``Tensor`` wrappers with a tape-based autograd (imperative UX);
- **traced**: the same model code jit-compiled over a parameter pytree
  (``paddle_tpu.jit`` / hapi ``Model`` / fleet use this path for speed).
"""

# Honor JAX_PLATFORMS=cpu even when a TPU PJRT plugin's sitecustomize
# imported jax at interpreter startup and force-selected its own platform
# (the env var is latched too late in that case; jax.config is not — legal
# until the first backend initializes).  Without this, `JAX_PLATFORMS=cpu
# python train.py` hangs dialing the TPU tunnel on plugin machines.
import os as _os  # isort: skip

if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    import jax as _jax  # isort: skip
    try:
        _jax.config.update("jax_platforms", "cpu")
    # tpulint: disable=silent-except(backend already initialized means the config is already right or already latched; logging is not configured this early in import)
    except Exception:
        pass
    _os.environ.setdefault("PADDLE_TPU_PLATFORM", "cpu")

from . import core  # isort: skip  (must init flags first)
from . import tensor as tensor_api
from .core import (Parameter, Tensor, get_default_dtype, get_device, get_flags,  # noqa: F401
                   no_grad, seed, set_default_dtype, set_device, set_flags, to_tensor)
from .core.autograd import enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .core.device import (device_count, is_compiled_with_cuda,  # noqa: F401
                          is_compiled_with_tpu, synchronize)
from .core.dtype import (bfloat16, bool_, complex64, complex128, float16, float32,  # noqa: F401
                         float64, int8, int16, int32, int64, uint8)
from .core.rng import get_rng_state, set_rng_state  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import cast, is_tensor, rank, shape  # noqa: F401

__version__ = "0.1.0"

bool = bool_  # noqa: A001
reverse = flip  # noqa: F405 — fluid-era alias (reference fluid/layers reverse)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """``paddle.grad`` parity (reference: imperative/partial_grad_engine.cc).

    Computes grads of ``outputs`` w.r.t. ``inputs`` without touching ``.grad``.
    """
    from .core import autograd as _autograd
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [(t._grad, t._node) for t in inputs]
    for t in inputs:
        t._grad = None
    capture = {id(t): t for t in inputs}
    try:
        for i, o in enumerate(outputs):
            g = None if grad_outputs is None else grad_outputs[i]
            _autograd.backward(o, g, retain_graph=True, capture=capture,
                               accumulate_leaves=False)
        results = []
        for t, (old, _) in zip(inputs, saved):
            if t._grad is None:
                if not allow_unused:
                    raise RuntimeError("one of the inputs received no gradient; "
                                       "pass allow_unused=True to permit this")
                results.append(None)
            else:
                results.append(Tensor(t._grad))
    finally:
        for t, (old, node) in zip(inputs, saved):
            t._grad = old
    return results


# Submodules imported lazily to keep import time low and avoid cycles.
_LAZY = ("nn", "optimizer", "amp", "metric", "io", "vision", "distributed", "jit",
         "static", "hapi", "ops", "models", "distribution", "profiler", "text",
         "incubate", "utils", "autograd", "regularizer", "callbacks", "linalg", "fft",
         "signal", "sparse", "onnx", "device", "framework", "inference",
         "quantization", "compat", "sysconfig", "hub", "reader", "dataset",
         "serving", "telemetry", "gateway", "faults", "simulation",
         "autoscaler")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    alias = _TOP_ALIASES.get(name)
    if alias is not None:
        import importlib
        obj = getattr(importlib.import_module(alias[0], __name__), alias[1])
        globals()[name] = obj
        return obj
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def save(obj, path, protocol=4, **configs):
    from .framework import io as _io
    return _io.save(obj, path, protocol=protocol, **configs)


def load(path, **configs):
    from .framework import io as _io
    return _io.load(path, **configs)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.model_summary import summary as _summary
    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.dynamic_flops import flops as _flops
    return _flops(net, input_size, custom_ops, print_detail)


def enable_static():
    from . import static as _static
    _static._enable()


def disable_static():
    from . import static as _static
    _static._disable()


def in_dynamic_mode():
    from . import static as _static
    return not _static._enabled()


def in_dygraph_mode():
    """Legacy alias (reference fluid.framework.in_dygraph_mode)."""
    return in_dynamic_mode()


enable_dygraph = disable_static
disable_dygraph = enable_static


# ------------------------------------------------------------------ places
def CPUPlace():
    from .core.device import Place
    return Place("cpu")


def TPUPlace(dev_id: int = 0):
    from .core.device import Place
    return Place(f"tpu:{dev_id}")


def CUDAPlace(dev_id: int = 0):
    raise RuntimeError(
        "paddle_tpu has no CUDA devices; use paddle.TPUPlace()/CPUPlace() or "
        "paddle.set_device('tpu')")


def CUDAPinnedPlace():
    raise RuntimeError("paddle_tpu has no CUDA pinned memory; host numpy "
                       "arrays transfer via device_put")


def NPUPlace(dev_id: int = 0):
    raise RuntimeError("paddle_tpu is not compiled with NPU support")


def XPUPlace(dev_id: int = 0):
    raise RuntimeError("paddle_tpu is not compiled with XPU support")


# ------------------------------------------------- legacy/top-level aliases
def get_cudnn_version():
    return None


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def get_cuda_rng_state():
    """No CUDA generators in this build; returns [] (shape-compatible with
    the reference's per-device state list)."""
    return []


def set_cuda_rng_state(state_list):
    if state_list:
        raise ValueError("no CUDA generators exist in a TPU build")


def disable_signal_handler():
    """The reference unhooks its C++ signal handlers; none are installed
    here, so this is a documented no-op."""


def monkey_patch_math_varbase():
    """Tensor operator methods are installed at class definition in this
    framework; retained as a no-op for API parity."""


def monkey_patch_variable():
    """See monkey_patch_math_varbase."""


def tolist(x):
    return x.tolist() if hasattr(x, "tolist") else list(x)


def crop_tensor(x, shape=None, offsets=None, name=None):
    from .tensor import crop
    return crop(x, shape=shape, offsets=offsets)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .static import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


# binds the NAME to the function after the submodule import, so
# ``paddle.batch`` is the callable (the submodule stays importable as
# ``paddle_tpu.batch`` via sys.modules)
from .batch import batch  # noqa: E402,F401

# name → (module, attr) resolved on first access through __getattr__
import numpy as _np  # noqa: E402

dtype = _np.dtype  # paddle.dtype: dtype objects are numpy/jnp dtypes here

_TOP_ALIASES = {
    "Model": (".hapi", "Model"),
    "DataParallel": (".distributed", "DataParallel"),
    "ParamAttr": (".framework.param_attr", "ParamAttr"),
    "VarBase": (".core.tensor", "Tensor"),   # legacy dygraph tensor name
}
