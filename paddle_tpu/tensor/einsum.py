"""Einsum (reference: python/paddle/tensor/einsum.py) — delegate to XLA's einsum."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply


def einsum(equation, *operands):
    return apply(lambda ops: jnp.einsum(equation, *ops), list(operands),
                 name="einsum")
