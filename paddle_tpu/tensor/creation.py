"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, apply, to_tensor  # noqa: F401


def _dt(dtype, default_float=True):
    if dtype is None:
        return get_default_dtype() if default_float else convert_dtype("int64")
    return convert_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_to_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_to_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = getattr(fill_value, "_data", fill_value)
    if dtype is None:
        return Tensor(jnp.full(_to_shape(shape), fill_value))
    return Tensor(jnp.full(_to_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return apply(lambda a: jnp.zeros_like(a, dtype=convert_dtype(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply(lambda a: jnp.ones_like(a, dtype=convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply(lambda a, f: jnp.full_like(a, f, dtype=convert_dtype(dtype)), x, fill_value)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = getattr(start, "_data", start)
    end = getattr(end, "_data", end)
    step = getattr(step, "_data", step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        py = (start, end, step)
        dtype = convert_dtype("int64") if all(isinstance(v, (int, np.integer)) for v in py) else get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(_u(start), _u(stop), int(_u(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(_u(start), _u(stop), int(_u(num)), base=_u(base), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(*d.shape, k=offset, dtype=bool)
            return jnp.where(mask, d, padding_value)
        return jnp.diag(a, k=offset)
    return apply(f, x)


def diagflat(x, offset=0, name=None):
    return apply(lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply(lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply(lambda a: jnp.triu(a, k=diagonal), x)


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    return apply(lambda *xs: jnp.meshgrid(*xs, indexing="ij"), *tensors)


def assign(x, output=None):
    out = apply(lambda a: a + 0 if jnp.issubdtype(jnp.asarray(a).dtype, jnp.number) else jnp.asarray(a),
                x if isinstance(x, Tensor) else to_tensor(np.asarray(x)))
    if output is not None:
        output._adopt(out)
        return output
    return out


def clone(x, name=None):
    return apply(lambda a: a + 0, x)


def tolist(x):
    return x.tolist()


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=convert_dtype("int64")))


def _u(v):
    return getattr(v, "_data", v)


def _to_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(_u(s)) for s in shape)
