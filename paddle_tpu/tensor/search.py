"""Search / sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, apply


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(convert_dtype(dtype)), x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(convert_dtype(dtype)), x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=True, descending=descending)
        return idx.astype(convert_dtype("int64"))
    return apply(f, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return apply(lambda a: jnp.sort(a, axis=axis, descending=descending), x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(getattr(k, "item", lambda: k)()) if not isinstance(k, int) else k

    def f(a):
        ax = axis if axis is not None else a.ndim - 1
        a_m = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(a_m, k)
        else:
            vals, idx = jax.lax.top_k(-a_m, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(convert_dtype("int64")), -1, ax)
    return apply(f, x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(jnp.where, condition, x, y)


def where_(condition, x, y, name=None):
    out = where(condition, x, y)
    x._adopt(out)
    return x


def nonzero(x, as_tuple=False):
    a = np.asarray(getattr(x, "_data", x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def masked_fill_(x, mask, value, name=None):
    from .manipulation import masked_fill
    out = masked_fill(x, mask, value)
    x._adopt(out)
    return x


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(s, v):
        side = "right" if right else "left"
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            out = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
                s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1]))
            out = out.reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else convert_dtype("int64"))
    return apply(f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        srt = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax, stable=True)
        vals = jnp.take(srt, k - 1, axis=ax)
        inds = jnp.take(idx, k - 1, axis=ax).astype(convert_dtype("int64"))
        if keepdim:
            vals, inds = jnp.expand_dims(vals, ax), jnp.expand_dims(inds, ax)
        return vals, inds
    return apply(f, x)


def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        a_m = jnp.moveaxis(a, ax, -1)

        def one(row):
            srt = jnp.sort(row)
            first = jnp.concatenate([jnp.array([True]), srt[1:] != srt[:-1]])
            grp = jnp.cumsum(first) - 1
            counts = jnp.zeros(row.shape[0], dtype=jnp.int32).at[grp].add(1)
            best_grp = jnp.argmax(counts)
            val = srt[jnp.argmax(grp == best_grp)]
            idx = row.shape[0] - 1 - jnp.argmax(jnp.flip(row == val))
            return val, idx.astype(convert_dtype("int64"))
        flat = a_m.reshape(-1, a_m.shape[-1])
        vals, idxs = jax.vmap(one)(flat)
        vals = vals.reshape(a_m.shape[:-1])
        idxs = idxs.reshape(a_m.shape[:-1])
        if keepdim:
            vals, idxs = jnp.expand_dims(vals, ax), jnp.expand_dims(idxs, ax)
        return vals, idxs
    return apply(f, x)


def index_fill(x, index, axis, value, name=None):
    def f(a, i):
        a_m = jnp.moveaxis(a, axis, 0)
        out = a_m.at[i].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply(f, x, index)
