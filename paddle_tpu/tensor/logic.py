"""Logical / comparison ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply


def equal(x, y, name=None):
    return apply(jnp.equal, x, y)


def not_equal(x, y, name=None):
    return apply(jnp.not_equal, x, y)


def greater_than(x, y, name=None):
    return apply(jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return apply(jnp.greater_equal, x, y)


def less_than(x, y, name=None):
    return apply(jnp.less, x, y)


def less_equal(x, y, name=None):
    return apply(jnp.less_equal, x, y)


def logical_and(x, y, out=None, name=None):
    return apply(jnp.logical_and, x, y)


def logical_or(x, y, out=None, name=None):
    return apply(jnp.logical_or, x, y)


def logical_xor(x, y, out=None, name=None):
    return apply(jnp.logical_xor, x, y)


def logical_not(x, out=None, name=None):
    return apply(jnp.logical_not, x)


def bitwise_and(x, y, out=None, name=None):
    return apply(jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return apply(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return apply(jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return apply(jnp.bitwise_not, x)


def bitwise_left_shift(x, y, name=None):
    return apply(jnp.left_shift, x, y)


def bitwise_right_shift(x, y, name=None):
    return apply(jnp.right_shift, x, y)


def is_tensor(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return apply(lambda a: jnp.asarray(a.size == 0), x)
