"""``paddle_tpu.tensor`` — the functional tensor namespace.

Reference: python/paddle/tensor/ (~300 functions monkey-patched onto Tensor).
All functions accept eager Tensors (autograd-recorded) or raw jax arrays /
tracers (pure path under jit).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor  # noqa: F401
from .creation import (arange, assign, clone, diag, diagflat, empty, empty_like, eye,  # noqa: F401
                       full, full_like, linspace, logspace, meshgrid, numel, ones,
                       ones_like, tril, triu, zeros, zeros_like)
from .einsum import einsum  # noqa: F401
from .linalg import (bincount, cholesky, cholesky_solve, cond, corrcoef, cov, cross, det,  # noqa: F401
                     eig, eigh, eigvals, eigvalsh, histogram, inv, lstsq, lu, matrix_power,
                     matrix_rank, matrix_transpose, multi_dot, norm, pinv, qr, slogdet,
                     solve, svd, triangular_solve, diagonal, inverse)
from .logic import (bitwise_and, bitwise_left_shift, bitwise_not, bitwise_or,  # noqa: F401
                    bitwise_right_shift, bitwise_xor, equal, greater_equal, greater_than,
                    is_empty, is_tensor, less_equal, less_than, logical_and, logical_not,
                    logical_or, logical_xor, not_equal)
from .manipulation import (as_complex, as_real, atleast_1d, atleast_2d, atleast_3d,  # noqa: F401
                           broadcast_tensors, broadcast_to, chunk, concat, crop, expand,
                           expand_as, flatten, flip, gather, gather_nd, index_add,
                           index_put, index_sample, index_select, masked_fill,
                           masked_select, moveaxis, pad, put_along_axis,
                           repeat_interleave, reshape, reshape_, roll, rot90, scatter,
                           scatter_, scatter_nd, scatter_nd_add, shard_index, slice,
                           split, squeeze, stack, strided_slice, swapaxes,
                           take_along_axis, tensordot, tile, transpose, unique,
                           unique_consecutive, unsqueeze, unsqueeze_, unstack,
                           squeeze_, t, view, view_as)
from .math import *  # noqa: F401,F403
from .math import _mod as _math_mod  # noqa: F401
from .random import (bernoulli, bernoulli_, binomial, exponential_, gaussian,  # noqa: F401
                     multinomial, normal, normal_, poisson, rand, randint, randint_like,
                     randn, randperm, standard_normal, uniform, uniform_)
from .search import (argmax, argmin, argsort, bucketize, index_fill, kthvalue,  # noqa: F401
                     masked_fill_, mode, nonzero, searchsorted, sort, topk, where, where_)
from .stat import mean, median, nanmedian, nanquantile, quantile, std, var  # noqa: F401

import sys as _sys

_self = _sys.modules[__name__]


def rank(x):
    return to_tensor(x.ndim if hasattr(x, "ndim") else jnp.ndim(x))


def shape(x):
    return to_tensor(list(x.shape), dtype="int64")


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def cast(x, dtype):
    return x.astype(dtype) if isinstance(x, Tensor) else Tensor(x).astype(dtype)


def real(x, name=None):
    return apply(jnp.real, x)


def imag(x, name=None):
    return apply(jnp.imag, x)


# ---------------------------------------------------------------------------
# Monkey-patch the functional namespace onto Tensor as methods
# (reference: python/paddle/tensor/__init__.py tensor_method_func list).
# ---------------------------------------------------------------------------
_METHOD_NAMES = [
    # math
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs", "ceil",
    "floor", "round", "trunc", "cos", "sin", "tan", "acos", "asin", "atan", "cosh",
    "sinh", "tanh", "acosh", "asinh", "atanh", "reciprocal", "square", "sign", "neg",
    "erf", "erfinv", "digamma", "lgamma", "sigmoid", "angle", "conj", "frac",
    "isnan", "isinf", "isfinite", "add", "subtract", "multiply", "divide",
    "floor_divide", "mod", "remainder", "floor_mod", "pow", "maximum", "minimum",
    "fmax", "fmin", "atan2", "logaddexp", "hypot", "heaviside", "inner", "outer",
    "kron", "scale", "clip", "sum", "mean", "max", "min", "prod", "amax", "amin",
    "nansum", "nanmean", "logsumexp", "all", "any", "count_nonzero", "cumsum",
    "cumprod", "cummax", "cummin", "diff", "trace", "addmm", "matmul", "mm", "bmm",
    "dot", "mv", "dist", "increment", "isclose", "allclose", "equal_all", "lerp",
    "rad2deg", "deg2rad", "take",
    # stat
    "var", "std", "median", "nanmedian", "quantile", "nanquantile",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "bitwise_and",
    "bitwise_or", "bitwise_xor", "bitwise_not", "is_empty",
    # manipulation
    "reshape", "reshape_", "flatten", "transpose", "moveaxis", "swapaxes", "unsqueeze",
    "squeeze", "concat", "split", "chunk", "tile", "expand", "expand_as",
    "broadcast_to", "flip", "rot90", "roll", "gather", "gather_nd", "take_along_axis",
    "put_along_axis", "scatter", "scatter_", "scatter_nd_add", "index_select",
    "index_sample", "index_add", "index_put", "repeat_interleave", "unique",
    "unique_consecutive", "masked_select", "masked_fill", "masked_fill_", "pad",
    "strided_slice", "slice", "as_complex", "as_real", "tensordot", "unstack",
    "view", "view_as", "unbind",
    # linalg
    "norm", "cond", "matrix_power", "det", "slogdet", "inv", "pinv", "solve",
    "cholesky", "qr", "svd", "eig", "eigvals", "lstsq", "multi_dot", "cross",
    "histogram", "bincount", "matrix_transpose",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "where", "where_", "nonzero",
    "searchsorted", "bucketize", "kthvalue", "mode", "index_fill",
    # random (in-place)
    "uniform_", "normal_", "bernoulli_", "exponential_",
    # misc
    "cast", "is_floating_point", "is_integer", "is_complex", "rank",
]


def unbind(x, axis=0, name=None):
    return unstack(x, axis)


def _patch():
    for name in _METHOD_NAMES:
        fn = getattr(_self, name, None)
        if fn is None:
            continue
        if hasattr(Tensor, name) and name not in ("where",):
            continue

        def make(f):
            def method(self, *args, **kwargs):
                return f(self, *args, **kwargs)
            method.__name__ = f.__name__
            return method
        setattr(Tensor, name, make(fn))


_patch()
from .random import check_shape  # noqa: F401,E402


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Printing options for Tensor repr (reference tensor/to_string.py:34) —
    forwarded to numpy, which renders our reprs.  Note this adjusts numpy's
    process-global print state (our Tensor repr IS numpy's)."""
    import numpy as _np
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = int(precision)
    if threshold is not None:
        kwargs["threshold"] = int(threshold)
    if edgeitems is not None:
        kwargs["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kwargs["linewidth"] = int(linewidth)
    if sci_mode is not None:
        if sci_mode:
            # numpy's suppress=False merely ALLOWS scientific notation; a
            # formatter is needed to force it (the reference's sci_mode=True)
            prec = int(precision) if precision is not None else 8
            kwargs["formatter"] = {
                "float_kind": lambda v, p=prec: f"{v:.{p}e}"}
        else:
            kwargs["suppress"] = True
            kwargs["formatter"] = None
    _np.set_printoptions(**kwargs)
