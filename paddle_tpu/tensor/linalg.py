"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, paddle.linalg)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import apply


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if axis is None and p in ("fro", 2):
            return jnp.linalg.norm(a.reshape(-1), ord=2, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        ordv = p
        if p == "fro":
            ordv = None if isinstance(ax, tuple) else 2
        if p == "inf":
            ordv = jnp.inf
        elif p == "-inf":
            ordv = -jnp.inf
        return jnp.linalg.norm(a, ord=ordv, axis=ax, keepdims=keepdim)
    return apply(f, x)


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), x)


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def det(x, name=None):
    return apply(jnp.linalg.det, x)


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return apply(f, x)


def inv(x, name=None):
    return apply(jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0,
                                                 unit_diagonal=unitriangular)
    return apply(f, x, y)


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply(f, x)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply(f, x, y)


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1
    out = apply(f, x)
    if get_infos:
        from .creation import zeros
        return out[0], out[1], zeros([1], "int32")
    return out


def qr(x, mode="reduced", name=None):
    return apply(lambda a: jnp.linalg.qr(a, mode=mode), x)


def svd(x, full_matrices=False, name=None):
    def f(a):
        u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
        return u, s, vh
    return apply(f, x)


def eig(x, name=None):
    return apply(jnp.linalg.eig, x)


def eigvals(x, name=None):
    return apply(jnp.linalg.eigvals, x)


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), x)


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply(f, x, y)


def multi_dot(x, name=None):
    return apply(lambda xs: jnp.linalg.multi_dot(xs), list(x))


def matrix_transpose(x, name=None):
    return apply(lambda a: jnp.swapaxes(a, -1, -2), x)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(f, x, y)


def histogram(input, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a.reshape(-1), bins=bins, range=(lo, hi))
        return h.astype(convert_dtype("int64"))  # int32 under no-x64, silent
    return apply(f, input)


def bincount(x, weights=None, minlength=0, name=None):
    return apply(lambda a, w: jnp.bincount(a, weights=w, minlength=minlength,
                                           length=None), x, weights)


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a, fw, aw: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                           fweights=fw, aweights=aw), x, fweights, aweights)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2), x)


def inverse(x, name=None):
    return inv(x)
