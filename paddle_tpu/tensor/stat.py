"""Statistics ops (reference: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply


def _ax(axis):
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.mean(a, axis=_ax(axis), keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.var(a, axis=_ax(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.std(a, axis=_ax(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(a):
        if mode == "avg":
            return jnp.median(a, axis=_ax(axis), keepdims=keepdim)
        n = a.shape[axis] if axis is not None else a.size
        srt = jnp.sort(a.reshape(-1) if axis is None else a, axis=0 if axis is None else axis)
        return jnp.take(srt, (n - 1) // 2, axis=0 if axis is None else axis)
    return apply(f, x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=_ax(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply(lambda a, qq: jnp.quantile(a, jnp.asarray(qq), axis=_ax(axis), keepdims=keepdim,
                                            method=interpolation), x, q)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply(lambda a, qq: jnp.nanquantile(a, jnp.asarray(qq), axis=_ax(axis),
                                               keepdims=keepdim, method=interpolation), x, q)
