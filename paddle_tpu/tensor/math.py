"""Elementwise & reduction math ops (reference: python/paddle/tensor/math.py,
operators/elementwise/, operators/reduce_ops/)."""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, apply

_mod = sys.modules[__name__]


def _unary(name, fn):
    def wrapper(x, name=None):
        return apply(fn, x)
    wrapper.__name__ = name
    setattr(_mod, name, wrapper)


def _binary(name, fn):
    def wrapper(x, y, name=None):
        return apply(fn, x, y)
    wrapper.__name__ = name
    setattr(_mod, name, wrapper)


for _n, _f in {
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "sqrt": jnp.sqrt, "rsqrt": lambda x: jax.lax.rsqrt(x),
    "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor, "round": jnp.round,
    "trunc": jnp.trunc, "cos": jnp.cos, "sin": jnp.sin, "tan": jnp.tan,
    "acos": jnp.arccos, "asin": jnp.arcsin, "atan": jnp.arctan,
    "cosh": jnp.cosh, "sinh": jnp.sinh, "tanh": jnp.tanh,
    "acosh": jnp.arccosh, "asinh": jnp.arcsinh, "atanh": jnp.arctanh,
    "reciprocal": jnp.reciprocal, "square": jnp.square, "sign": jnp.sign,
    "neg": jnp.negative, "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "digamma": jax.scipy.special.digamma, "lgamma": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid, "angle": jnp.angle, "conj": jnp.conj,
    "real": jnp.real, "imag": jnp.imag, "frac": lambda x: x - jnp.trunc(x),
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
    "i0": jax.scipy.special.i0, "i1": jax.scipy.special.i1,
}.items():
    _unary(_n, _f)

for _n, _f in {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.true_divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "remainder": jnp.mod, "floor_mod": jnp.mod,
    "pow": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp, "hypot": jnp.hypot,
    "heaviside": jnp.heaviside, "copysign": jnp.copysign,
    "nextafter": jnp.nextafter, "ldexp": jnp.ldexp,
    "gcd": jnp.gcd, "lcm": jnp.lcm, "inner": jnp.inner, "outer": jnp.outer,
    "kron": jnp.kron,
}.items():
    _binary(_n, _f)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(a, s, b):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out
    return apply(f, x, scale, bias)


def clip(x, min=None, max=None, name=None):
    return apply(lambda a, lo, hi: jnp.clip(a, lo, hi), x, min, max)


def multiplex(inputs, index, name=None):
    return apply(lambda ins, idx: jnp.stack(ins, 1)[jnp.arange(ins[0].shape[0]), idx.reshape(-1)],
                 list(inputs), index)


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(getattr(axis, "item", lambda: axis)()) if not isinstance(axis, int) else axis


def _reduction(name, fn, int_promote=False):
    def wrapper(x, axis=None, keepdim=False, name=None):
        ax = _norm_axis(axis)

        def f(a):
            out = fn(a, axis=ax, keepdims=keepdim)
            return out
        return apply(f, x)
    wrapper.__name__ = name
    setattr(_mod, name, wrapper)


for _n, _f in {
    "sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min,
    "prod": jnp.prod, "amax": jnp.max, "amin": jnp.min,
    "nansum": jnp.nansum, "nanmean": jnp.nanmean,
    "logsumexp": jax.scipy.special.logsumexp,
    "all": jnp.all, "any": jnp.any,
}.items():
    _reduction(_n, _f)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.count_nonzero(a, axis=_norm_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    return apply(lambda a: jnp.cumsum(a if dtype is None else a.astype(convert_dtype(dtype)),
                                      axis=axis), x)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda a: jnp.cumprod(a if dtype is None else a.astype(convert_dtype(dtype)),
                                       axis=dim), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def g(a):
        ax = 0 if axis is None else axis
        flat = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, flat, axis=ax)
        iota = jax.lax.broadcasted_iota(convert_dtype("int64"), flat.shape, ax)
        # last occurrence of the running max (reference/torch tie-break)
        eq = flat == vals
        idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, iota, -1), axis=ax)
        return vals, idx.astype(convert_dtype(dtype))
    return apply(g, x)


def cummin(x, axis=None, dtype="int64", name=None):
    def g(a):
        ax = 0 if axis is None else axis
        flat = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, flat, axis=ax)
        iota = jax.lax.broadcasted_iota(convert_dtype("int64"), flat.shape, ax)
        # last occurrence of the running min (reference/torch tie-break)
        eq = flat == vals
        idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, iota, -1), axis=ax)
        return vals, idx.astype(convert_dtype(dtype))
    return apply(g, x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply(lambda a, p, ap: jnp.diff(a, n=n, axis=axis, prepend=p, append=ap),
                 x, prepend, append)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply(f, x, y, name="matmul")


def mm(x, y, name=None):
    return apply(jnp.matmul, x, y)


def bmm(x, y, name=None):
    return apply(jnp.matmul, x, y)


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mv(x, y, name=None):
    return apply(jnp.matmul, x, y)


def dist(x, y, p=2, name=None):
    return apply(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), x)


def increment(x, value=1.0, name=None):
    out = apply(lambda a: a + value, x)
    x._adopt(out)
    return x


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y)


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight)


def rad2deg(x, name=None):
    return apply(jnp.rad2deg, x)


def deg2rad(x, name=None):
    return apply(jnp.deg2rad, x)


def take(x, index, mode="raise", name=None):
    # XLA cannot raise on device; 'raise' degrades to 'clip' (documented).
    jmode = "wrap" if mode == "wrap" else "clip"
    return apply(lambda a, i: jnp.take(a.reshape(-1), i.reshape(-1) if hasattr(i, "reshape") else i,
                                       mode=jmode).reshape(jnp.shape(i)),
                 x, index)


def broadcast_shape(x_shape, y_shape):
    import numpy as np
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (reference math.py add_n / sum_op)."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    return apply(lambda *xs: functools.reduce(jnp.add, xs), *inputs)


def tanh_(x, name=None):
    x._adopt(apply(jnp.tanh, x))
    return x
