"""Random ops (reference: python/paddle/tensor/random.py).

Stateful-eager / key-scoped-traced via core.rng (see rng.py docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import rng
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, apply
from .creation import _to_shape


def _dt(dtype):
    return get_default_dtype() if dtype is None else convert_dtype(dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = rng.next_key() if seed == 0 else jax.random.key(seed)
    return apply(lambda k: jax.random.uniform(k, _to_shape(shape), _dt(dtype), min, max),
                 Tensor(key))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(x.shape, x.dtype, min, max, seed)
    x._adopt(out)
    return x


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    key = rng.next_key()
    return apply(lambda k: jax.random.normal(k, _to_shape(shape), _dt(dtype)), Tensor(key))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = getattr(mean, "shape", None) or getattr(std, "shape", None) or [1]
    key = rng.next_key()
    return apply(lambda k, m, s: m + s * jax.random.normal(k, _to_shape(shape), get_default_dtype()),
                 Tensor(key), mean, std)


def normal_(x, mean=0.0, std=1.0, name=None):
    out = normal(mean, std, x.shape)
    x._adopt(out.astype(x.dtype))
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = rng.next_key() if seed == 0 else jax.random.key(seed)
    return apply(lambda k: mean + std * jax.random.normal(k, _to_shape(shape), _dt(dtype)),
                 Tensor(key))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = rng.next_key()
    return apply(lambda k: jax.random.randint(k, _to_shape(shape), low, high,
                                              convert_dtype(dtype)), Tensor(key))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    key = rng.next_key()
    return apply(lambda k: jax.random.permutation(k, n).astype(convert_dtype(dtype)), Tensor(key))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = rng.next_key()

    def f(k, probs):
        logits = jnp.log(jnp.maximum(probs, 1e-30))
        if replacement:
            return jax.random.categorical(
                k, logits, axis=-1,
                shape=(*probs.shape[:-1], num_samples)
            ).astype(convert_dtype("int64"))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(k, probs.shape, logits.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(convert_dtype("int64"))
    return apply(f, Tensor(key), x)


def bernoulli(x, name=None):
    key = rng.next_key()
    return apply(lambda k, p: jax.random.bernoulli(k, p).astype(p.dtype), Tensor(key), x)


def bernoulli_(x, p=0.5, name=None):
    key = rng.next_key()
    out = apply(lambda k, a: jax.random.bernoulli(k, p, a.shape).astype(a.dtype), Tensor(key), x)
    x._adopt(out)
    return x


def poisson(x, name=None):
    key = rng.next_key()
    return apply(lambda k, lam: jax.random.poisson(k, lam).astype(lam.dtype), Tensor(key), x)


def binomial(count, prob, name=None):
    key = rng.next_key()
    return apply(lambda k, n, p: jax.random.binomial(k, n, p).astype(
        convert_dtype("int64")), Tensor(key), count, prob)


def exponential_(x, lam=1.0, name=None):
    key = rng.next_key()
    out = apply(lambda k, a: (jax.random.exponential(k, a.shape, a.dtype) / lam).astype(a.dtype),
                Tensor(key), x)
    x._adopt(out)
    return x


def check_shape(shape):
    """Validate a shape argument (reference tensor/random.py check_shape)."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if not isinstance(s, (int,)) and not hasattr(s, "dtype"):
                raise TypeError(f"shape entries must be ints/Tensors, got {type(s)}")
    return shape
