"""Shape / layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import builtins

import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, apply


def _shape(s):
    if isinstance(s, Tensor):
        return tuple(int(v) for v in np.asarray(s._data))
    if isinstance(s, (int, np.integer)):
        return (int(s),)
    return tuple(int(getattr(v, "item", lambda: v)()) if not isinstance(v, int) else v for v in s)


def reshape(x, shape, name=None):
    return apply(lambda a: jnp.reshape(a, _shape(shape)), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._adopt(out)
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s0 = start_axis % nd if nd else 0
        s1 = stop_axis % nd if nd else 0
        newshape = a.shape[:s0] + (-1,) + a.shape[s1 + 1:]
        return jnp.reshape(a, newshape)
    return apply(f, x)


def transpose(x, perm=None, name=None):
    return apply(lambda a: jnp.transpose(a, perm), x)


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), x)


transpose_ = transpose


def unsqueeze(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply(lambda a: jnp.expand_dims(a, ax), x)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        ax = tuple(a_ for a_ in ax if a.shape[a_] == 1)
        return jnp.squeeze(a, ax) if ax else a
    return apply(f, x)


def concat(x, axis=0, name=None):
    axis = int(getattr(axis, "item", lambda: axis)()) if not isinstance(axis, int) else axis
    return apply(lambda xs: jnp.concatenate(xs, axis=axis), list(x))


def stack(x, axis=0, name=None):
    return apply(lambda xs: jnp.stack(xs, axis=axis), list(x))


def unstack(x, axis=0, num=None, name=None):
    def f(a):
        n = num or a.shape[axis]
        return [jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis)]
    return apply(f, x)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis)

    def f(a):
        if isinstance(num_or_sections, int):
            return jnp.split(a, num_or_sections, axis=axis)
        secs = list(num_or_sections)
        total = a.shape[axis]
        known = sum(s for s in secs if s != -1)
        secs = [s if s != -1 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1]
        return jnp.split(a, idx, axis=axis)
    return apply(f, x)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    return apply(lambda a: jnp.tile(a, _shape(repeat_times)), x)


def expand(x, shape, name=None):
    def f(a):
        tgt = list(_shape(shape))
        for i, t in enumerate(tgt):
            if t == -1:
                tgt[i] = a.shape[i - (len(tgt) - a.ndim)]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply(f, x)


def expand_as(x, y, name=None):
    return apply(lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_to(x, shape, name=None):
    return apply(lambda a: jnp.broadcast_to(a, _shape(shape)), x)


def broadcast_tensors(inputs, name=None):
    return apply(lambda xs: list(jnp.broadcast_arrays(*xs)), list(inputs))


def flip(x, axis, name=None):
    return apply(lambda a: jnp.flip(a, axis), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), x)


def gather(x, index, axis=0, name=None):
    axis = int(getattr(axis, "item", lambda: axis)()) if not isinstance(axis, int) else axis
    return apply(lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim else i, axis=axis), x, index)


def gather_nd(x, index, name=None):
    def f(a, idx):
        d = idx.shape[-1]
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a[flat_idx]
    return apply(f, x, index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        if reduce == "add":
            return _put_along(a, i, v, axis, "add")
        if reduce in ("mul", "multiply"):
            return _put_along(a, i, v, axis, "multiply")
        return _put_along(a, i, v, axis, "set")
    return apply(f, arr, indices, values)


def _put_along(a, i, v, axis, mode):
    idx = [jnp.broadcast_to(jax.lax.broadcasted_iota(i.dtype, i.shape, d), i.shape)
           for d in range(a.ndim)]
    idx[axis] = i
    at = a.at[tuple(idx)]
    return getattr(at, {"add": "add", "multiply": "multiply", "set": "set"}[mode])(v)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].add(u)
    return apply(f, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._adopt(out)
    return x


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        z = jnp.zeros(_shape(shape), u.dtype)
        return z.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(f, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    return apply(lambda a, i, u: a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u), x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply(lambda a, i: jnp.take(a, i, axis=axis), x, index)


def index_sample(x, index):
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index)


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i].add(v_m.astype(a.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply(f, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, idx, v):
        idx = tuple(idx)
        return a.at[idx].add(v) if accumulate else a.at[idx].set(v)
    return apply(f, x, list(indices), value)


def repeat_interleave(x, repeats, axis=None, name=None):
    return apply(lambda a, r: jnp.repeat(a, r, axis=axis,
                                         total_repeat_length=None if isinstance(r, int) else None),
                 x, repeats)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    def f(a):
        res = jnp.unique(np.asarray(a), return_index=return_index,
                         return_inverse=return_inverse, return_counts=return_counts, axis=axis)
        return res
    # unique has data-dependent shape: eager-only (numpy), like reference's unique op on CPU
    a = np.asarray(getattr(x, "_data", x))
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        out = [Tensor(jnp.asarray(res[0]))]
        for r in res[1:]:
            out.append(Tensor(jnp.asarray(r.astype(np.int64))))
        return tuple(out)
    return Tensor(jnp.asarray(res))


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64",
                       name=None):
    a = np.asarray(getattr(x, "_data", x))
    if axis is None:
        a = a.reshape(-1)
        ax = 0
    else:
        ax = axis
    take = np.ones(a.shape[ax], dtype=bool)
    sl = [builtins.slice(None)] * a.ndim
    sl[ax] = builtins.slice(1, None)
    sl2 = [builtins.slice(None)] * a.ndim
    sl2[ax] = builtins.slice(None, -1)
    neq = (a[tuple(sl)] != a[tuple(sl2)])
    while neq.ndim > 1:
        neq = neq.any(axis=-1 if ax == 0 else 0)
    take[1:] = neq
    out = [Tensor(jnp.asarray(np.compress(take, a, axis=ax)))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(take) - 1, dtype=np.int64)))
    if return_counts:
        idx = np.nonzero(take)[0]
        counts = np.diff(np.append(idx, a.shape[ax]))
        out.append(Tensor(jnp.asarray(counts, dtype=np.int64)))
    return out[0] if len(out) == 1 else tuple(out)


def masked_select(x, mask, name=None):
    """Eager-only (output shape is data-dependent, so it cannot trace — the
    reference's dygraph op has the same shape dynamism).  The mask is
    concretized to host indices and the select runs as a differentiable
    gather, so ``backward()`` scatters grads to the selected positions
    (reference masked_select_grad_kernel)."""
    m = np.asarray(getattr(mask, "_data", mask))
    data = getattr(x, "_data", x)
    flat_idx = np.nonzero(np.broadcast_to(m, data.shape).reshape(-1))[0]
    return apply(lambda a: a.reshape(-1)[flat_idx], x)


def masked_fill(x, mask, value, name=None):
    return apply(lambda a, m, v: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask, value)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def f(a, p):
        p = list(int(v) for v in (np.asarray(p).reshape(-1)))
        nd = a.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pad applies to last len(p)//2 spatial dims,
            # ordered (last_dim_lo, last_dim_hi, second_last_lo, ...) for NCHW
            k = len(p) // 2
            width = [(0, 0)] * nd
            if data_format in ("NCHW", "NCL", "NCDHW"):
                dims = list(range(nd - k, nd))
            else:  # NHWC-family: spatial dims are 1..k
                dims = list(range(1, 1 + k))
            for j, d in enumerate(reversed(dims)):
                width[d] = (p[2 * j], p[2 * j + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)
    return apply(f, x, pad)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(s, e, st)
        return a[tuple(sl)]
    return apply(f, x)


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    return strided_slice(x, axes, starts, ends, [1] * len(axes))


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    def f(a):
        sh = _shape(shape)
        off = [0] * a.ndim if offsets is None else [int(o) for o in offsets]
        sl = tuple(builtins.slice(o, (o + s) if s != -1 else None) for o, s in zip(off, sh))
        return a[sl]
    return apply(f, x)


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply(lambda a: a.view(convert_dtype(shape_or_dtype)), x)


def view_as(x, other, name=None):
    return apply(lambda a, b: jnp.reshape(a, b.shape), x, other)


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(i):
        size = index_num // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        inside = (i >= lo) & (i < hi)
        return jnp.where(inside, i - lo, ignore_value)
    return apply(f, input)


def squeeze_(x, axis=None, name=None):
    """Inplace squeeze (reference *_ inplace convention)."""
    x._adopt(squeeze(x, axis))
    return x


def unsqueeze_(x, axis, name=None):
    x._adopt(unsqueeze(x, axis))
    return x


def t(x, name=None):
    """Transpose a 0/1/2-D tensor (reference tensor/linalg.py t())."""
    nd = len(x.shape)
    if nd > 2:
        raise ValueError(
            f"paddle.t only accepts tensors of rank <= 2, got rank {nd}; "
            f"use paddle.transpose for higher ranks")
    if nd < 2:
        return x
    return transpose(x, [1, 0])
