"""Short-time signal processing (reference: python/paddle/signal.py).

frame / overlap_add / stft / istft.  TPU-native design: the reference routes
frame and overlap_add through dedicated C++ kernels
(operators/frame_op.cc, overlap_add_op.cc); here framing is a static gather
(index matrix built from iota, one XLA gather per call) and overlap-add is a
scatter-add — both shapes are static so XLA tiles them; the FFT stage rides
the native Fft HLO via paddle_tpu.fft.
"""

from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import apply

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_raw(x, frame_length, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError(f"Attribute axis should be 0 or -1, but got {axis}")
    n = x.shape[-1] if axis == -1 else x.shape[0]
    if frame_length > n:
        raise ValueError(
            f"Attribute frame_length should be less equal than sequence length, "
            f"but got ({frame_length}) > ({n})")
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(num_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])        # (F, L)
    if axis == -1:
        # (..., seq) -> (..., frame_length, num_frames)
        out = jnp.take(x, idx, axis=-1)                # (..., F, L)
        return jnp.swapaxes(out, -1, -2)
    # (seq, ...) -> (num_frames, frame_length, ...)
    return jnp.take(x, idx, axis=0)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slide a window over ``x`` producing overlapping frames."""
    if hop_length <= 0:
        raise ValueError(
            f"Attribute hop_length should be greater than 0, but got {hop_length}")
    return apply(lambda a: _frame_raw(a, frame_length, hop_length, axis), x)


def _overlap_add_raw(x, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError(f"Attribute axis should be 0 or -1, but got {axis}")
    if axis == -1:
        frame_length, num_frames = x.shape[-2], x.shape[-1]
        frames = jnp.swapaxes(x, -1, -2)               # (..., F, L)
    else:
        num_frames, frame_length = x.shape[0], x.shape[1]
        frames = jnp.moveaxis(x, (0, 1), (-2, -1))     # (..., F, L)
    seq_len = (num_frames - 1) * hop_length + frame_length
    idx = (jnp.arange(num_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :]).reshape(-1)
    flat = frames.reshape(frames.shape[:-2] + (num_frames * frame_length,))
    out = jnp.zeros(frames.shape[:-2] + (seq_len,), x.dtype)
    out = out.at[..., idx].add(flat)
    if axis == 0:
        out = jnp.moveaxis(out, -1, 0)
    return out


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from overlapping frames (adjoint of ``frame``)."""
    if hop_length <= 0:
        raise ValueError(
            f"Attribute hop_length should be greater than 0, but got {hop_length}")
    return apply(lambda a: _overlap_add_raw(a, hop_length, axis), x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform.

    x: (T,) or (B, T) real (or complex with onesided=False).
    Returns (..., n_fft//2+1 if onesided else n_fft, num_frames), complex.
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if not 0 < win_length <= n_fft:
        raise ValueError(
            f"Expected 0 < win_length <= n_fft, but got win_length={win_length}")
    wdata = None if window is None else getattr(window, "_data", window)

    def f(a, w):
        if a.ndim not in (1, 2):
            raise ValueError(f"x should be a 1D or 2D tensor, but got {a.ndim}D")
        if w is None:
            w2 = jnp.ones((win_length,), a.real.dtype if jnp.iscomplexobj(a)
                          else a.dtype)
        else:
            w2 = w
        if win_length < n_fft:  # center-pad the window out to n_fft
            lpad = (n_fft - win_length) // 2
            w2 = jnp.pad(w2, (lpad, n_fft - win_length - lpad))
        if onesided and jnp.iscomplexobj(a):
            raise ValueError(
                "stft with onesided=True requires real input; pass "
                "onesided=False for complex signals")
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        fr = _frame_raw(a, n_fft, hop_length, -1)       # (..., n_fft, F)
        fr = fr * w2[:, None]
        if onesided:
            spec = jnp.fft.rfft(fr, axis=-2)
        else:
            spec = jnp.fft.fft(fr, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    return apply(f, x, wdata)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with least-squares window-envelope normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wdata = None if window is None else getattr(window, "_data", window)

    def f(spec, w):
        if spec.ndim not in (2, 3):
            raise ValueError(f"x should be 2D or 3D, but got {spec.ndim}D")
        if w is None:
            w2 = jnp.ones((win_length,), jnp.float32)
        else:
            w2 = w.astype(jnp.float32)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w2 = jnp.pad(w2, (lpad, n_fft - win_length - lpad))
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        if onesided:
            fr = jnp.fft.irfft(spec, n=n_fft, axis=-2)   # (..., n_fft, F)
        else:
            fr = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                fr = fr.real
        fr = fr * w2[:, None]
        sig = _overlap_add_raw(fr, hop_length, -1)
        env = _overlap_add_raw(
            jnp.broadcast_to((w2 ** 2)[:, None], (n_fft, spec.shape[-1])),
            hop_length, -1)
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            sig = sig[..., n_fft // 2: sig.shape[-1] - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig

    return apply(f, x, wdata)
