"""Profiler (reference API shape: platform/profiler.h ``RecordEvent``:130,
``EnableProfiler/DisableProfiler``:216-219 + python fluid/profiler.py:133,200,257
start/stop/context-manager and the chrome-trace export via tools/timeline.py;
new-style python/paddle/profiler Profiler class).

TPU-native: backed by jax.profiler — traces are XPlane protos viewable in
TensorBoard/Perfetto (the chrome-trace viewer role of timeline.py), host-side
annotations via TraceAnnotation (≙ RecordEvent RAII), and a step-level wall
clock summary table (≙ the aggregated profiler tables).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, Optional, Tuple

import jax

__all__ = ["Profiler", "RecordEvent", "start_profiler", "stop_profiler",
           "reset_profiler", "profiler", "summary", "snapshot_events"]

_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
# RecordEvent exits on engine-callback/loader threads mutate _events
# concurrently with summary() readers — defaultdict creation + list writes
# race without this
_events_lock = threading.Lock()
_active_dir: Optional[str] = None
_log = logging.getLogger(__name__)


class RecordEvent:
    """Annotate a host-side region; shows up on the XPlane timeline and in
    the local summary table (≙ platform::RecordEvent RAII)."""

    def __init__(self, name: str):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self._t0 = 0.0

    def __enter__(self):
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        with _events_lock:
            rec = _events[self.name]
            rec[0] += 1
            rec[1] += dt
        return self._ann.__exit__(*exc)

    # fluid/profiler API aliases
    begin = __enter__

    def end(self):
        self.__exit__(None, None, None)


def start_profiler(log_dir: str = "./profiler_log", state: str = "All",
                   tracer_option: str = "Default"):
    """≙ fluid/profiler.py:200 start_profiler (state/tracer_option accepted
    for API parity; the XPlane trace always captures host+device)."""
    global _active_dir
    if _active_dir is not None:
        return
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _active_dir = log_dir


def stop_profiler(sorted_key: str = "total",
                  profile_path: Optional[str] = None,
                  on_summary: Optional[Callable[[str], None]] = None):
    """≙ fluid/profiler.py:257 stop_profiler; finalizes the trace directory
    and reports the aggregated event table — through ``on_summary(text)``
    when given, else the module logger (library code must not print;
    tests/test_no_print.py enforces it)."""
    global _active_dir
    if _active_dir is None:
        return
    jax.profiler.stop_trace()
    text = (summary(sorted_key) + f"\n[profiler] trace written to "
            f"{_active_dir} (open with TensorBoard / xprof)")
    if on_summary is not None:
        on_summary(text)
    else:
        _log.info("%s", text)
    _active_dir = None


def reset_profiler():
    """≙ fluid/profiler.py reset_profiler: clear the aggregated host-event
    table (the XPlane trace state is unaffected)."""
    with _events_lock:
        _events.clear()


def snapshot_events() -> Dict[str, Tuple[int, float]]:
    """Consistent copy of the aggregated {name: (calls, total_s)} table —
    the accessor ``utils.stats.op_summary`` joins on (reading the dict
    while callback threads mutate it would tear)."""
    with _events_lock:
        return {n: (int(c), float(t)) for n, (c, t) in _events.items()}


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: str = "./profiler_log"):
    """≙ fluid/profiler.py:133 context manager."""
    start_profiler(profile_path, state)
    try:
        yield
    finally:
        stop_profiler(sorted_key)


def summary(sorted_key: str = "total") -> str:
    """Aggregated host-event table (≙ the reference's profiler summary)."""
    rows = [(name, c, tot, tot / max(c, 1))
            for name, (c, tot) in snapshot_events().items()]
    key = {"total": 2, "calls": 1, "ave": 3}.get(sorted_key, 2)
    rows.sort(key=lambda r: r[key], reverse=True)
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"]
    lines += [f"{n:<40}{c:>8}{t:>12.4f}{a:>12.6f}" for n, c, t, a in rows]
    return "\n".join(lines)


class Profiler:
    """New-style API (reference python/paddle/profiler/profiler.py):
    ``Profiler(on_trace_ready=...)`` with start/stop/step."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir: str = "./profiler_log", timer_only: bool = False):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.on_trace_ready = on_trace_ready
        self._step_times = []
        self._step_samples = []
        self._last = None

    def start(self):
        if not self.timer_only:
            start_profiler(self.log_dir)
        self._last = time.perf_counter()
        return self

    def step(self, num_samples: Optional[int] = None):
        """Mark a step boundary.  ``num_samples`` (the reference's
        benchmark-hint arg) is recorded — ``step_info()`` reports items/sec
        over the timed spans.  When a ``telemetry.TrainMonitor`` is active
        (``set_active_monitor`` / ``TelemetryCallback``) the step timing is
        ALSO routed there, so profiler-paced loops land in the same
        trace/summary as instrumented train steps."""
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self._step_times.append(dt)
            self._step_samples.append(0 if num_samples is None
                                      else int(num_samples))
            from .telemetry import current_monitor
            mon = current_monitor()
            if mon is not None:
                mon.record_profiler_step(dt, samples=int(num_samples or 0))
        self._last = now

    def stop(self):
        if not self.timer_only:
            stop_profiler()
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step_info(self) -> str:
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times)
        info = (f"steps={len(ts)} avg={ts.mean()*1e3:.2f}ms "
                f"p50={np.percentile(ts,50)*1e3:.2f}ms "
                f"p99={np.percentile(ts,99)*1e3:.2f}ms")
        samples = sum(self._step_samples)
        if samples:
            info += f" ips={samples / ts.sum():.2f}"
        return info

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
