"""``paddle_tpu.utils`` (reference: python/paddle/utils/)."""

from .. import profiler  # noqa: F401  (paddle.utils.profiler parity)
from . import cpp_extension  # noqa: F401
from . import dlpack, download  # noqa: F401
from ..profiler import Profiler, profiler as get_profiler  # noqa: F401


class ProfilerOptions(dict):
    """Legacy fluid profiler options holder (utils/profiler.py)."""



def try_import(name: str):
    """Reference: paddle/utils/lazy_import.py."""
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"optional dependency {name!r} is not available "
                          f"in this environment") from e


def deprecated(update_to="", since="", reason="", level=0):
    """Deprecation decorator (reference utils/deprecated.py): warns once per
    call site and keeps the wrapped signature."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def require_version(min_version, max_version=None):
    """Check the installed framework version (reference utils/__init__.py
    require_version)."""
    from .. import __version__

    def parse(v):
        parts = [int(p) for p in str(v).split(".")[:3] if p.isdigit()]
        return tuple(parts + [0] * (3 - len(parts)))  # "0.1" == "0.1.0"

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required min {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > required max {max_version}")


def run_check():
    """Install sanity check (reference utils/install_check.py run_check):
    a tiny matmul + grad on every local device, then a sharded matmul over
    all of them."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    devs = jax.local_devices()
    x = jnp.ones((4, 4))
    for d in devs:
        y = jax.device_put(x, d)
        # tpulint: disable=jit-in-hot-loop(run_check probes each device once at diagnosis time)
        out = jax.jit(lambda a: (a @ a).sum())(y)
        assert np.isfinite(float(out))
    g = jax.grad(lambda a: (a @ a).sum())(x)
    assert np.isfinite(np.asarray(g)).all()
    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(devs), ("x",))
        xs = jax.device_put(jnp.ones((len(devs) * 2, 4)),
                            NamedSharding(mesh, P("x")))
        # tpulint: disable=jit-in-hot-loop(run_check's one-shot sharded matmul probe)
        out = jax.jit(lambda a: (a @ a.T).sum(),
                      out_shardings=NamedSharding(mesh, P()))(xs)
        assert np.isfinite(float(out))
    print(f"PaddleTPU works well on {len(devs)} {jax.default_backend()} "
          f"device(s).")
    print("PaddleTPU is installed successfully!")


class _UniqueNameGenerator:
    def __init__(self):
        self.ids = {}
        self.prefix = ""

    def __call__(self, key):
        tmp = self.ids.get(key, 0)
        self.ids[key] = tmp + 1
        return f"{self.prefix}{key}_{tmp}"


class _UniqueNameModule:
    """paddle.utils.unique_name (reference fluid/unique_name.py)."""

    def __init__(self):
        self._gen = _UniqueNameGenerator()

    def generate(self, key):
        return self._gen(key)

    def switch(self, new_generator=None):
        old = self._gen
        if isinstance(new_generator, (str, bytes)):
            # reference contract: a string is a namespace PREFIX
            gen = _UniqueNameGenerator()
            gen.prefix = new_generator.decode() \
                if isinstance(new_generator, bytes) else new_generator
            self._gen = gen
        else:
            self._gen = new_generator or _UniqueNameGenerator()
        return old

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            old = self.switch(new_generator)
            try:
                yield
            finally:
                self._gen = old
        return ctx()


unique_name = _UniqueNameModule()
