"""``paddle_tpu.utils`` (reference: python/paddle/utils/)."""

from .. import profiler  # noqa: F401  (paddle.utils.profiler parity)
from . import cpp_extension  # noqa: F401


def try_import(name: str):
    """Reference: paddle/utils/lazy_import.py."""
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"optional dependency {name!r} is not available "
                          f"in this environment") from e
