"""Download helpers (reference: utils/download.py get_weights_path_from_url).
This build runs with zero network egress, so remote fetches raise with a
pointer to the local-path alternatives every dataset/model accepts."""

from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "get_path_from_url"]


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    # honor an already-downloaded local file (the reference's cache-hit path)
    if root_dir:
        cand = os.path.join(root_dir, os.path.basename(url))
        if os.path.exists(cand):
            return cand
    if os.path.exists(url):
        return url
    raise RuntimeError(
        f"cannot download {url!r}: this environment has no network egress. "
        f"Place the file locally and pass its path (datasets take "
        f"data_file=, models load local state_dicts)")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, root_dir=os.path.expanduser(
        "~/.cache/paddle_tpu/weights"))
