"""Runtime stats registry (reference: paddle/fluid/platform/monitor.h
StatRegistry + the STAT_ADD/STAT_INT_ADD macros, surfaced in python via
paddle.fluid.core.get_int_stats).

Named monotonic/gauge counters that any subsystem can bump cheaply, plus
fixed-bucket histograms and a Prometheus text exposition — ONE registry
mechanism for everything that counts (the serving engines keep a private
``StatRegistry`` instance per engine and the process keeps the global one;
``paddle_tpu.telemetry`` exports either).  An op-summary view joins the
profiler's RecordEvent timings.  TPU-native notes: device-side numbers
(memory in use, per-op time) come from XLA/JAX introspection rather than a
CUDA allocator hook — ``device_memory_stats`` delegates to the memory
ledger's ``device_allocator_stats`` (the single accounting point).
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["StatRegistry", "Histogram", "stat_registry", "stat_add",
           "stat_sub", "get_stat", "get_all_stats", "device_memory_stats",
           "op_summary", "prometheus_text", "prom_escape_label",
           "prom_sample", "DEFAULT_TIME_BUCKETS"]

# Prometheus-style latency buckets (seconds): sub-ms ticks through
# multi-second compiles land in distinct buckets.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)


class _Stat:
    __slots__ = ("name", "value", "lock", "kind")

    def __init__(self, name: str, kind: str = "counter"):
        self.name = name
        self.value = 0
        self.lock = threading.Lock()
        self.kind = kind                 # "counter" (monotonic) or "gauge"

    def increase(self, v):
        with self.lock:
            self.value += v

    def decrease(self, v):
        with self.lock:
            self.value -= v
            self.kind = "gauge"          # a decremented stat is not monotonic

    def set(self, v):
        with self.lock:
            self.value = v
            self.kind = "gauge"

    def set_max(self, v):
        with self.lock:
            if v > self.value:
                self.value = v
            self.kind = "gauge"

    def reset(self):
        with self.lock:
            self.value = 0


class Histogram:
    """Fixed-bound cumulative-bucket histogram (the Prometheus model):
    ``observe`` is O(log buckets) under a lock; ``percentile`` is a
    bucket-resolution estimate (exact sample percentiles live in the
    telemetry tracer, which keeps the raw events)."""

    __slots__ = ("name", "bounds", "counts", "total", "count", "lock")

    def __init__(self, name: str, bounds: Sequence[float] = None):
        self.name = name
        self.bounds = tuple(sorted(bounds if bounds is not None
                                   else DEFAULT_TIME_BUCKETS))
        self.counts = [0] * (len(self.bounds) + 1)   # last = +Inf overflow
        self.total = 0.0
        self.count = 0
        self.lock = threading.Lock()

    def observe(self, v: float):
        i = bisect.bisect_left(self.bounds, v)
        with self.lock:
            self.counts[i] += 1
            self.total += v
            self.count += 1

    def snapshot(self) -> Dict[str, object]:
        with self.lock:
            return {"bounds": self.bounds, "counts": tuple(self.counts),
                    "sum": self.total, "count": self.count}

    def percentile(self, q: float) -> Optional[float]:
        """Upper bound of the bucket holding the q-quantile (0 <= q <= 1);
        None when empty.  Overflow observations report the largest bound."""
        with self.lock:
            if self.count == 0:
                return None
            target = q * self.count
            acc = 0
            for i, c in enumerate(self.counts):
                acc += c
                if acc >= target and c:
                    return (self.bounds[i] if i < len(self.bounds)
                            else self.bounds[-1])
            return self.bounds[-1]

    def reset(self):
        with self.lock:
            self.counts = [0] * (len(self.bounds) + 1)
            self.total = 0.0
            self.count = 0


class StatRegistry:
    """Named counters/gauges + histograms (reference monitor.h:77).  The
    process-wide instance backs ``stat_add``; serving engines hold private
    instances so concurrent engines don't alias each other's counters."""

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> _Stat:
        with self._lock:
            if name not in self._stats:
                self._stats[name] = _Stat(name)
            return self._stats[name]

    def add(self, name: str, value=1):
        self.get(name).increase(value)

    def sub(self, name: str, value=1):
        self.get(name).decrease(value)

    def set(self, name: str, value):
        """Gauge write: the stat's current value becomes ``value`` and its
        exported type becomes gauge (non-monotonic)."""
        self.get(name).set(value)

    def set_max(self, name: str, value):
        """High-water gauge: keeps the max ever written (HBM peaks)."""
        self.get(name).set_max(value)

    def value(self, name: str):
        return self.get(name).value

    def histogram(self, name: str, bounds: Sequence[float] = None
                  ) -> Histogram:
        with self._lock:
            if name not in self._hists:
                self._hists[name] = Histogram(name, bounds)
            return self._hists[name]

    def observe(self, name: str, value: float,
                bounds: Sequence[float] = None):
        self.histogram(name, bounds).observe(value)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {n: s.value for n, s in sorted(self._stats.items())}

    def histograms(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            hists = list(self._hists.items())
        return {n: h.snapshot() for n, h in sorted(hists)}

    def kinds(self) -> Dict[str, str]:
        with self._lock:
            return {n: s.kind for n, s in sorted(self._stats.items())}

    def reset(self, name: Optional[str] = None):
        if name is not None:
            self.get(name).reset()  # auto-create like get()/value()
            return
        with self._lock:
            targets = list(self._stats.values())
            hists = list(self._hists.values())
        for s in targets:
            s.reset()
        for h in hists:
            h.reset()


_registry = StatRegistry()


def stat_registry() -> StatRegistry:
    return _registry


def stat_add(name: str, value=1):
    """STAT_ADD macro analog."""
    _registry.add(name, value)


def stat_sub(name: str, value=1):
    _registry.sub(name, value)


def get_stat(name: str):
    return _registry.value(name)


def get_all_stats() -> Dict[str, float]:
    return _registry.snapshot()


_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(namespace: str, name: str) -> str:
    n = _METRIC_NAME_RE.sub("_", name)
    return f"{namespace}_{n}" if namespace else n


def _fmt(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def prom_escape_label(value) -> str:
    """Escape one label VALUE per the Prometheus text exposition format
    (0.0.4): backslash, double-quote, and newline — in that order, so the
    escaping backslashes are not themselves re-escaped.  This is THE
    escaping implementation: every exposition in the tree (`telemetry`,
    `serving`, `gateway`, `telemetry_ledger`, `telemetry_slo`, this
    module's `prometheus_text`) renders label values through it — one
    copy, so the escaping rules cannot drift between emitters."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prom_sample(name: str, value, labels: Optional[Dict[str, object]] = None
                ) -> str:
    """One exposition sample line ``name{k="v",...} value`` with label
    values escaped via :func:`prom_escape_label` (label NAMES are
    sanitized like metric names).  The shared line renderer behind every
    ``prometheus_text`` emitter."""
    if labels:
        body = ",".join(
            f'{_METRIC_NAME_RE.sub("_", str(k))}="{prom_escape_label(v)}"'
            for k, v in labels.items())
        return f"{name}{{{body}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def prometheus_text(registry: Optional[StatRegistry] = None,
                    namespace: str = "paddle_tpu",
                    extra_gauges: Optional[Dict[str, float]] = None,
                    extra_counters: Optional[Dict[str, float]] = None
                    ) -> str:
    """Prometheus text exposition (format 0.0.4) of one registry: counters
    and gauges from their recorded kind, histograms as cumulative
    ``_bucket``/``_sum``/``_count`` series.  ``extra_gauges`` /
    ``extra_counters`` let a caller append derived values (e.g.
    ``engine.metrics()`` means and compile counts) without registering
    them, typed to match their documented kind."""
    reg = registry if registry is not None else _registry
    lines: List[str] = []
    kinds = reg.kinds()
    for name, value in reg.snapshot().items():
        pn = _prom_name(namespace, name)
        lines.append(f"# TYPE {pn} {kinds.get(name, 'counter')}")
        lines.append(prom_sample(pn, value))
    for name, h in reg.histograms().items():
        pn = _prom_name(namespace, name)
        lines.append(f"# TYPE {pn} histogram")
        acc = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            acc += c
            lines.append(prom_sample(f"{pn}_bucket", acc, {"le": bound}))
        lines.append(prom_sample(f"{pn}_bucket", h["count"], {"le": "+Inf"}))
        lines.append(prom_sample(f"{pn}_sum", h["sum"]))
        lines.append(prom_sample(f"{pn}_count", h["count"]))
    for extras, kind in ((extra_gauges, "gauge"),
                         (extra_counters, "counter")):
        for name, value in (extras or {}).items():
            pn = _prom_name(namespace, name)
            lines.append(f"# TYPE {pn} {kind}")
            lines.append(prom_sample(pn, value))
    return "\n".join(lines) + "\n"


def device_memory_stats(device_index: int = 0) -> Dict[str, int]:
    """Per-device allocator stats from the PJRT client (≙ the reference's
    STAT_gpu0_mem_size family fed by the CUDA allocator).  Delegates to
    ``telemetry_memory.device_allocator_stats`` — the memory ledger is
    the single accounting point for raw ``memory_stats()`` calls
    (tpulint ``raw-memory-introspection``); lazy import, stats stays a
    leaf module."""
    from ..telemetry_memory import device_allocator_stats
    return device_allocator_stats(device_index)


def op_summary(top: int = 20) -> List[Tuple[str, int, float]]:
    """(name, calls, total seconds) rows from the profiler's RecordEvent
    aggregation, sorted by total time — the op-summary table view of the
    reference's profiler output."""
    from .. import profiler
    rows = [(n, int(c), float(t))
            for n, (c, t) in profiler.snapshot_events().items()]
    rows.sort(key=lambda r: -r[2])
    return rows[:top]
