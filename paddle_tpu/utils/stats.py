"""Runtime stats registry (reference: paddle/fluid/platform/monitor.h
StatRegistry + the STAT_ADD/STAT_INT_ADD macros, surfaced in python via
paddle.fluid.core.get_int_stats).

Named monotonic/gauge counters that any subsystem can bump cheaply, plus an
op-summary view joining the profiler's RecordEvent timings.  TPU-native
notes: device-side numbers (memory in use, per-op time) come from XLA/JAX
introspection rather than a CUDA allocator hook — ``device_memory_stats``
reads ``jax.local_devices()[i].memory_stats()``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["StatRegistry", "stat_registry", "stat_add", "stat_sub",
           "get_stat", "get_all_stats", "device_memory_stats", "op_summary"]


class _Stat:
    __slots__ = ("name", "value", "lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.lock = threading.Lock()

    def increase(self, v):
        with self.lock:
            self.value += v

    def decrease(self, v):
        with self.lock:
            self.value -= v

    def reset(self):
        with self.lock:
            self.value = 0


class StatRegistry:
    """Process-wide named counters (reference monitor.h:77)."""

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> _Stat:
        with self._lock:
            if name not in self._stats:
                self._stats[name] = _Stat(name)
            return self._stats[name]

    def add(self, name: str, value=1):
        self.get(name).increase(value)

    def sub(self, name: str, value=1):
        self.get(name).decrease(value)

    def value(self, name: str):
        return self.get(name).value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {n: s.value for n, s in sorted(self._stats.items())}

    def reset(self, name: Optional[str] = None):
        if name is not None:
            self.get(name).reset()  # auto-create like get()/value()
            return
        with self._lock:
            targets = list(self._stats.values())
        for s in targets:
            s.reset()


_registry = StatRegistry()


def stat_registry() -> StatRegistry:
    return _registry


def stat_add(name: str, value=1):
    """STAT_ADD macro analog."""
    _registry.add(name, value)


def stat_sub(name: str, value=1):
    _registry.sub(name, value)


def get_stat(name: str):
    return _registry.value(name)


def get_all_stats() -> Dict[str, float]:
    return _registry.snapshot()


def device_memory_stats(device_index: int = 0) -> Dict[str, int]:
    """Per-device allocator stats from the PJRT client (≙ the reference's
    STAT_gpu0_mem_size family fed by the CUDA allocator)."""
    import jax
    devs = jax.local_devices()
    if device_index >= len(devs):
        return {}
    stats = devs[device_index].memory_stats() or {}
    return {k: int(v) for k, v in stats.items()}


def op_summary(top: int = 20) -> List[Tuple[str, int, float]]:
    """(name, calls, total seconds) rows from the profiler's RecordEvent
    aggregation (profiler._events), sorted by total time — the op-summary
    table view of the reference's profiler output."""
    from .. import profiler
    rows = [(n, int(c), float(t)) for n, (c, t) in profiler._events.items()]
    rows.sort(key=lambda r: -r[2])
    return rows[:top]
