"""DLPack interop (reference: utils/dlpack.py to_dlpack/from_dlpack) —
zero-copy exchange with other frameworks via the array-object DLPack
protocol (``__dlpack__``/``__dlpack_device__``; the legacy PyCapsule form
was retired by the ecosystem and by jax 0.9)."""

from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Return a DLPack-capable array object for ``x`` (consumable by
    torch/np/cupy ``from_dlpack``)."""
    return getattr(x, "_data", x)


def from_dlpack(ext):
    """Wrap an external DLPack-capable array (torch/np/cupy tensor, or the
    object returned by :func:`to_dlpack`) as a Tensor, zero-copy where the
    producer allows it."""
    import jax.dlpack
    if not hasattr(ext, "__dlpack__"):
        raise TypeError(
            "from_dlpack expects an array object implementing __dlpack__ "
            "(the legacy PyCapsule protocol is no longer supported — pass "
            "the tensor itself)")
    return Tensor(jax.dlpack.from_dlpack(ext))
