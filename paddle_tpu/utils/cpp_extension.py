"""C++ extension loader (reference: python/paddle/utils/cpp_extension —
``load(name, sources)`` JIT-compiles user C++ into ops; ``setup`` builds a
wheel).

TPU-native split of responsibilities:

- **Device kernels** never come from user C++ here — TPU kernels are Pallas
  (see ops/custom.register_op); there is no user-facing Mosaic C++ ABI.
- **Host-side ops** (CPU pre/post-processing, custom IO, legacy numeric
  code) DO get the reference treatment: ``load`` compiles the sources with
  g++ into a shared library (no pybind11 in this image — the ABI is plain
  ``extern "C"``), and ``wrap_elementwise`` adapts an exported symbol into
  a jax-compatible op via ``jax.pure_callback``, so it composes with jit
  (as a host callback) and with the eager dispatch.

Exported-symbol ABI for ``wrap_elementwise``::

    extern "C" void <name>(const float* x, float* out, int64_t n);
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtensionError", "ExtensionModule"]

_LOCK = threading.Lock()


class CppExtensionError(RuntimeError):
    pass


class ExtensionModule:
    """A loaded extension: raw ctypes handle + op-wrapping helpers."""

    def __init__(self, name: str, lib_path: str):
        self.name = name
        self.lib_path = lib_path
        self.lib = ctypes.CDLL(lib_path)

    def symbol(self, fn_name: str):
        try:
            return getattr(self.lib, fn_name)
        except AttributeError:
            raise CppExtensionError(
                f"{self.lib_path} exports no symbol {fn_name!r} (declare it "
                f'extern "C")') from None

    def wrap_elementwise(self, fn_name: str, dtype=np.float32) -> Callable:
        """Wrap ``void f(const T*, T*, int64)`` as a jax-compatible op.

        Returns a function on raw arrays usable eagerly, through
        ops.register_op, or inside jit (lowered as a host pure_callback —
        XLA moves the data host-side for this op, which is the honest cost
        of running foreign CPU code in a TPU program).
        """
        import jax
        cfun = self.symbol(fn_name)
        cfun.restype = None
        ctype = np.ctypeslib.ndpointer(dtype=dtype, flags="C_CONTIGUOUS")
        cfun.argtypes = [ctype, ctype, ctypes.c_int64]

        def host_op(x):
            x = np.ascontiguousarray(np.asarray(x, dtype=dtype))
            out = np.empty_like(x)
            cfun(x.reshape(-1), out.reshape(-1), x.size)
            return out

        def op(x):
            return jax.pure_callback(
                host_op, jax.ShapeDtypeStruct(x.shape, dtype), x,
                vmap_method="sequential")

        op.__name__ = fn_name
        return op


def load(name: str, sources: Sequence[str], extra_cxx_flags: Optional[list] = None,
         build_directory: Optional[str] = None, verbose: bool = False,
         **kwargs) -> ExtensionModule:
    """Compile ``sources`` into lib<name>.so and load it (reference
    cpp_extension.load signature; CUDA-specific kwargs are ignored)."""
    sources = [os.path.abspath(s) for s in sources]
    for s in sources:
        if not os.path.exists(s):
            raise CppExtensionError(f"source file not found: {s}")
    build_dir = build_directory or os.path.join(
        os.path.dirname(sources[0]), "_paddle_tpu_ext")
    out = os.path.join(build_dir, f"lib{name}.so")
    with _LOCK:
        stale = (not os.path.exists(out) or any(
            os.path.getmtime(out) < os.path.getmtime(s) for s in sources))
        if stale:
            os.makedirs(build_dir, exist_ok=True)
            cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
                   + (extra_cxx_flags or []) + sources
                   + ["-o", out + ".tmp", "-lpthread"])
            if verbose:
                print("[cpp_extension]", " ".join(cmd))
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise CppExtensionError(
                    f"compiling {name}:\n{' '.join(cmd)}\n{proc.stderr[-2000:]}")
            os.replace(out + ".tmp", out)
    return ExtensionModule(name, out)
