"""Fake-clock traffic simulation harness for the serving control plane.

Scaling policy is impossible to test against real engines on real time:
an end-to-end scale-up trajectory (SLO burn → alert dwell → spawn → warm
→ activate → recovery → idle dwell → drain) spans minutes of wall clock
and every XLA compile in between.  This module makes the whole
trajectory a deterministic CPU unit test: a **real**
:class:`~paddle_tpu.gateway.ServingGateway` (real queues, routing,
quarantine, drains) fronting **fake-timed** engines, all sharing one
injectable :class:`SimClock` — no sleeps, no device, no nondeterminism.

Pieces:

- :class:`SimClock` — the shared fake monotonic clock (``advance(dt)``).
- :class:`SimTracer` — a real :class:`~paddle_tpu.telemetry.Tracer`
  whose ``now()`` reads the sim clock, so ring timestamps,
  ``last_event_age_s`` (the gateway's stall/quarantine dial) and SLO
  windows all live on simulated time.
- :class:`SimEngine` — a host-only engine with the real scheduling
  surface (``add_request`` / ``step`` / ``pop_finished`` / ``cancel`` /
  ``warmup`` / ``compile_grid``): one token per active slot per
  ``step()``, deterministic token streams (stream equality pins
  zero-drop/replay correctness), a program-cache model that emits real
  tracer compile events (so warmup vs in-serve compile accounting — the
  PR 2/6 contracts — is exercised), and fault modes for chaos scenarios
  (``paddle_tpu.faults``): ``kill()`` replica death, ``stall(ticks)``
  temporary freeze, ``slow(factor)`` straggler, ``flaky(n)`` transient
  dispatch errors (the engine stops ticking / slows / raises while
  holding work; the gateway's stall health-check, hedging, and
  retry/breaker paths each get their natural trigger).
- workload generators — ``steady`` (Poisson), ``diurnal`` (sinusoid-
  modulated Poisson), ``flash_crowd`` (step spike) — seconds → rate
  callables, sampled per tick with a seeded Poisson draw.
- :class:`TrafficSim` — the driver: per ``dt`` tick it samples arrivals,
  submits them to the gateway, runs one gateway round (and one
  autoscaler ``evaluate()`` when attached), fires any scheduled
  injections (``at(t, fn)`` — replica death mid-burst), advances the
  clock, and samples a fleet/queue timeline.  ``run()`` returns a report:
  outcomes, TTFT percentiles (sim seconds), shed rate, the timeline, the
  autoscaler decision history, and the ``dropped`` list that must stay
  empty (the zero-drop contract across every transition).

This doubles as the scenario-diversity workload generator the ROADMAP
north star asks for: the same arrival processes drive the real engines
in ``bench.py`` A/Bs (``gpt_autoscale``) and the CPU tier-1 scenario
tests (``tests/test_autoscaler.py``).

Everything here is stdlib + telemetry — importing it never touches JAX,
so policy tests cost milliseconds.

No reference counterpart: the reference snapshot serves static batches;
this is the traffic side of the elastic-serving control plane
(docs/AUTOSCALING.md).
"""

from __future__ import annotations

import collections
import contextlib
import logging
import math
import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .faults import TransientDispatchError
from .telemetry import Tracer
from .utils.stats import StatRegistry, prometheus_text as _prometheus_text

__all__ = ["SimClock", "SimTracer", "SimEngine", "TrafficSim",
           "steady", "diurnal", "flash_crowd", "sim_tokens",
           "SimFleetHost", "build_sim_fleet"]


class SimClock:
    """Deterministic fake monotonic clock: a callable returning the
    current simulated seconds, advanced explicitly."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError("time only moves forward")
        self.t += float(dt)
        return self.t


class SimTracer(Tracer):
    """A real :class:`Tracer` on simulated time: ``now()`` reads the
    injected clock, so every ring event, ``last_event_age_s`` liveness
    peek and downstream consumer (gateway health checks, SLO windows,
    trace stitching) sees the fake timebase.  The epoch is 0 — sim time
    IS the shared timebase across every sim tracer."""

    def __init__(self, clock: Callable[[], float], **kwargs):
        self._sim_clock = clock
        super().__init__(**kwargs)
        self._t0 = 0.0

    def now(self) -> float:
        return float(self._sim_clock())


def sim_tokens(prompt: Sequence[int], n: int) -> List[int]:
    """The deterministic token stream a :class:`SimEngine` emits for
    ``prompt`` — the oracle stream-equality checks compare against
    (replays and reroutes must re-deliver exactly this)."""
    seed = sum(int(t) for t in prompt) * 31 + len(prompt)
    return [(seed + 7 * i) % 997 for i in range(int(n))]


class _SimRequest:
    __slots__ = ("rid", "prompt", "max_new", "on_token", "emitted",
                 "stream", "prefill_left")

    def __init__(self, rid, prompt, max_new, on_token):
        self.rid = rid
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.on_token = on_token
        self.emitted = 0
        self.stream = sim_tokens(prompt, max_new)
        self.prefill_left = 0        # prefill ticks before tokens flow


def sim_chain_keys(prompt: Sequence[int], block_size: int) -> List[str]:
    """The sim engines' chain-digest model: one string key per FULL
    prompt block, a pure function of the tokens — identical across
    replicas, so migrated pages address the same chains everywhere
    (the real engines' (pad, tokens) rolling digest, minus the
    bucketing)."""
    toks = [int(t) for t in prompt]
    return [f"sim:{block_size}:{tuple(toks[:(i + 1) * block_size])!r}"
            for i in range(len(toks) // block_size)]


class SimEngine:
    """Host-only fake-timed serving engine (module docstring).

    ``max_slots`` concurrent requests; each ``step()`` delivers
    ``tokens_per_tick`` tokens to every active request — service time in
    sim seconds is queueing + ``ceil(max_new / tokens_per_tick)`` driver
    ticks.  ``prompt_buckets`` shape the program-cache model (one
    ``prefill:<P>`` family per bucket + one ``decode``), matching the
    real engines' grids so warmup/compile accounting is exercised, not
    faked.  ``warmup_unsupported=True`` raises ``NotImplementedError``
    from ``warmup()`` — the TP/mesh-engine shape the autoscaler must
    degrade gracefully on."""

    prefix_caching = False

    def __init__(self, *, max_slots: int = 4, tokens_per_tick: int = 1,
                 prompt_buckets: Sequence[int] = (8, 16),
                 tracer: Optional[Tracer] = None,
                 compile_wall_s: float = 0.0,
                 warmup_unsupported: bool = False,
                 draft_k: int = 0, acceptance=0.0, spec_seed: int = 0,
                 prefix_caching: bool = False, block_size: int = 4,
                 kv_store=None, prefix_capacity_blocks: int = 64,
                 page_bytes: int = 1024,
                 prefill_ticks_per_block: int = 0,
                 logger: Optional[logging.Logger] = None):
        """``draft_k > 0`` enables the SEEDED speculative-acceptance
        model: each ``step()`` becomes one spec round per active request
        — ``draft_k`` tokens drafted, a per-request acceptance
        probability decides the leading accepted run, and the request
        advances by ``lead + 1`` tokens (so throughput scales with
        acceptance exactly like the real ragged spec engine, while the
        token STREAM stays ``sim_tokens`` — replay/reroute equality
        checks hold unchanged).  ``acceptance`` is either one
        probability for every request or a ``(lo, hi)`` pair from which
        each request draws its own (seeded by ``spec_seed`` and the
        request id).  Everything is deterministic: same seeds, same
        arrival order → the same lead sequence, tick for tick.  The
        ``spec_rounds`` / ``tokens_drafted`` / ``tokens_accepted``
        counters mirror the real engine's registry names."""
        if int(max_slots) < 1:
            raise ValueError("max_slots must be >= 1")
        if int(tokens_per_tick) < 1:
            raise ValueError("tokens_per_tick must be >= 1")
        if int(draft_k) < 0:
            raise ValueError("draft_k must be >= 0")
        if int(draft_k) > 0 and int(tokens_per_tick) != 1:
            # the spec model paces by acceptance (lead + 1 per round);
            # a conflicting tokens_per_tick would be silently ignored
            raise ValueError(
                "tokens_per_tick and draft_k are mutually exclusive "
                "pacing knobs — the acceptance model replaces the fixed "
                "burst")
        if int(draft_k) == 0 and (acceptance != 0.0 or spec_seed != 0):
            # the symmetric guard: acceptance knobs without draft_k would
            # silently measure non-speculative pacing
            raise ValueError(
                "acceptance/spec_seed need draft_k > 0 (the speculative "
                "acceptance model is off without a draft budget)")
        self.S = self.max_slots = int(max_slots)
        self.tokens_per_tick = int(tokens_per_tick)
        self.draft_k = int(draft_k)
        self._acceptance = (tuple(float(a) for a in acceptance)
                           if isinstance(acceptance, (tuple, list))
                           else float(acceptance))
        probs = (self._acceptance if isinstance(self._acceptance, tuple)
                 else (self._acceptance,))
        if (len(probs) not in (1, 2)
                or any(not 0.0 <= a <= 1.0 for a in probs)
                or (len(probs) == 2 and probs[0] > probs[1])):
            raise ValueError(
                "acceptance must be a probability in [0, 1] or an "
                "ordered (lo, hi) pair of them")
        self._spec_seed = int(spec_seed)
        # ---- tier / migration model (docs/KV_TIERING.md) ----
        # the real paged engines' prefix-cache + TieredKVStore surface,
        # host-only: chains are pure token functions (sim_chain_keys),
        # the "HBM" tier is a capacity-bounded LRU of chains, eviction
        # demotes into the attached kv_store, admission restores from
        # it, and ``prefill_ticks_per_block`` makes warmth VISIBLE on
        # the fake clock (a warm block skips its prefill ticks — the
        # TTFT benefit tier-aware routing and the migration A/B pin).
        self.prefix_caching = bool(prefix_caching)
        self.bs = int(block_size)
        if self.bs < 1:
            raise ValueError("block_size must be >= 1")
        if kv_store is not None and not self.prefix_caching:
            raise ValueError("kv_store needs prefix_caching=True — pages "
                             "are addressed by prefix chain keys")
        self.kv_store = kv_store
        self.prefix_capacity_blocks = int(prefix_capacity_blocks)
        if self.prefix_capacity_blocks < 1:
            raise ValueError("prefix_capacity_blocks must be >= 1")
        self.page_bytes = int(page_bytes)
        self.prefill_ticks_per_block = int(prefill_ticks_per_block)
        if self.prefill_ticks_per_block < 0:
            raise ValueError("prefill_ticks_per_block must be >= 0")
        self._prefix: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self.buckets = tuple(sorted(int(b) for b in prompt_buckets))
        self.tracer = tracer
        self.compile_wall_s = float(compile_wall_s)
        self.warmup_unsupported = bool(warmup_unsupported)
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._rids = 0
        self._queue: List[_SimRequest] = []
        self._active: Dict[int, _SimRequest] = {}
        self._finished: Dict[int, List[int]] = {}
        self._progs: set = set()
        self._in_warmup = False
        self.warmed = False
        self.dead = False
        self.in_serve_compiles = 0
        # fault modes beyond kill() (paddle_tpu.faults integration):
        # stall freezes N rounds (silent — the stall health-check sees a
        # dead tracer), slow delivers work only every factor-th round
        # (but stays visibly alive), flaky fails the next N dispatches
        self._stall_ticks = 0
        self._slow_factor = 1
        self._slow_phase = 0
        self._flaky = 0
        self.stats = StatRegistry()

    # -------------------------------------------------------------- grid --

    def compile_grid(self) -> List[str]:
        return [f"prefill:{P}" for P in self.buckets] + ["decode"]

    def _bucket_label(self, prompt_len: int) -> str:
        for P in self.buckets:
            if prompt_len <= P:
                return f"prefill:{P}"
        return f"prefill:{self.buckets[-1]}"

    def _fetch(self, label: str):
        """One program-cache access: misses outside warmup are in-serve
        compiles (the count the zero-compile acceptance pin reads)."""
        hit = label in self._progs
        if not hit:
            self._progs.add(label)
            if not self._in_warmup:
                self.in_serve_compiles += 1
                self.stats.add("in_serve_compiles")
        if self.tracer is not None:
            self.tracer.compile_event(
                "sim", label, hit=hit,
                wall_s=0.0 if hit else self.compile_wall_s)

    def warmup(self, cache_dir: Optional[str] = None, max_workers: int = 1,
               block: bool = True) -> Dict[str, Any]:
        """Precompile the full grid (instant in sim time).  With a tracer
        the run sits in an ``expected_compiles`` window keyed to the
        grid, same as the real engines' warmup."""
        if self.warmup_unsupported:
            raise NotImplementedError(
                "sim engine configured unwarmable (the TP/mesh shape)")
        grid = self.compile_grid()
        ctx = (self.tracer.expected_compiles(keys=set(grid))
               if self.tracer is not None else contextlib.nullcontext())
        self._in_warmup = True
        try:
            with ctx:
                for label in grid:
                    self._fetch(label)
        finally:
            self._in_warmup = False
        self.warmed = True
        return {"programs": len(grid), "wall_s": 0.0,
                "cache_dir": None if cache_dir is None else str(cache_dir)}

    # --------------------------------------------------------- scheduling --

    def _free_slots(self) -> List[int]:
        return list(range(self.S - len(self._active)))

    def add_request(self, prompt, max_new_tokens: int, on_token=None,
                    trace_ctx=None, **sampling) -> int:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self._flaky > 0:
            self._flaky -= 1
            self.stats.add("dispatch_errors")
            raise TransientDispatchError(
                "sim engine flaky dispatch (injected)")
        rid = self._rids
        self._rids += 1
        req = _SimRequest(rid, prompt, max_new_tokens, on_token)
        if self.tracer is not None and trace_ctx is not None:
            self.tracer.bind_trace(rid, trace_ctx)
        self._queue.append(req)
        self.stats.add("requests_admitted")
        return rid

    def step(self):
        """One scheduler round: admit queued requests into free slots
        (paying the prefill program fetch), then deliver
        ``tokens_per_tick`` tokens to every active request.  A dead
        engine does nothing — no tokens AND no tracer events, so its
        tracer's event age grows with simulated time and the gateway's
        stall health-check fires."""
        if self.dead:
            return
        if self._stall_ticks > 0:
            self._stall_ticks -= 1      # frozen AND silent: no tracer
            return                      # event, so the stall check fires
        if self._slow_factor > 1:
            self._slow_phase += 1
            if self._slow_phase % self._slow_factor != 0:
                # a straggler is SLOW, not dead: it shows liveness (the
                # tick event below keeps the stall health-check green)
                # but moves no tokens this round
                if self.tracer is not None:
                    self.tracer.tick("sim", 0.0,
                                     active=len(self._active),
                                     queued=len(self._queue), slow=True)
                return
        while self._queue and len(self._active) < self.S:
            req = self._queue.pop(0)
            self._fetch(self._bucket_label(len(req.prompt)))
            nblocks = len(req.prompt) // self.bs
            if self.prefix_caching:
                # warm blocks (HBM hit or lower-tier restore) skip their
                # prefill ticks — the tier benefit on the fake clock
                warm = self._warm_prefix(req.prompt)
                req.prefill_left = max(nblocks - warm, 0) \
                    * self.prefill_ticks_per_block
                if req.prefill_left == 0:
                    self._register_chains(req.prompt)
            else:
                req.prefill_left = nblocks * self.prefill_ticks_per_block
            self._active[req.rid] = req
        if self._active:
            self._fetch("decode")
            if self.draft_k:
                self.stats.add("spec_rounds")
        retired = []
        for rid, req in list(self._active.items()):
            if req.prefill_left > 0:
                req.prefill_left -= 1
                if req.prefill_left == 0 and self.prefix_caching:
                    self._register_chains(req.prompt)
                continue
            if self.draft_k:
                # seeded acceptance model: one spec round — draft_k
                # drafted, the leading accepted run + 1 delivered.  The
                # STREAM is unchanged (sim_tokens), only pacing scales
                # with acceptance, mirroring the real ragged spec engine.
                lead = self._spec_lead(req)
                self.stats.add("tokens_drafted", self.draft_k)
                self.stats.add("tokens_accepted", lead)
                burst = lead + 1
            else:
                burst = self.tokens_per_tick
            for _ in range(burst):
                tok = req.stream[req.emitted]
                req.emitted += 1
                # same counter name as the real serving engines, so a
                # FleetCollector's tokens/s rollup reads sim and real
                # targets through one suffix
                self.stats.add("tokens_emitted")
                done = req.emitted >= req.max_new
                if req.on_token is not None:
                    req.on_token(rid, tok, done)
                if done:
                    retired.append(rid)
                    break
        for rid in retired:
            req = self._active.pop(rid)
            self._finished[rid] = list(req.stream)
            self.stats.add("requests_finished")
            if self.tracer is not None:
                self.tracer.bind_trace(rid, None)
        if self.tracer is not None:
            self.tracer.tick("sim", 0.0, active=len(self._active),
                             queued=len(self._queue))

    def _req_acceptance(self, rid: int) -> float:
        """The request's own acceptance probability: fixed when
        ``acceptance`` is a float, drawn once (seeded by rid) from the
        ``(lo, hi)`` range otherwise."""
        a = self._acceptance
        if isinstance(a, tuple):
            lo, hi = a
            rng = random.Random((self._spec_seed << 20)
                                ^ (rid * 2654435761))
            return lo + (hi - lo) * rng.random()
        return a

    def _spec_lead(self, req: "_SimRequest") -> int:
        """Deterministic accepted-run draw for one spec round: count
        leading Bernoulli(p) successes over draft_k trials, seeded by
        (spec_seed, rid, tokens emitted so far) — same seeds replay the
        identical lead sequence."""
        p = self._req_acceptance(req.rid)
        rng = random.Random((self._spec_seed * 1000003)
                            ^ (req.rid * 7919) ^ (req.emitted << 1))
        lead = 0
        while lead < self.draft_k and rng.random() < p:
            lead += 1
        return lead

    # ---------------------------------------------- tier / migration --
    # (the real paged engines' public KV-tiering surface, host-only —
    # docs/KV_TIERING.md; the gateway's disaggregated pipeline and the
    # tier-aware router drive sim fleets through these exactly as they
    # drive real ones)

    def kv_page_meta(self):
        """Portable page signature (JSON-able lists, the KVPage meta
        contract): sim engines exchange pages iff block size and page
        width match."""
        return ["sim", self.bs, self.page_bytes]

    def attach_kv_store(self, store):
        if store is not None and not self.prefix_caching:
            raise ValueError("kv_store needs prefix_caching=True — pages "
                             "are addressed by prefix chain keys")
        self.kv_store = store
        return store

    def _enforce_prefix_capacity(self):
        from .kv_store import KVPage
        while len(self._prefix) > self.prefix_capacity_blocks:
            chain, _ = self._prefix.popitem(last=False)       # LRU first
            if self.kv_store is None:
                continue        # no lower tier: the page is DROPPED —
                #                 counting a "demotion" here would fake
                #                 tier traffic that never happened (the
                #                 real engines' store-gated discipline)
            self.kv_store.put(KVPage(chain, bytes(self.page_bytes),
                                     self.kv_page_meta()))
            self.stats.add("kvstore_demoted_blocks")
            if self.tracer is not None:
                self.tracer.emit("kvstore", what="demote",
                                 chain=chain[:48], bytes=self.page_bytes,
                                 engine="sim")

    def _register_chains(self, prompt):
        for chain in sim_chain_keys(prompt, self.bs):
            self._prefix[chain] = None
            self._prefix.move_to_end(chain)
        self._enforce_prefix_capacity()

    def _warm_prefix(self, prompt) -> int:
        """Leading warm blocks at admission: HBM hits LRU-touch, lower-
        tier hits RESTORE (store → prefix LRU), a miss stops the walk —
        the sim mirror of the real engines' restore-before-fill."""
        depth = 0
        for chain in sim_chain_keys(prompt, self.bs):
            if chain in self._prefix:
                self._prefix.move_to_end(chain)
            elif self.kv_store is not None and self.kv_store.lookup(
                    chain, meta=self.kv_page_meta()) is not None:
                self._prefix[chain] = None
                self.stats.add("kvstore_restored_blocks")
                if self.tracer is not None:
                    self.tracer.emit("kvstore", what="restore",
                                     chain=chain[:48],
                                     bytes=self.page_bytes, engine="sim")
            else:
                break
            depth += 1
        self._enforce_prefix_capacity()
        return depth

    def flush_prefix(self) -> int:
        """Demote every cached chain to the attached store (the bench /
        smoke primitive); returns the demoted count."""
        if self.kv_store is None:
            raise ValueError("flush_prefix needs an attached kv_store")
        from .kv_store import KVPage
        n = 0
        while self._prefix:
            chain, _ = self._prefix.popitem(last=False)
            self.kv_store.put(KVPage(chain, bytes(self.page_bytes),
                                     self.kv_page_meta()))
            n += 1
        self.stats.add("kvstore_demoted_blocks", n)
        return n

    def export_prefix_pages(self, prompt) -> List[Any]:
        """Leading resident pages for ``prompt`` (migration source
        primitive); stops at the first miss."""
        if not self.prefix_caching:
            return []
        from .kv_store import KVPage
        pages: List[Any] = []
        for chain in sim_chain_keys(prompt, self.bs):
            if chain in self._prefix:
                pages.append(KVPage(chain, bytes(self.page_bytes),
                                    self.kv_page_meta()))
                continue
            if self.kv_store is not None:
                page = self.kv_store.lookup(chain,
                                            meta=self.kv_page_meta())
                if page is not None:
                    pages.append(page)
                    continue
            break
        return pages

    def prefix_index(self) -> Dict[str, str]:
        """PUBLIC tier map (serving.py contract)."""
        idx = {chain: "hbm" for chain in self._prefix}
        if self.kv_store is not None:
            for chain, tier in self.kv_store.index().items():
                idx.setdefault(chain, tier)
        return idx

    def prefix_match(self, prompt) -> Dict[str, Any]:
        """PUBLIC tier-aware affinity read (serving.py contract): pure —
        no LRU touch, no restore."""
        out: Dict[str, Any] = {"hbm": 0, "total": 0, "tiers": []}
        if not self.prefix_caching:
            return out
        leading_hbm = True
        for chain in sim_chain_keys(prompt, self.bs):
            if chain in self._prefix:
                tier = "hbm"
            else:
                tier = (self.kv_store.tier_of(chain)
                        if self.kv_store is not None else None)
                if tier is None:
                    break
            if tier != "hbm":
                leading_hbm = False
            if leading_hbm:
                out["hbm"] += 1
            out["total"] += 1
            out["tiers"].append(tier)
        return out

    def cancel(self, rid: int) -> bool:
        """Release one in-flight request (queued or active) and deliver
        the terminal stream signal — the serving.py primitive the
        gateway's deadline/quarantine paths ride."""
        req = None
        for i, q in enumerate(self._queue):
            if q.rid == rid:
                req = self._queue.pop(i)
                break
        if req is None:
            req = self._active.pop(rid, None)
        if req is None:
            return False
        self.stats.add("requests_cancelled")
        if self.tracer is not None:
            self.tracer.bind_trace(rid, None)
        if req.on_token is not None:
            req.on_token(rid, None, True)
        return True

    def pending(self) -> bool:
        return bool(self._queue) or bool(self._active)

    def pop_finished(self) -> Dict[int, List[int]]:
        out, self._finished = self._finished, {}
        return out

    def kill(self):
        """Replica-death injection: freeze the engine mid-work."""
        self.dead = True

    def stall(self, ticks: int):
        """Stall injection: freeze for ``ticks`` scheduler rounds —
        silent (no tracer events), so the gateway's stall health-check
        quarantines it if the freeze outlasts ``stall_threshold_s`` —
        then resume where it left off."""
        if int(ticks) < 0:
            raise ValueError("ticks must be >= 0")
        self._stall_ticks = int(ticks)

    def slow(self, factor: int):
        """Straggler injection: serve one real round per ``factor``
        ``step()`` calls (``factor=1`` restores full speed).  Unlike a
        stall the engine stays visibly alive — slow replicas are the
        hedging workload, not the quarantine workload."""
        if int(factor) < 1:
            raise ValueError("factor must be >= 1")
        self._slow_factor = int(factor)
        self._slow_phase = 0

    def flaky(self, n: int):
        """Transient-dispatch-error injection: the next ``n``
        ``add_request`` calls raise
        :class:`~paddle_tpu.faults.TransientDispatchError` (the
        retryable class the gateway's retry/breaker path keys on)."""
        if int(n) < 0:
            raise ValueError("n must be >= 0")
        self._flaky = int(n)

    # --------------------------------------------------------- telemetry --

    def metrics(self) -> Dict[str, float]:
        out = dict(self.stats.snapshot())
        out["active"] = float(len(self._active))
        out["queued"] = float(len(self._queue))
        if self.draft_k:
            out["acceptance_rate"] = (
                float(out.get("tokens_accepted", 0))
                / max(float(out.get("tokens_drafted", 0)), 1.0))
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_sim_engine"
                        ) -> str:
        return _prometheus_text(
            self.stats, namespace=namespace,
            extra_gauges={"active": len(self._active),
                          "queued": len(self._queue),
                          "warmed": int(self.warmed),
                          "dead": int(self.dead)})


# ---------------------------------------------------------------------------
# workload generators
# ---------------------------------------------------------------------------

def steady(rate_per_s: float) -> Callable[[float], float]:
    """Constant-rate Poisson arrivals."""
    r = float(rate_per_s)
    if r < 0:
        raise ValueError("rate must be >= 0")
    return lambda t: r


def diurnal(base_per_s: float, peak_per_s: float, period_s: float,
            phase_s: float = 0.0) -> Callable[[float], float]:
    """Sinusoid-modulated arrivals: rate(t) swings ``base → peak → base``
    over ``period_s``, starting at the trough (t = ``phase_s``) — the
    day/night traffic shape."""
    base, peak = float(base_per_s), float(peak_per_s)
    if base < 0 or peak < base:
        raise ValueError("need 0 <= base <= peak")
    period = float(period_s)
    if period <= 0:
        raise ValueError("period_s must be > 0")

    def rate(t: float) -> float:
        x = 2.0 * math.pi * ((t - phase_s) / period)
        return base + (peak - base) * 0.5 * (1.0 - math.cos(x))
    return rate


def flash_crowd(base_per_s: float, spike_per_s: float, at_s: float,
                duration_s: float) -> Callable[[float], float]:
    """Step spike: ``base`` everywhere except ``[at_s, at_s +
    duration_s)`` where the rate jumps to ``spike`` — the cache-miss
    stampede / launch-event shape."""
    base, spike = float(base_per_s), float(spike_per_s)
    if base < 0 or spike < 0:
        raise ValueError("rates must be >= 0")
    t0, t1 = float(at_s), float(at_s) + float(duration_s)
    return lambda t: spike if t0 <= t < t1 else base


def _poisson(rng: random.Random, lam: float) -> int:
    """Seeded Poisson draw (Knuth for small λ, normal approximation past
    30 — per-tick λ in any sane sim sits well under that)."""
    if lam <= 0.0:
        return 0
    if lam > 30.0:
        return max(0, int(round(rng.gauss(lam, math.sqrt(lam)))))
    limit = math.exp(-lam)
    n, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return n
        n += 1


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

class TrafficSim:
    """Drive a workload through a real gateway on the fake clock (module
    docstring).  ``rate_fn``: seconds → arrivals/second (the generators
    above, or any callable).  ``dt``: sim seconds per driver tick — each
    tick is one arrival sample + one ``gateway.step()`` (+ one
    ``autoscaler.evaluate()``).  ``seed`` fixes the arrival process and
    request shapes — identical seeds replay identical scenarios."""

    def __init__(self, gateway, clock: SimClock,
                 rate_fn: Callable[[float], float], *, dt: float = 0.25,
                 seed: int = 0, prompt_len: Tuple[int, int] = (3, 12),
                 max_new: Tuple[int, int] = (4, 8), vocab: int = 997,
                 priority: int = 0, autoscaler=None,
                 sample_every_s: float = 1.0,
                 ttft_deadline_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 logger: Optional[logging.Logger] = None):
        if float(dt) <= 0:
            raise ValueError("dt must be > 0")
        self.gateway = gateway
        self.clock = clock
        self.rate_fn = rate_fn
        self.dt = float(dt)
        self.seed = int(seed)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.max_new = (int(max_new[0]), int(max_new[1]))
        self.vocab = int(vocab)
        self.priority = int(priority)
        self.autoscaler = autoscaler
        self.sample_every_s = float(sample_every_s)
        # optional per-request deadlines: the gateway's hedging trigger
        # (TTFT-at-risk) and expiry paths need them to exist in the
        # workload, exactly like real traffic carries them
        self.ttft_deadline_s = ttft_deadline_s
        self.deadline_s = deadline_s
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self.handles: List[Any] = []
        self.samples: List[Dict[str, Any]] = []
        self._injections: List[Tuple[float, Callable[[], None], str]] = []
        self._fired: List[str] = []
        self._last_sample_at: Optional[float] = None

    def at(self, t_s: float, fn: Callable[[], None], label: str = "event"
           ) -> "TrafficSim":
        """Schedule an injection: ``fn()`` fires on the first tick at or
        after sim time ``t_s`` (replica death mid-burst:
        ``sim.at(30, engine.kill, "kill r1")``)."""
        self._injections.append((float(t_s), fn, str(label)))
        self._injections.sort(key=lambda e: e[0])
        return self

    # ------------------------------------------------------------- drive --

    def _submit_arrivals(self, rng: random.Random, n: int):
        for _ in range(n):
            plen = rng.randint(*self.prompt_len)
            prompt = [rng.randint(1, self.vocab) for _ in range(plen)]
            self.handles.append(self.gateway.submit(
                prompt, rng.randint(*self.max_new),
                priority=self.priority,
                ttft_deadline_s=self.ttft_deadline_s,
                deadline_s=self.deadline_s))

    def _fire_due(self, t: float):
        while self._injections and self._injections[0][0] <= t:
            _ts, fn, label = self._injections.pop(0)
            self._fired.append(label)
            fn()

    def _sample(self, t: float):
        if self._last_sample_at is not None \
                and t - self._last_sample_at < self.sample_every_s - 1e-9:
            return
        self._last_sample_at = t
        reps = self.gateway.replicas()
        self.samples.append({
            "t": t,
            "active": sum(1 for r in reps if r.state == "active"),
            "draining": sum(1 for r in reps if r.state == "draining"),
            "quarantined": sum(1 for r in reps
                               if r.state == "quarantined"),
            "queued": sum(d["depth"] for d in
                          self.gateway.queue_depths().values()),
            "inflight": sum(len(r.inflight) for r in reps),
            "rate": self.rate_fn(t),
        })

    def _tick(self, rng: Optional[random.Random]):
        t = self.clock()
        self._fire_due(t)
        if rng is not None:
            self._submit_arrivals(rng,
                                  _poisson(rng, self.rate_fn(t) * self.dt))
        self.gateway.step()
        if self.autoscaler is not None:
            self.autoscaler.evaluate()
        self._sample(t)
        self.clock.advance(self.dt)

    def run(self, duration_s: float, drain: bool = True,
            max_drain_ticks: int = 100000) -> Dict[str, Any]:
        """Run the scenario for ``duration_s`` sim seconds, then (with
        ``drain=True``) keep ticking WITHOUT new arrivals until nothing
        is queued or in flight — every admitted request must reach a
        terminal state for the report's zero-drop accounting to mean
        anything.  A scenario that cannot drain inside
        ``max_drain_ticks`` stops and reports the stuck requests in
        ``dropped`` instead of raising — report honesty over an
        exception."""
        rng = random.Random(self.seed)
        end = self.clock() + float(duration_s)
        while self.clock() < end - 1e-9:
            self._tick(rng)
        if drain:
            ticks = 0
            while self.gateway.pending() and ticks < int(max_drain_ticks):
                self._tick(None)
                ticks += 1
            if self.gateway.pending():
                self._log.warning(
                    "sim: scenario did not drain in %d ticks", ticks)
        return self.report()

    # ------------------------------------------------------------ report --

    @staticmethod
    def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
        if not sorted_vals:
            return None
        i = min(len(sorted_vals) - 1,
                max(0, math.ceil(q * len(sorted_vals)) - 1))
        return sorted_vals[i]

    def report(self) -> Dict[str, Any]:
        outcomes: Dict[str, int] = {}
        ttfts: List[float] = []
        for h in self.handles:
            outcomes[h.status] = outcomes.get(h.status, 0) + 1
            if h.status == "finished" and h.first_token_at is not None:
                ttfts.append(h.first_token_at - h.submitted_at)
        ttfts.sort()
        offered = len(self.handles)
        shed = outcomes.get("shed", 0)
        # dropped = admitted but never terminal: the zero-drop contract
        # every scaling transition must preserve
        dropped = [h.gid for h in self.handles if not h.done]
        report = {
            "offered": offered,
            "outcomes": outcomes,
            "shed_rate": (shed / offered) if offered else 0.0,
            "ttft_s": {
                "n": len(ttfts),
                "p50": self._percentile(ttfts, 0.50),
                "p95": self._percentile(ttfts, 0.95),
                "p99": self._percentile(ttfts, 0.99),
                "max": ttfts[-1] if ttfts else None,
            },
            "dropped": dropped,
            "injections_fired": list(self._fired),
            "timeline": list(self.samples),
            "end_t": self.clock(),
        }
        if self.autoscaler is not None:
            report["decisions"] = self.autoscaler.decisions()
            report["fleet"] = self.autoscaler.autoscaler_snapshot()["fleet"]
        return report

# ---------------------------------------------------------------------------
# simulated fleet (rank-0 collector over fake-clock hosts)
# ---------------------------------------------------------------------------

class SimFleetHost:
    """One simulated fleet member: a :class:`SimEngine` + its
    :class:`SimTracer` + a per-host ``SLOMonitor``, all on the shared
    :class:`SimClock`, behind an UNSTARTED
    :class:`~paddle_tpu.ops_server.OpsServer` — a scrape target a
    :class:`~paddle_tpu.telemetry_fleet.FleetCollector` federates
    through ``OpsServer.render()`` without binding a single port.  This
    is the multi-host rehearsal shape (one ops server per host, a rank-0
    collector scraping them) on deterministic time."""

    def __init__(self, clock: SimClock, *, name: str = "sim0",
                 slo_resolution_s: float = 5.0, **engine_kwargs):
        from .ops_server import OpsServer
        from .telemetry_ledger import RunLedger
        from .telemetry_slo import SLOMonitor
        self.name = str(name)
        self.clock = clock
        self.tracer = SimTracer(clock)
        self.engine = SimEngine(tracer=self.tracer, **engine_kwargs)
        self.slo = SLOMonitor(clock=clock, resolution_s=slo_resolution_s)
        self.tracer.set_slo(self.slo)
        self.ledger = RunLedger(clock=clock)
        self.server = OpsServer()
        self.server.attach(self.engine, name=f"{self.name}.engine")
        self.server.attach(self.slo, name=f"{self.name}.slo")
        self.server.attach(self.ledger, name=f"{self.name}.ledger")

    def submit(self, prompt, max_new_tokens: int, **sampling) -> int:
        """Admit one request through the host's request timeline: the
        tracer sees queued/first_token/token/retired, so TTFT and ITL
        samples flow into the host's SLO monitor (and from there into a
        federating collector's merged sketches) — the bookkeeping the
        gateway layer does in a full deployment, collapsed to one
        host."""
        state = {"started": False}

        def on_token(rid, _tok, done):
            if not state["started"]:
                state["started"] = True
                self.tracer.request_event(rid, "admitted")
                self.tracer.request_event(rid, "first_token")
            self.tracer.request_event(rid, "token")
            if done:
                self.tracer.request_event(rid, "retired")

        rid = self.engine.add_request(prompt, max_new_tokens,
                                      on_token=on_token, **sampling)
        self.tracer.request_event(rid, "queued",
                                  prompt_len=len(list(prompt)))
        return rid


def build_sim_fleet(clock: SimClock, n_hosts: int = 3, *,
                    interval_s: float = 5.0, objectives=(),
                    spool_dir: Optional[str] = None, **engine_kwargs):
    """A rank-0 :class:`~paddle_tpu.telemetry_fleet.FleetCollector` on
    the shared fake clock over ``n_hosts`` :class:`SimFleetHost` s —
    returns ``(collector, hosts)``.  Drive hosts (``host.engine.step()``
    etc.), ``clock.advance(...)``, then ``collector.scrape_once()``: the
    whole federation pipeline (scrape → parse → merge → rollups → spool)
    runs deterministically with zero sockets and zero sleeps."""
    from .telemetry_fleet import FleetCollector
    if int(n_hosts) < 1:
        raise ValueError("n_hosts must be >= 1")
    hosts = [SimFleetHost(clock, name=f"sim{i}", **engine_kwargs)
             for i in range(int(n_hosts))]
    collector = FleetCollector(interval_s=interval_s, clock=clock,
                               objectives=objectives, spool_dir=spool_dir)
    for host in hosts:
        collector.add_target(host.name, server=host.server)
    return collector, hosts
