"""Inference API (reference: python/paddle/inference/__init__.py, C++
AnalysisPredictor — api/analysis_predictor.cc:151).

The reference's predictor loads a serialized program, runs IR optimization
passes, and exposes named zero-copy input/output handles.  TPU-native
equivalent: the artifact is the StableHLO export written by
``paddle.jit.save`` (XLA *is* the optimizing compiler — the analysis passes
collapse per SURVEY §7), and the handles hold device arrays directly, so
``copy_from_cpu → run → copy_to_cpu`` round-trips through one compiled
executable with no per-call retracing.

Usage (reference calling convention)::

    config = Config(path_prefix)          # the jit.save prefix
    predictor = create_predictor(config)
    names = predictor.get_input_names()
    predictor.get_input_handle(names[0]).copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    result = out.copy_to_cpu()
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorPool",
           "get_version"]


def get_version() -> str:
    from .. import __version__
    return __version__


class Config:
    """Predictor configuration (reference paddle_infer.Config).

    Accepts the ``jit.save`` path prefix, or (prog_file, params_file) where
    prog_file ends in ``.pdmodel``.  GPU/IR/memory knobs are accepted for
    API parity and recorded; placement follows the JAX default device and
    XLA owns optimization.
    """

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._params_file = params_file
        self._use_gpu = False
        self._ir_optim = True
        self._memory_optim = True
        self._cpu_math_threads = 1

    # -- model location ----------------------------------------------------
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        # only the model location changes — previously-set knobs survive
        # (the reference Config.set_model does not reset options)
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        if params_file is not None:
            self._params_file = params_file

    def set_prog_file(self, path: str):
        self.set_model(path, self._params_file)

    def model_dir(self) -> str:
        return os.path.dirname(self._prefix or "")

    def prog_file(self) -> str:
        return (self._prefix or "") + ".pdmodel"

    def params_file(self) -> str:
        return self._params_file or (self._prefix or "") + ".pdiparams"

    # -- device / optimization knobs (parity; XLA decides) ------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self) -> bool:
        return self._use_gpu

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def ir_optim(self) -> bool:
        return self._ir_optim

    def enable_memory_optim(self):
        self._memory_optim = True

    def set_cpu_math_library_num_threads(self, n: int):
        self._cpu_math_threads = int(n)

    def enable_mkldnn(self):  # no MKLDNN in an XLA build
        pass

    def disable_glog_info(self):
        pass

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix!r}, backend="
                f"{jax.default_backend()}, ir_optim={self._ir_optim})")


class _IOHandle:
    """Named input/output tensor handle (reference ZeroCopyTensor)."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self._dtype = dtype
        self._value: Optional[jax.Array] = None

    def reshape(self, shape):
        self._shape = tuple(int(s) for s in shape)

    def copy_from_cpu(self, data: np.ndarray):
        arr = np.asarray(data)
        if self._dtype is not None:
            arr = arr.astype(self._dtype, copy=False)
        self._value = jax.device_put(arr)

    def share_external_data(self, data):
        self._value = jax.device_put(getattr(data, "_data", data))

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"handle {self.name!r} holds no data")
        return np.asarray(self._value)

    def shape(self):
        if self._value is not None:
            return list(self._value.shape)
        return list(self._shape or [])


class Predictor:
    """Compiled inference session over a ``jit.save`` artifact."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        self._gen_fn = None
        prefix = config._prefix or ""
        if os.path.exists(prefix + ".genmodel") and \
                os.path.exists(config.prog_file()):
            raise ValueError(
                f"ambiguous artifacts at {prefix!r}: both a jit.save "
                f".pdmodel and a generation .genmodel exist — use distinct "
                f"prefixes (silently picking one would serve the wrong "
                f"program)")
        if os.path.exists(prefix + ".genmodel"):
            # generation artifact (models/_decode.py save_generate_program):
            # same handle surface — inputs are input_ids + seed [+ mask]
            from ..models._decode import load_generate_program
            self._gen_fn, meta = load_generate_program(prefix)
            self._layer = None
            names = ["input_ids", "seed"]
            shapes = [(meta["batch_size"], meta["prompt_len"]), ()]
            dtypes = ["int32", "uint32"]
            if meta["masked"]:
                names.append("prompt_mask")
                shapes.append((meta["batch_size"], meta["prompt_len"]))
                dtypes.append("int32")
        else:
            if not os.path.exists(config.prog_file()):
                raise ValueError(f"model file {config.prog_file()!r} not found")
            self._layer = jit_load(config._prefix)
            meta = getattr(self._layer, "_meta", {}) or {}
            names = meta.get("input_names") or \
                [f"x{i}" for i in range(meta.get("n_inputs", 1))]
            shapes = meta.get("input_shapes") or [None] * len(names)
            dtypes = meta.get("input_dtypes") or [None] * len(names)
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle(n, s, d) for n, s, d in zip(names, shapes, dtypes)}
        if self._gen_fn is not None:
            self._inputs["seed"]._value = np.uint32(0)  # optional input
        self._input_order = names
        self._outputs: Dict[str, _IOHandle] = {}
        self._output_order: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_order)

    def get_input_handle(self, name: str) -> _IOHandle:
        if name not in self._inputs:
            raise KeyError(f"unknown input {name!r}; inputs: {self._input_order}")
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute the compiled program.  ``inputs`` (positional list) is the
        convenience form; otherwise values come from the input handles."""
        if inputs is not None:
            if len(inputs) != len(self._input_order):
                raise ValueError(
                    f"run() got {len(inputs)} inputs for {len(self._input_order)} "
                    f"model inputs {self._input_order}")
            for n, v in zip(self._input_order, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(v))
        missing = [n for n in self._input_order
                   if self._inputs[n]._value is None]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        if self._gen_fn is not None:
            kw = {}
            if "prompt_mask" in self._inputs:
                kw["prompt_mask"] = self._inputs["prompt_mask"]._value
            out = self._gen_fn(self._inputs["input_ids"]._value,
                               seed=self._inputs["seed"]._value, **kw)
        else:
            args = [self._inputs[n]._value for n in self._input_order]
            out = self._layer._exported.call(self._layer._params,
                                             self._layer._buffers, *args)
        leaves = jax.tree_util.tree_leaves(out)
        self._output_order = [f"output_{i}" for i in range(len(leaves))]
        self._outputs = {}
        for nm, leaf in zip(self._output_order, leaves):
            h = _IOHandle(nm)
            h._value = leaf
            self._outputs[nm] = h
        if inputs is not None:
            return [np.asarray(l) for l in leaves]
        return None

    def get_output_names(self) -> List[str]:
        return list(self._output_order)

    def get_output_handle(self, name: str) -> _IOHandle:
        if name not in self._outputs:
            raise KeyError(f"unknown output {name!r} (run() first); "
                           f"outputs: {self._output_order}")
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass

    def clone(self) -> "Predictor":
        p = Predictor.__new__(Predictor)
        p._layer = self._layer  # share the compiled executable + weights
        p._gen_fn = self._gen_fn
        p._inputs = {n: _IOHandle(h.name, h._shape, h._dtype)
                     for n, h in self._inputs.items()}
        if self._gen_fn is not None:
            p._inputs["seed"]._value = np.uint32(0)
        p._input_order = list(self._input_order)
        p._outputs = {}
        p._output_order = []
        return p


def create_predictor(config: Config) -> Predictor:
    """Reference: paddle_infer.create_predictor."""
    return Predictor(config)


class PredictorPool:
    """Pool of cloned predictors (reference PredictorPool) — clones share
    the compiled executable and weights, so the pool is cheap."""

    def __init__(self, config: Config, size: int = 1):
        base = create_predictor(config)
        self._preds = [base] + [base.clone() for _ in range(size - 1)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]
