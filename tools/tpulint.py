#!/usr/bin/env python
"""tpulint CLI — JAX/TPU correctness lint with a ratcheted baseline.

Usage:
    python tools/tpulint.py paddle_tpu tools            # CI gate (baseline)
    python tools/tpulint.py --no-baseline some/file.py  # raw findings
    python tools/tpulint.py --write-baseline paddle_tpu tools
    python tools/tpulint.py --json paddle_tpu tools     # machine-readable
    python tools/tpulint.py --list-rules

Exit codes (the contract tools/collect_smoke.sh and CI key off):
    0  clean — no findings beyond the committed baseline
    1  NEW violations (count above baseline for some file+rule), or any
       finding at all under --no-baseline
    2  usage / internal error (bad args, unreadable baseline)
    3  STALE baseline — the tree has fewer violations than the baseline
       records; shrink it with --write-baseline so the ratchet only
       turns one way

The engine lives in paddle_tpu/analysis/, loaded here by file path so the
lint never imports JAX (paddle_tpu/__init__.py pulls in the full
framework; a commit-time linter must not pay that).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
DEFAULT_BASELINE = ROOT / "tools" / "tpulint_baseline.json"


def load_analysis():
    """Import paddle_tpu.analysis WITHOUT importing paddle_tpu."""
    pkg_dir = ROOT / "paddle_tpu" / "analysis"
    spec = importlib.util.spec_from_file_location(
        "_tpulint_analysis", pkg_dir / "__init__.py",
        submodule_search_locations=[str(pkg_dir)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_tpulint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=["paddle_tpu", "tools"],
                    help="files/dirs to lint (default: paddle_tpu tools)")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help="ratchet baseline JSON (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding; exit 1 if any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current tree")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings + counts as JSON on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--root", type=Path, default=ROOT,
                    help="repo root for relative paths (default: %(default)s)")
    args = ap.parse_args(argv)

    analysis = load_analysis()

    if args.list_rules:
        for name, rule in sorted(analysis.RULES.items()):
            print(f"{name}\n    {rule.hazard}")
        return 0

    t0 = time.monotonic()
    paths = [Path(p) if Path(p).is_absolute() else args.root / p
             for p in (args.paths or ["paddle_tpu", "tools"])]
    for p in paths:
        if not p.exists():
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2
    findings = analysis.lint_paths(paths, root=args.root)
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        # guard: rewriting an existing baseline from a DIFFERENT path set
        # would silently truncate it to the subset's counts
        norm = sorted(str(p) for p in (args.paths or ["paddle_tpu", "tools"]))
        if args.baseline.exists():
            try:
                prior = json.loads(args.baseline.read_text()).get("paths")
            except (OSError, json.JSONDecodeError):
                prior = None
            if prior is not None and prior != norm:
                print(f"tpulint: refusing to overwrite {args.baseline}: it was "
                      f"generated from paths {prior}, this run lints {norm}.\n"
                      f"  Re-run over the original paths, or write a subset "
                      f"baseline elsewhere with --baseline OTHER.json",
                      file=sys.stderr)
                return 2
        analysis.write_baseline(args.baseline, findings, paths=norm)
        print(f"tpulint: wrote {len(findings)} baselined finding(s) to "
              f"{args.baseline} ({elapsed:.1f}s)")
        return 0

    if args.as_json:
        print(analysis.render_json(findings))

    if args.no_baseline:
        if not args.as_json and findings:
            print(analysis.render_text(findings))
        print(f"tpulint: {len(findings)} finding(s) in {elapsed:.1f}s "
              f"(no baseline)", file=sys.stderr)
        return 1 if findings else 0

    try:
        baseline = analysis.load_baseline(args.baseline)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"tpulint: cannot read baseline {args.baseline}: {e}\n"
              f"  (generate one with --write-baseline)", file=sys.stderr)
        return 2

    new, stale = analysis.diff_baseline(findings, baseline)
    if new:
        if not args.as_json:
            print(analysis.render_text(new))
        buckets = sorted({(f.path, f.rule) for f in new})
        print(f"tpulint: NEW violation(s) above baseline in "
              f"{len(buckets)} file+rule bucket(s) "
              f"({elapsed:.1f}s) — all sites for each bucket are listed; "
              f"fix the new one or (rarely) pragma it with a reason",
              file=sys.stderr)
        return 1
    if stale:
        for path, rule, cur, base in stale:
            print(f"{path}: {rule}: baseline records {base}, tree has {cur}",
                  file=sys.stderr)
        print("tpulint: STALE baseline — violations were burned down "
              "(good!); shrink the ratchet:\n"
              "  python tools/tpulint.py --write-baseline paddle_tpu tools",
              file=sys.stderr)
        return 3
    print(f"tpulint: OK — {len(findings)} baselined finding(s), 0 new, "
          f"{elapsed:.1f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
