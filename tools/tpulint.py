#!/usr/bin/env python
"""tpulint CLI — JAX/TPU correctness lint with a ratcheted baseline.

Usage:
    python tools/tpulint.py paddle_tpu tools            # per-file CI gate
    python tools/tpulint.py --program paddle_tpu tools  # + whole-program
                                                        #   concurrency passes
    python tools/tpulint.py --changed-only              # pre-commit: only
                                                        #   git-changed files
    python tools/tpulint.py --no-baseline some/file.py  # raw findings
    python tools/tpulint.py --write-baseline --program paddle_tpu tools
    python tools/tpulint.py --json paddle_tpu tools     # machine-readable
    python tools/tpulint.py --list-rules

Exit codes (the contract tools/collect_smoke.sh and CI key off):
    0  clean — no findings beyond the committed baseline
    1  NEW violations (count above baseline for some file+rule), or any
       finding at all under --no-baseline
    2  usage / internal error (bad args, unreadable baseline)
    3  STALE baseline — the tree has fewer violations than the baseline
       records; shrink it with --write-baseline so the ratchet only
       turns one way

Stages: the per-file rule sweep always runs; ``--program`` adds the
whole-program concurrency passes (thread-entry reachability, guarded-by
race detection — docs/STATIC_ANALYSIS.md § Whole-program passes).  The
baseline diff is stage-aware: a per-file-only run never reads the frozen
program-pass counts as stale, and vice versa.

``--changed-only`` lints just the files git reports modified/untracked
(pre-commit speed path; implies skipping the program stage and the stale
check, both of which need the whole tree).  Per-file results are
memoized in ``.tpulint_cache.json`` keyed by content hash + engine
digest, so an unchanged file costs a dict lookup, not a parse.

The engine lives in paddle_tpu/analysis/, loaded here by file path so the
lint never imports JAX (paddle_tpu/__init__.py pulls in the full
framework; a commit-time linter must not pay that).
"""

from __future__ import annotations

import argparse
import hashlib
import importlib.util
import json
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
DEFAULT_BASELINE = ROOT / "tools" / "tpulint_baseline.json"
DEFAULT_CACHE = ROOT / ".tpulint_cache.json"
CACHE_VERSION = 1

#: engine sources whose content invalidates every memoized result
_ENGINE_FILES = ("engine.py", "rules.py", "program.py", "concurrency.py")


def load_analysis():
    """Import paddle_tpu.analysis WITHOUT importing paddle_tpu."""
    pkg_dir = ROOT / "paddle_tpu" / "analysis"
    spec = importlib.util.spec_from_file_location(
        "_tpulint_analysis", pkg_dir / "__init__.py",
        submodule_search_locations=[str(pkg_dir)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_tpulint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def engine_digest() -> str:
    h = hashlib.blake2b(digest_size=16)
    for name in _ENGINE_FILES:
        p = ROOT / "paddle_tpu" / "analysis" / name
        if p.exists():
            h.update(p.read_bytes())
    return h.hexdigest()


def changed_files(root: Path):
    """Repo-relative paths git reports as modified (vs HEAD) or untracked.
    None when git is unavailable (caller falls back to a full sweep)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
        if diff.returncode != 0:
            return None
    except (OSError, subprocess.TimeoutExpired):
        return None
    names = set(diff.stdout.splitlines()) | set(untracked.stdout.splitlines())
    return sorted(n for n in names if n.endswith(".py"))


class ResultCache:
    """Per-file finding memo keyed by source content hash; the engine
    digest gates the whole cache so a rule edit re-lints everything."""

    def __init__(self, path: Path, digest: str):
        self.path = path
        self.digest = digest
        self.files = {}
        self.dirty = False
        try:
            data = json.loads(path.read_text())
            if data.get("version") == CACHE_VERSION \
                    and data.get("engine") == digest:
                self.files = data.get("files", {})
        except (OSError, ValueError):
            pass

    def get(self, rel: str, content_hash: str):
        entry = self.files.get(rel)
        if entry and entry.get("hash") == content_hash:
            return entry["findings"]
        return None

    def put(self, rel: str, content_hash: str, findings):
        self.files[rel] = {"hash": content_hash, "findings": findings}
        self.dirty = True

    def save(self):
        if not self.dirty:
            return
        try:
            self.path.write_text(json.dumps(
                {"version": CACHE_VERSION, "engine": self.digest,
                 "files": self.files}, sort_keys=True))
        except OSError:
            pass                       # a cache must never fail the lint


def lint_files_cached(analysis, paths, root: Path, cache):
    """Per-file sweep with memoization; findings round-trip the cache as
    plain dicts (same schema as --json)."""
    import dataclasses
    out = []
    for f, rel in analysis.iter_py_files(paths, root):
        try:
            source = f.read_text(encoding="utf-8")
        except UnicodeDecodeError as e:
            out.append(analysis.Finding(path=rel, line=1, col=1,
                                        rule="syntax-error",
                                        message=f"not valid UTF-8: {e.reason}"))
            continue
        if cache is not None:
            h = hashlib.blake2b(source.encode("utf-8"),
                                digest_size=16).hexdigest()
            hit = cache.get(rel, h)
            if hit is not None:
                out.extend(analysis.Finding(**d) for d in hit)
                continue
        findings = analysis.lint_source(rel, source)
        if cache is not None:
            cache.put(rel, h, [dataclasses.asdict(x) for x in findings])
        out.extend(findings)
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=["paddle_tpu", "tools"],
                    help="files/dirs to lint (default: paddle_tpu tools)")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help="ratchet baseline JSON (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding; exit 1 if any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current tree")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings + counts as JSON on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--program", action="store_true",
                    help="also run the whole-program concurrency passes "
                         "(thread-entry reachability + guarded-by races)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only git-modified/untracked files (pre-commit "
                         "path; skips the program stage and the stale check)")
    ap.add_argument("--cache", type=Path, default=DEFAULT_CACHE,
                    help="per-file memo file (default: %(default)s)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the per-file result memo")
    ap.add_argument("--root", type=Path, default=ROOT,
                    help="repo root for relative paths (default: %(default)s)")
    args = ap.parse_args(argv)

    analysis = load_analysis()

    if args.list_rules:
        for name, rule in sorted(analysis.RULES.items()):
            print(f"{name}\n    {rule.hazard}")
        for name, hazard in sorted(analysis.PROGRAM_RULES.items()):
            print(f"{name} [--program]\n    {hazard}")
        return 0

    if args.changed_only and args.write_baseline:
        print("tpulint: --write-baseline needs the full sweep, not "
              "--changed-only (the baseline would silently shrink to the "
              "changed subset)", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    req_paths = list(args.paths or ["paddle_tpu", "tools"])
    paths = [Path(p) if Path(p).is_absolute() else args.root / p
             for p in req_paths]
    for p in paths:
        if not p.exists():
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2

    if args.changed_only:
        rels = changed_files(args.root)
        if rels is None:
            print("tpulint: --changed-only: git unavailable, falling back "
                  "to the full sweep", file=sys.stderr)
        else:
            roots = [p.resolve() for p in paths]
            selected = []
            for rel in rels:
                f = (args.root / rel).resolve()
                if f.exists() and any(r == f or r in f.parents
                                      for r in roots):
                    selected.append(f)
            if not selected:
                print("tpulint: --changed-only: no changed .py files under "
                      f"{req_paths}; nothing to lint", file=sys.stderr)
                return 0
            paths = selected

    cache = None
    if not args.no_cache:
        cache = ResultCache(args.cache, engine_digest())
    findings = lint_files_cached(analysis, paths, args.root, cache)
    if cache is not None:
        cache.save()
    file_elapsed = time.monotonic() - t0

    program_report = None
    if args.program and not args.changed_only:
        tp = time.monotonic()
        prog_findings, program_report = analysis.analyze_program(
            paths, root=args.root)
        findings = sorted(findings + prog_findings)
        program_elapsed = time.monotonic() - tp
    else:
        program_elapsed = 0.0
    elapsed = time.monotonic() - t0
    timing = (f"{elapsed:.1f}s"
              + (f" (files {file_elapsed:.1f}s + program "
                 f"{program_elapsed:.1f}s)" if args.program else ""))

    active_rules = set(analysis.RULES) | {"bad-pragma", "syntax-error"}
    if args.program:
        active_rules |= set(analysis.PROGRAM_RULES)

    if args.write_baseline:
        # guard: rewriting an existing baseline from a DIFFERENT path set
        # would silently truncate it to the subset's counts
        norm = sorted(str(p) for p in req_paths)
        if args.baseline.exists():
            try:
                prior = json.loads(args.baseline.read_text()).get("paths")
            except (OSError, json.JSONDecodeError):
                prior = None
            if prior is not None and prior != norm:
                print(f"tpulint: refusing to overwrite {args.baseline}: it was "
                      f"generated from paths {prior}, this run lints {norm}.\n"
                      f"  Re-run over the original paths, or write a subset "
                      f"baseline elsewhere with --baseline OTHER.json",
                      file=sys.stderr)
                return 2
        analysis.write_baseline(args.baseline, findings, paths=norm)
        print(f"tpulint: wrote {len(findings)} baselined finding(s) to "
              f"{args.baseline} ({timing})")
        return 0

    if args.as_json:
        doc = json.loads(analysis.render_json(findings))
        if program_report is not None:
            doc["program"] = program_report.to_dict()
        print(json.dumps(doc, indent=2, sort_keys=True))

    if args.no_baseline:
        if not args.as_json and findings:
            print(analysis.render_text(findings))
        print(f"tpulint: {len(findings)} finding(s) in {timing} "
              f"(no baseline)", file=sys.stderr)
        return 1 if findings else 0

    try:
        baseline = analysis.load_baseline(args.baseline)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"tpulint: cannot read baseline {args.baseline}: {e}\n"
              f"  (generate one with --write-baseline)", file=sys.stderr)
        return 2

    new, stale = analysis.diff_baseline(findings, baseline,
                                        active_rules=active_rules)
    if args.changed_only:
        stale = []       # a subset sweep can't judge tree-wide burn-down
    if new:
        if not args.as_json:
            print(analysis.render_text(new))
        buckets = sorted({(f.path, f.rule) for f in new})
        print(f"tpulint: NEW violation(s) above baseline in "
              f"{len(buckets)} file+rule bucket(s) "
              f"({timing}) — all sites for each bucket are listed; "
              f"fix the new one or (rarely) pragma it with a reason",
              file=sys.stderr)
        return 1
    if stale:
        for path, rule, cur, base in stale:
            print(f"{path}: {rule}: baseline records {base}, tree has {cur}",
                  file=sys.stderr)
        print("tpulint: STALE baseline — violations were burned down "
              "(good!); shrink the ratchet:\n"
              "  python tools/tpulint.py --write-baseline --program "
              "paddle_tpu tools",
              file=sys.stderr)
        return 3
    print(f"tpulint: OK — {len(findings)} baselined finding(s), 0 new, "
          f"{timing}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
