#!/bin/bash
# One-shot TPU evidence session (run the moment the chip is healthy).
# Everything runs under tpu_guard.sh (claim hygiene: no signal ever reaches
# a claim-holder) and writes committed artifacts:
#   BENCH_pre.json       - bench.py --config all (the driver artifact's dry run)
#   TPU_SMOKE_${R}.log    - Mosaic smoke suite (pytest -m tpu)
#   FUSED_PROBE_${R}.json - XLA-fusion roofline numbers for the kernel decision
#   FLASH_SWEEP_${R}.json - flash block-size sweep on gpt2s (pick the winner)
#   SPEC_BENCH_${R}.json  - speculative-decode speedup (lossless check + tok/s)
#   DECODE_INT8_${R}.json - gpt_decode with the int8 KV cache (A/B vs bf16)
#   SERVE_BENCH_${R}.json - continuous-batching engine vs static batches
#
# Usage: from /root/repo:  bash tools/tpu_session.sh
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="/root/repo:/root/.axon_site"
R="${PADDLE_TPU_ROUND:-r05}"
G=tools/tpu_guard.sh

echo "=== 1/7 bench (all configs)"
TPU_GUARD_LOG=/tmp/bench_all.log $G python bench.py --config all
grep "^{" /tmp/bench_all.log | tee BENCH_pre.json

echo "=== 2/7 Mosaic smoke suite"
TPU_GUARD_LOG=TPU_SMOKE_${R}.log PADDLE_TPU_TEST_TPU=1 \
    $G python -m pytest -m tpu tests/test_tpu_smoke.py -q -v
tail -5 TPU_SMOKE_${R}.log

echo "=== 3/7 fusion roofline probe"
TPU_GUARD_LOG=/tmp/fused_probe.log $G python tools/fused_probe.py
grep "^{" /tmp/fused_probe.log | tee FUSED_PROBE_${R}.json

echo "=== 4/7 flash block sweep (gpt2s)"
TPU_GUARD_LOG=/tmp/flash_sweep.log $G python tools/flash_sweep.py
grep "^{" /tmp/flash_sweep.log | tee FLASH_SWEEP_${R}.json

echo "=== 5/7 speculative-decode speedup"
TPU_GUARD_LOG=/tmp/spec_bench.log $G python tools/spec_bench.py
if grep -q "^{" /tmp/spec_bench.log; then
    grep "^{" /tmp/spec_bench.log | tee SPEC_BENCH_${R}.json
else
    echo "spec_bench FAILED (no JSON line); tail of log:" >&2
    tail -5 /tmp/spec_bench.log >&2
fi

echo "=== 6/7 int8 KV-cache decode A/B"
TPU_GUARD_LOG=/tmp/decode_int8.log PADDLE_TPU_DECODE_KV=int8 \
    $G python bench.py --config gpt_decode
grep "^{" /tmp/decode_int8.log | tee DECODE_INT8_${R}.json

echo "=== 7/7 continuous-batching engine throughput"
TPU_GUARD_LOG=/tmp/serve_bench.log $G python tools/serve_bench.py --speculative
if grep -q "^{" /tmp/serve_bench.log; then
    grep "^{" /tmp/serve_bench.log | tee SERVE_BENCH_${R}.json
else
    echo "serve_bench FAILED (no JSON line); tail of log:" >&2
    tail -5 /tmp/serve_bench.log >&2
fi
