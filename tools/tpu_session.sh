#!/bin/bash
# One-shot TPU evidence session (run the moment the chip is healthy).
# Everything runs under tpu_guard.sh (claim hygiene: no signal ever reaches
# a claim-holder) and writes committed artifacts:
#   BENCH_pre.json       - bench.py --config all (the driver artifact's dry run)
#   TPU_SMOKE_${R}.log    - Mosaic smoke suite (pytest -m tpu)
#   FUSED_PROBE_${R}.json - XLA-fusion roofline numbers for the kernel decision
#   FLASH_SWEEP_${R}.json - flash block-size sweep on gpt2s (pick the winner)
#   SPEC_BENCH_${R}.json  - speculative-decode speedup (lossless check + tok/s)
#   DECODE_INT8_${R}.json - gpt_decode with the int8 KV cache (A/B vs bf16)
#   SERVE_BENCH_${R}.json - continuous-batching engine vs static batches
#   BENCH_DIFF_${R}.json  - bench_diff of this round's bench vs the
#                           committed BENCH_r*.json trajectory (backend-
#                           labeled rounds compare honestly: bench_diff
#                           classifies cross-backend pairs non-comparable)
#
# Usage: from /root/repo:  bash tools/tpu_session.sh
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="/root/repo:/root/.axon_site"
R="${PADDLE_TPU_ROUND:-r06}"
G=tools/tpu_guard.sh

echo "=== 1/8 bench (all configs)"
TPU_GUARD_LOG=/tmp/bench_all.log $G python bench.py --config all
grep "^{" /tmp/bench_all.log | tee BENCH_pre.json

echo "=== 2/8 Mosaic smoke suite"
TPU_GUARD_LOG=TPU_SMOKE_${R}.log PADDLE_TPU_TEST_TPU=1 \
    $G python -m pytest -m tpu tests/test_tpu_smoke.py -q -v
tail -5 TPU_SMOKE_${R}.log

echo "=== 3/8 fusion roofline probe"
TPU_GUARD_LOG=/tmp/fused_probe.log $G python tools/fused_probe.py
grep "^{" /tmp/fused_probe.log | tee FUSED_PROBE_${R}.json

echo "=== 4/8 flash block sweep (gpt2s)"
TPU_GUARD_LOG=/tmp/flash_sweep.log $G python tools/flash_sweep.py
grep "^{" /tmp/flash_sweep.log | tee FLASH_SWEEP_${R}.json

echo "=== 5/8 speculative-decode speedup"
TPU_GUARD_LOG=/tmp/spec_bench.log $G python tools/spec_bench.py
if grep -q "^{" /tmp/spec_bench.log; then
    grep "^{" /tmp/spec_bench.log | tee SPEC_BENCH_${R}.json
else
    echo "spec_bench FAILED (no JSON line); tail of log:" >&2
    tail -5 /tmp/spec_bench.log >&2
fi

echo "=== 6/8 int8 KV-cache decode A/B"
TPU_GUARD_LOG=/tmp/decode_int8.log PADDLE_TPU_DECODE_KV=int8 \
    $G python bench.py --config gpt_decode
grep "^{" /tmp/decode_int8.log | tee DECODE_INT8_${R}.json

echo "=== 7/8 continuous-batching engine throughput"
TPU_GUARD_LOG=/tmp/serve_bench.log $G python tools/serve_bench.py --speculative
if grep -q "^{" /tmp/serve_bench.log; then
    grep "^{" /tmp/serve_bench.log | tee SERVE_BENCH_${R}.json
else
    echo "serve_bench FAILED (no JSON line); tail of log:" >&2
    tail -5 /tmp/serve_bench.log >&2
fi

echo "=== 8/8 bench_diff vs the committed trajectory"
# compare this round's fresh bench artifact against the newest committed
# BENCH_r*.json (CPU rounds included — the backend label keeps the
# comparison honest; cross-backend pairs are classified non-comparable,
# never regressions).  bench_diff needs no device, so no guard.
PREV=$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1)
if [ -n "$PREV" ] && [ -s BENCH_pre.json ]; then
    python tools/bench_diff.py "$PREV" BENCH_pre.json --json \
        | tee BENCH_DIFF_${R}.json || true
else
    echo "bench_diff skipped: no prior BENCH_r*.json or empty BENCH_pre.json"
fi
