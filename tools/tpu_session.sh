#!/bin/bash
# One-shot TPU evidence session (run the moment the chip is healthy).
# Everything runs under tpu_guard.sh (claim hygiene: no signal ever reaches
# a claim-holder) and writes committed artifacts:
#   BENCH_pre.json       - bench.py --config all (the driver artifact's dry run)
#   TPU_SMOKE_r03.log    - Mosaic smoke suite (pytest -m tpu)
#   FUSED_PROBE_r03.json - XLA-fusion roofline numbers for the kernel decision
#
# Usage: from /root/repo:  bash tools/tpu_session.sh
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="/root/repo:/root/.axon_site"
G=tools/tpu_guard.sh

echo "=== 1/3 bench (all configs)"
TPU_GUARD_LOG=/tmp/bench_all.log $G python bench.py --config all
grep "^{" /tmp/bench_all.log | tee BENCH_pre.json

echo "=== 2/3 Mosaic smoke suite"
TPU_GUARD_LOG=TPU_SMOKE_r03.log PADDLE_TPU_TEST_TPU=1 \
    $G python -m pytest -m tpu tests/test_tpu_smoke.py -q -v
tail -5 TPU_SMOKE_r03.log

echo "=== 3/3 fusion roofline probe"
TPU_GUARD_LOG=/tmp/fused_probe.log $G python tools/fused_probe.py
grep "^{" /tmp/fused_probe.log | tee FUSED_PROBE_r03.json
