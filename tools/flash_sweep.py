"""Flash-attention block-size sweep on the gpt2s bench config (VERDICT r2 #2:
the measured flash-bwd residual is ~11ms/step; block size is the main lever).

Each block size runs in a FRESH child process because
``PADDLE_TPU_FLASH_BLOCK`` is read at trace time and jit caches the kernel.

Run on TPU:  PYTHONPATH=/root/repo:/root/.axon_site \
             tools/tpu_guard.sh python tools/flash_sweep.py
Prints one JSON line per block size and a final "best" line; paste the table
into BENCH_NOTES.md and set the winning block in bench.py's environment.
"""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

BLOCKS = [None, 128, 256, 512]   # None = auto (largest divisor)


def child(block):
    env = dict(os.environ)
    if block:
        env["PADDLE_TPU_FLASH_BLOCK"] = str(block)
    env["_FLASH_SWEEP_CHILD"] = "1"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # run under the claim guard with NO timeout: a claim-holder must exit on
    # its own (tpu_guard.sh: bound the work, not the process)
    proc = subprocess.run(
        [os.path.join(root, "tools", "tpu_guard.sh"), sys.executable,
         os.path.abspath(__file__)], env=env,
        capture_output=True, text=True, cwd=root)
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    return json.loads(lines[-1]) if lines else {"error": proc.stderr[-300:]}


def measure():
    import bench
    out = bench.bench_gpt2s(on_tpu=True)
    out["flash_block"] = os.environ.get("PADDLE_TPU_FLASH_BLOCK", "auto")
    print(json.dumps(out), flush=True)


def main():
    if os.environ.get("_FLASH_SWEEP_CHILD") == "1":
        measure()
        return
    results = []
    for b in BLOCKS:
        r = child(b)
        r.setdefault("flash_block", b if b else "auto")
        print(json.dumps(r), flush=True)
        if "value" in r and r.get("value"):
            results.append(r)
    if results:
        best = max(results, key=lambda r: r["value"])
        print(json.dumps({"best_block": best["flash_block"],
                          "tokens_per_sec": best["value"],
                          "mfu": best.get("mfu")}), flush=True)


if __name__ == "__main__":
    main()
