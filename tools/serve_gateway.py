#!/usr/bin/env python
"""Run a multi-replica serving gateway over GPT engines and drive a demo
workload through it.

Builds ``--replicas`` N engine replicas from a named config (the
``tools/warmup.py`` presets), fronts them with
``paddle_tpu.gateway.ServingGateway`` (admission control, deadlines,
routing, drain), optionally exposes the live ops endpoint (``--ops-port``
→ ``/gateway`` ``/metrics`` ``/healthz`` …), runs a synthetic mixed
workload, and prints a one-line JSON report: admitted/shed counts, TTFT
percentiles, per-replica outcomes.

Examples::

    python tools/serve_gateway.py --replicas 2 --demo 12
    python tools/serve_gateway.py --replicas 2 --demo 24 \\
        --max-queue-depth 4 --ttft-deadline 5.0 --ops-port 9100
    python tools/serve_gateway.py --replicas 2 --demo 8 --drain-one
    python tools/serve_gateway.py --replicas 1 --demo 24 --autoscale \\
        --max-replicas 3 --up-cooldown 0 --ops-port 9100
    python tools/serve_gateway.py --replicas 2 --demo 12 --resilience \\
        --chaos crash
    python tools/serve_gateway.py --replicas 2 --demo 12 --resilience \\
        --chaos '[{"kind": "slow", "at_s": 0, "factor": 10}]'

``--drain-one`` gracefully drains replica 0 mid-workload — the rolling-
restart rehearsal: the report asserts every admitted request still
finished (zero drops).

``--chaos`` wraps every replica in a ``paddle_tpu.faults.FaultyEngine``
and injects the named preset (or inline/`@file` JSON plan) on the real
clock — the chaos rehearsal; pair it with ``--resilience`` to watch the
breaker/retry/hedge/brownout layer absorb the faults (report
``resilience`` section + live ``/resilience`` with ``--ops-port``).

``--autoscale`` closes the loop: a TTFT-p99 + shed-rate ``SLOMonitor``
feeds an ``ElasticAutoscaler`` (min/max/cooldown knobs below) that
spawns AOT-warmed replicas from the same engine config on firing alerts
and drains the least-loaded one under sustained idle; the report gains
the decision timeline and the live ops endpoint gains ``/autoscaler``.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

ENGINES = ("ragged", "paged", "contiguous")
PRESETS = ("tiny", "gpt2-small", "gpt2-medium", "gpt2-large")

#: --chaos presets (fault at_s are seconds after startup; every preset
#: targets r0 so a >= 2 replica fleet demonstrates the recovery)
CHAOS_PRESETS = {
    "crash": [{"kind": "crash", "at_s": 0.5, "replica": "r0"}],
    "stall": [{"kind": "stall", "at_s": 0.5, "duration_s": 8.0,
               "replica": "r0"}],
    "slow": [{"kind": "slow", "at_s": 0.0, "duration_s": 60.0,
              "factor": 10.0, "replica": "r0"}],
    "flaky": [{"kind": "dispatch_error", "at_s": 0.0, "duration_s": 10.0,
               "count": 4, "replica": "r0"}],
    "mixed": [{"kind": "slow", "at_s": 0.0, "duration_s": 60.0,
               "factor": 10.0, "replica": "r0"},
              {"kind": "dispatch_error", "at_s": 0.0, "duration_s": 10.0,
               "count": 3, "replica": "r1"}],
}


def _chaos_plan(spec):
    """--chaos value → FaultPlan (or None): a preset name, inline JSON
    (plan dict or bare fault list), or @path to a JSON file."""
    if spec is None:
        return None
    from paddle_tpu.faults import FaultPlan
    if spec in CHAOS_PRESETS:
        return FaultPlan.from_dict({"faults": CHAOS_PRESETS[spec]})
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return FaultPlan.from_json(f.read())
    return FaultPlan.from_json(spec)


def _build_model(args):
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, gpt_preset
    if args.preset == "tiny":
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=max(128, args.max_len),
                        compute_dtype="float32")
    else:
        cfg = gpt_preset(args.preset,
                         max_position_embeddings=max(1024, args.max_len))
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return cfg, model, params


def _build_engine(args, model, params, tracer):
    buckets = [int(b) for b in args.buckets.split(",")]
    common = dict(max_slots=args.max_slots, max_len=args.max_len,
                  prompt_buckets=buckets, tracer=tracer)
    if args.engine == "ragged":
        from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
        return RaggedPagedContinuousBatchingEngine(
            model, params, block_size=args.block_size,
            token_budget=args.token_budget,
            enable_prefix_cache=args.prefix_cache, **common)
    if args.engine == "paged":
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        return PagedContinuousBatchingEngine(
            model, params, block_size=args.block_size,
            enable_prefix_cache=args.prefix_cache, **common)
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(model, params, **common)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serving gateway demo: N engine replicas behind "
                    "admission control / deadlines / routing / drain "
                    "(prints a JSON report)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--engine", choices=ENGINES, default="ragged")
    ap.add_argument("--preset", choices=PRESETS, default="tiny",
                    help="model config: 'tiny' (CPU smoke) or a GPT preset")
    ap.add_argument("--max-slots", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--token-budget", type=int, default=24)
    ap.add_argument("--buckets", default="8,16",
                    help="comma-separated prompt buckets")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable per-replica prefix caching (and the "
                         "gateway's prefix-affinity routing)")
    ap.add_argument("--demo", type=int, default=8,
                    help="number of synthetic requests to run")
    ap.add_argument("--max-new", type=int, default=8,
                    help="max_new_tokens per demo request")
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--max-queued-tokens", type=int, default=None)
    ap.add_argument("--ttft-deadline", type=float, default=None,
                    help="per-request TTFT deadline (seconds)")
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request total deadline (seconds)")
    ap.add_argument("--warmup-cache-dir", default=None,
                    help="AOT-warm every replica against this persistent "
                         "compile cache before taking traffic (PR 6)")
    ap.add_argument("--drain-one", action="store_true",
                    help="drain replica 0 mid-workload (rolling-restart "
                         "rehearsal; report asserts zero drops)")
    ap.add_argument("--autoscale", action="store_true",
                    help="attach the SLO-driven elastic autoscaler "
                         "(paddle_tpu.autoscaler): firing TTFT/shed "
                         "alerts spawn warmed replicas, sustained idle "
                         "drains them")
    ap.add_argument("--min-replicas", type=int, default=None,
                    help="autoscaler floor (default: --replicas)")
    ap.add_argument("--max-replicas", type=int, default=4,
                    help="autoscaler ceiling")
    ap.add_argument("--up-cooldown", type=float, default=2.0,
                    help="seconds between scale-ups")
    ap.add_argument("--down-cooldown", type=float, default=30.0,
                    help="seconds between scale-downs (also blocks a "
                         "drain right after a spawn)")
    ap.add_argument("--idle-utilization", type=float, default=0.15,
                    help="occupancy below this starts the idle dwell")
    ap.add_argument("--idle-dwell", type=float, default=10.0,
                    help="sustained-idle seconds before a scale-down")
    ap.add_argument("--ttft-slo", type=float, default=2.0,
                    help="TTFT p99 objective target (seconds) for the "
                         "autoscaler's SLO monitor")
    ap.add_argument("--shed-slo", type=float, default=0.05,
                    help="shed-rate objective target for the "
                         "autoscaler's SLO monitor")
    ap.add_argument("--ops-port", type=int, default=None,
                    help="start the live ops endpoint on this port "
                         "(/gateway /metrics /healthz /ledger /trace "
                         "/resilience /autoscaler /fleet)")
    ap.add_argument("--fleet", action="store_true",
                    help="federate the demo through a "
                         "telemetry_fleet.FleetCollector scraping the "
                         "demo's ops surface (in-process; with "
                         "--ops-port the collector also serves a live "
                         "GET /fleet) — the report gains the fleet "
                         "rollup (global goodput, merged TTFT p99, "
                         "tokens/s) and per-target scrape statuses")
    ap.add_argument("--chaos", default=None, metavar="PLAN",
                    help="inject a fault plan (paddle_tpu.faults): a "
                         f"preset name ({'/'.join(sorted(CHAOS_PRESETS))})"
                         ", inline JSON (a plan dict or bare fault "
                         "list), or @path to a JSON file; fault at_s "
                         "times are seconds after startup")
    ap.add_argument("--resilience", action="store_true",
                    help="attach the gateway resilience layer (circuit "
                         "breakers, retry/backoff, TTFT hedging, "
                         "brownout ladder) at ResiliencePolicy defaults "
                         "— the report and /resilience gain breaker/"
                         "brownout state")
    ap.add_argument("--stall-threshold", type=float, default=None,
                    help="replica stall-quarantine threshold in seconds "
                         "(default 30, or 5 with --chaos so injected "
                         "crashes resolve on demo timescales)")
    ap.add_argument("--prefill-replicas", type=int, default=0,
                    help="disaggregated serving (docs/KV_TIERING.md): "
                         "the first N replicas register role='prefill' "
                         "and the rest role='decode' (TieredKVStore "
                         "auto-attached) — prompts prefill on one pool, "
                         "KV pages migrate, decode runs on the other; "
                         "implies --prefix-cache, needs a paged engine")
    args = ap.parse_args(argv)
    if args.prefill_replicas:
        if args.engine == "contiguous":
            ap.error("--prefill-replicas needs a paged engine "
                     "(pages are block-table KV)")
        if args.prefill_replicas >= args.replicas:
            ap.error("--prefill-replicas must leave at least one "
                     "decode replica")
        args.prefix_cache = True

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.gateway import ResiliencePolicy, ServingGateway
    from paddle_tpu.telemetry import Tracer

    paddle.seed(0)
    cfg, model, params = _build_model(args)
    tracer = Tracer(capacity=16384)
    plan = _chaos_plan(args.chaos)
    stall_s = args.stall_threshold
    if stall_s is None:
        stall_s = 5.0 if plan is not None else 30.0
    t0 = time.monotonic()
    clock = lambda: time.monotonic() - t0       # noqa: E731 — fault at_s
    # times are relative to startup; gateway + wrappers share the clock
    gw = ServingGateway(max_queue_depth=args.max_queue_depth,
                        max_queued_tokens=args.max_queued_tokens,
                        stall_threshold_s=stall_s, clock=clock,
                        tracer=tracer,
                        resilience=(ResiliencePolicy() if args.resilience
                                    else None))
    names = []
    wrappers = []
    for i in range(args.replicas):
        eng = _build_engine(args, model, params, Tracer())
        role = "unified"
        if args.prefill_replicas:
            role = ("prefill" if i < args.prefill_replicas else "decode")
        if args.warmup_cache_dir:
            eng.warmup(cache_dir=args.warmup_cache_dir)
        if plan is not None:
            from paddle_tpu.faults import FaultyEngine
            eng = FaultyEngine(eng, plan, clock, replica=f"r{i}")
            wrappers.append(eng)
        names.append(gw.add_replica(eng, f"r{i}", role=role))

    asc = None
    if args.autoscale:
        from paddle_tpu.autoscaler import ElasticAutoscaler
        from paddle_tpu.telemetry_slo import Objective, SLOMonitor
        slo = SLOMonitor([
            Objective.latency("ttft_p99", "ttft_s", args.ttft_slo,
                              compliance=0.99, windows=(60.0, 15.0),
                              burn_threshold=1.0, for_s=1.0,
                              clear_s=10.0),
            Objective.ratio("shed_rate", "shed", "submitted",
                            args.shed_slo, windows=(60.0, 15.0),
                            burn_threshold=1.0, for_s=1.0,
                            clear_s=10.0),
        ], tracer=tracer)
        gw.set_slo(slo)
        gw.register_replica_factory(
            lambda: _build_engine(args, model, params, Tracer()))
        asc = ElasticAutoscaler(
            gw, slo=slo,
            min_replicas=(args.replicas if args.min_replicas is None
                          else args.min_replicas),
            max_replicas=args.max_replicas,
            scale_up_cooldown_s=args.up_cooldown,
            scale_down_cooldown_s=args.down_cooldown,
            idle_utilization=args.idle_utilization,
            idle_dwell_s=args.idle_dwell,
            cache_dir=args.warmup_cache_dir, tracer=tracer)

    srv = None
    if args.ops_port is not None:
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer(port=args.ops_port)
        srv.attach(gw, "gateway")
        for name in names:
            srv.attach(gw.replica(name).engine, name)
        if asc is not None:
            srv.attach(asc, "autoscaler")
            srv.attach(asc.slo, "slo")   # /slo + burn-rate gauges too —
            # the monitor driving the autoscaler's decisions
        srv.start()

    fleet = None
    if args.fleet:
        from paddle_tpu.telemetry_fleet import FleetCollector
        if asc is None:
            # no autoscaler means no SLO monitor yet — the fleet's merged
            # TTFT percentiles ride the /slo sketch export, so give the
            # gateway one to feed
            from paddle_tpu.telemetry_slo import SLOMonitor
            gw.set_slo(SLOMonitor(resolution_s=1.0))
            if srv is not None:
                srv.attach(gw._slo, "slo")
        if srv is not None:
            scrape_target = srv
        else:
            # no live endpoint requested: federate through an UNSTARTED
            # ops server — render()-only, no port bound
            from paddle_tpu.ops_server import OpsServer
            scrape_target = OpsServer()
            scrape_target.attach(gw, "gateway")
            for name in names:
                scrape_target.attach(gw.replica(name).engine, name)
            if asc is not None:
                scrape_target.attach(asc, "autoscaler")
                scrape_target.attach(asc.slo, "slo")
            else:
                scrape_target.attach(gw._slo, "slo")
        fleet = FleetCollector(interval_s=1.0)
        fleet.add_target("demo", server=scrape_target)
        if srv is not None:
            srv.attach(fleet, "fleet")   # live GET /fleet
        fleet.scrape_once()   # baseline: the post-run scrape's counter
        # deltas (tokens/s) measure the demo workload itself

    rng = np.random.RandomState(0)
    buckets = [int(b) for b in args.buckets.split(",")]
    reqs = []
    for _ in range(args.demo):
        plen = int(rng.randint(1, buckets[-1] + 1))
        prompt = [int(t) for t in rng.randint(1, cfg.vocab_size, plen)]
        n = int(rng.randint(1, args.max_new + 1))
        reqs.append(gw.submit(prompt, n,
                              ttft_deadline_s=args.ttft_deadline,
                              deadline_s=args.deadline))
    if args.drain_one and names:
        gw.drain(names[0])
    if asc is None and plan is None:
        gw.run_to_completion(max_ticks=100000)
    else:
        # the autoscaler gets one control round per gateway round — the
        # same interleave the simulation harness drives; under --chaos
        # the wall clock must actually advance for fault windows and
        # stall detection, so the drive loop paces itself
        ticks = 0
        while gw.pending():
            gw.step()
            if asc is not None:
                asc.evaluate()
            ticks += 1
            if ticks > 200000:
                raise RuntimeError("not done after 200000 ticks")
            if plan is not None:
                time.sleep(0.01)
        gw.pop_finished()

    outcomes = {}
    for r in reqs:
        outcomes[r.status] = outcomes.get(r.status, 0) + 1
    snap = gw.gateway_snapshot()
    admitted = [r for r in reqs if r.status not in ("shed", "failed")]
    dropped = [r.gid for r in admitted
               if r.status not in ("finished", "expired", "cancelled")]
    report = {
        "replicas": snap["replicas"],
        "offered": len(reqs),
        "outcomes": outcomes,
        "queues": snap["queues"],
        "queue_s": snap["queue_s"],
        "ttft_s": snap["ttft_s"],
        "dropped": dropped,            # must stay [] — the drain contract
        "ops_url": None if srv is None else srv.url,
    }
    if asc is not None:
        asnap = asc.autoscaler_snapshot()
        report["autoscaler"] = {"fleet": asnap["fleet"],
                                "decisions": asnap["decisions"],
                                "counters": asnap["counters"]}
    if fleet is not None:
        fsnap = fleet.scrape_once()
        report["fleet"] = {
            "rollup": fsnap["rollup"],
            "targets": [{"target": t["target"], "status": t["status"],
                         "tokens_per_s": t["tokens_per_s"],
                         "ttft_p99": t["ttft_p99"],
                         "occupancy": t["occupancy"]}
                        for t in fsnap["targets"]]}
    if plan is not None:
        report["chaos"] = {"plan": plan.to_dict(),
                           "injected": [ev for w in wrappers
                                        for ev in w.injected()]}
    if args.resilience:
        report["resilience"] = gw.resilience_snapshot()
    if gw.has_kv_surface():
        ksnap = gw.kvstore_snapshot()
        report["kvstore"] = {"counters": ksnap["counters"],
                             "decode_pool_pressure":
                                 ksnap["decode_pool_pressure"],
                             "prefix_index": ksnap["prefix_index"]}
    print(json.dumps(report))
    if srv is not None:
        srv.stop()
    return 0 if not dropped else 1


if __name__ == "__main__":
    sys.exit(main())
