#!/usr/bin/env python
"""ckpt_fsck — verify / list / gc a train_resilience checkpoint root.

The operator's answer to "can this job resume, and from where?":

    python tools/ckpt_fsck.py ROOT verify          # exit 0 iff resumable
    python tools/ckpt_fsck.py ROOT verify --step 120
    python tools/ckpt_fsck.py ROOT list [--json]
    python tools/ckpt_fsck.py ROOT gc --retain 5 --keep-every 100

``verify`` walks every step directory through the same digest
verification ``CheckpointManager.latest()`` uses (COMMIT marker →
manifest digest → per-file size + blake2b) and exits 0 only when a valid
resume point exists; any torn/corrupt/uncommitted step is reported with
its reason (a committed-but-corrupt step makes the root DEGRADED but
still exit-0 as long as an older valid step remains).  ``gc`` applies the
same bounded-retention policy the manager applies online.  ``--json``
emits one machine-readable document on stdout for CI gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _manager(args):
    from paddle_tpu.train_resilience import CheckpointManager
    return CheckpointManager(args.root, retain=args.retain,
                             keep_every=args.keep_every)


def _survey(mgr):
    """Status of every step directory under the root."""
    rows = []
    for step in mgr.steps():
        ok, reason = mgr.verify(step)
        rows.append({"step": step, "ok": ok,
                     "status": "valid" if ok else reason})
    return rows


def cmd_verify(args) -> int:
    mgr = _manager(args)
    if args.step is not None:
        ok, reason = mgr.verify(args.step)
        doc = {"root": args.root, "step": args.step, "ok": ok,
               "status": "valid" if ok else reason}
        if args.json:
            print(json.dumps(doc, indent=2))
        else:
            print(f"step {args.step}: {doc['status']}")
        return 0 if ok else 1
    rows = _survey(mgr)
    valid = [r["step"] for r in rows if r["ok"]]
    bad = [r for r in rows if not r["ok"]]
    resume = max(valid) if valid else None
    doc = {"root": args.root, "steps": rows, "resume_step": resume,
           "valid": len(valid), "broken": len(bad),
           "ok": resume is not None}
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        for r in rows:
            print(f"step {r['step']:>10d}  {r['status']}")
        if resume is None:
            print(f"{args.root}: NO VALID RESUME POINT "
                  f"({len(rows)} step dir(s), all broken or uncommitted)")
        else:
            state = "DEGRADED" if bad else "OK"
            print(f"{args.root}: {state} — resume at step {resume} "
                  f"({len(valid)} valid, {len(bad)} broken)")
    return 0 if resume is not None else 1


def cmd_list(args) -> int:
    mgr = _manager(args)
    rows = _survey(mgr)
    if args.json:
        print(json.dumps({"root": args.root, "steps": rows}, indent=2))
    else:
        for r in rows:
            print(f"step {r['step']:>10d}  {r['status']}")
    return 0


def cmd_gc(args) -> int:
    mgr = _manager(args)
    removed = mgr.gc()
    kept = mgr.steps()
    doc = {"root": args.root, "removed": removed, "kept": kept,
           "retain": args.retain, "keep_every": args.keep_every}
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        print(f"removed {len(removed)} step(s), kept {len(kept)}: "
              f"{kept}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ckpt_fsck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("root", help="checkpoint root (the CheckpointManager dir)")
    ap.add_argument("command", choices=("verify", "list", "gc"),
                    nargs="?", default="verify")
    ap.add_argument("--step", type=int, default=None,
                    help="verify one specific step instead of the root")
    ap.add_argument("--retain", type=int, default=5,
                    help="gc: committed steps to keep (default 5)")
    ap.add_argument("--keep-every", type=int, default=None,
                    help="gc: additionally pin every N-th step")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"ckpt_fsck: no such checkpoint root: {args.root}",
              file=sys.stderr)
        return 1
    return {"verify": cmd_verify, "list": cmd_list, "gc": cmd_gc}[
        args.command](args)


if __name__ == "__main__":
    sys.exit(main())
