"""Per-op micro-benchmark harness (≙ reference operators/benchmark/
op_tester.cc + tools/ci_op_benchmark.sh: config-driven op latency with a
relative regression gate).

Usage:
  python tools/op_bench.py                     # built-in op set, one JSON line per op
  python tools/op_bench.py --ops matmul,softmax
  python tools/op_bench.py --baseline prev.jsonl --gate 1.3   # CI regression gate

Each line: {"op": name, "shape": ..., "ms": median, "backend": ...}.
With --baseline, ops slower than gate x their baseline fail the run (exit 1)
— the reference's PR-vs-develop relative gate, no absolute numbers stored.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _cases():
    import jax
    import jax.numpy as jnp
    import numpy as np

    r = np.random.RandomState(0)
    B, L, H, D = 8, 1024, 12, 64
    a2 = jnp.asarray(r.standard_normal((4096, 4096)), jnp.bfloat16)
    act = jnp.asarray(r.standard_normal((B * L, 3072)), jnp.bfloat16)
    img = jnp.asarray(r.standard_normal((32, 224, 224, 3)), jnp.bfloat16)
    kern = jnp.asarray(r.standard_normal((3, 3, 3, 64)) * 0.1, jnp.bfloat16)
    qkv = jnp.asarray(r.standard_normal((B, L, H, D)), jnp.bfloat16)
    logits = jnp.asarray(r.standard_normal((B * L, 50304)), jnp.bfloat16)
    labels = jnp.asarray(r.randint(0, 50304, (B * L,)))

    def flash(q):
        from paddle_tpu.ops.attention import flash_attention
        return flash_attention(q, q, q, causal=True)

    def fused_ce(lg):
        from paddle_tpu.ops.loss import softmax_cross_entropy_mean
        return softmax_cross_entropy_mean(lg, labels)

    return {
        "matmul_4096": (lambda x: x @ x, a2),
        "softmax_50k": (lambda x: __import__("jax").nn.softmax(
            x.astype(jnp.float32), -1), logits),
        "gelu_bias": (lambda x: __import__("jax").nn.gelu(x + 1.0), act),
        "layer_norm": (lambda x: (x - x.mean(-1, keepdims=True))
                       * __import__("jax").lax.rsqrt(
                           x.astype(jnp.float32).var(-1, keepdims=True) + 1e-5),
                       act),
        "conv2d_nhwc": (lambda x: __import__("jax").lax.conv_general_dilated(
            x, kern, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")), img),
        "flash_attention": (flash, qkv),
        "fused_softmax_ce": (fused_ce, logits),
        "reduce_sum": (lambda x: x.astype(jnp.float32).sum(), act),
    }


def bench_op(name, fn, arg, iters=20):
    import jax
    import numpy as np

    jfn = jax.jit(fn)
    out = jfn(arg)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]  # sync
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = jfn(arg)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        times.append(time.perf_counter() - t0)
    return {
        "op": name,
        "shape": list(np.shape(arg)),
        "ms": round(float(np.median(times)) * 1e3, 4),
        "backend": jax.default_backend(),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="all")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--baseline", help="prior run's JSONL for the regression gate")
    ap.add_argument("--gate", type=float, default=1.3,
                    help="fail ops slower than gate x baseline")
    args = ap.parse_args()

    cases = _cases()
    names = list(cases) if args.ops == "all" else args.ops.split(",")
    baseline = {}
    if args.baseline:
        with open(args.baseline) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    baseline[rec["op"]] = rec["ms"]

    failed = []
    for name in names:
        if name not in cases:
            print(json.dumps({"op": name, "error": "unknown op"}))
            continue
        fn, arg = cases[name]
        rec = bench_op(name, fn, arg, args.iters)
        if name in baseline:
            rec["vs_baseline"] = round(rec["ms"] / baseline[name], 3)
            if rec["ms"] > baseline[name] * args.gate:
                rec["regressed"] = True
                failed.append(name)
        print(json.dumps(rec), flush=True)
    if failed:
        print(f"[op_bench] regression gate failed: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
