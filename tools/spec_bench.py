"""Speculative-decoding benchmark: plain greedy vs draft-accelerated decode.

Run on a healthy chip (guarded):
    TPU_GUARD_LOG=/tmp/spec_bench.log tools/tpu_guard.sh python tools/spec_bench.py
CPU smoke:
    JAX_PLATFORMS=cpu python tools/spec_bench.py --cpu

Prints one JSON line: plain and speculative tokens/s plus the measured
acceptance-driven speedup.  Speculative decoding is lossless (greedy
acceptance), so the speedup is pure serving win; the draft here is a
truncated-depth copy of the target's config with fresh weights — a real
deployment would distill one, which only raises the acceptance rate.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="tiny CPU smoke shapes")
    ap.add_argument("--draft_k", type=int, default=4)
    ap.add_argument("--iters", type=int, default=0, help="0 = auto")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel

    paddle.seed(0)
    if args.cpu:
        tcfg = dict(vocab_size=512, hidden_size=128, num_layers=4,
                    num_attention_heads=4, max_position_embeddings=128,
                    compute_dtype="float32")
        P, N, iters = 8, 16, args.iters or 2
    else:
        tcfg = dict(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_attention_heads=12, max_position_embeddings=1024,
                    compute_dtype="bfloat16")
        P, N, iters = 128, 256, args.iters or 3
    dcfg = dict(tcfg)
    dcfg["num_layers"] = max(tcfg["num_layers"] // 6, 1)  # cheap draft

    target = GPTModel(GPTConfig(**tcfg))
    tparams = {n: p._data for n, p in target.named_parameters()}
    paddle.seed(1)
    draft = GPTModel(GPTConfig(**dcfg))
    dparams = {n: p._data for n, p in draft.named_parameters()}

    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, tcfg["vocab_size"], (1, P)))

    def timed(fn):
        out = fn()                      # compile + warm
        np.asarray(out[0, -1])
        t0 = time.perf_counter()
        outs = [fn() for _ in range(iters)]
        np.asarray(jnp.stack([o[0, -1] for o in outs]))
        return (time.perf_counter() - t0), outs[-1]

    dt_plain, out_plain = timed(
        lambda: target.generate(tparams, ids, N))
    dt_spec, out_spec = timed(
        lambda: target.generate_speculative(tparams, ids, N, draft, dparams,
                                            draft_k=args.draft_k))
    assert np.array_equal(np.asarray(out_plain), np.asarray(out_spec)), \
        "speculative decoding must be lossless"
    _, rounds = target.generate_speculative(tparams, ids, N, draft, dparams,
                                            draft_k=args.draft_k,
                                            return_rounds=True)
    rounds = int(rounds)
    # acceptance per round: N-1 loop tokens over `rounds` rounds of at most
    # draft_k+1; the minimum possible is ceil((N-1)/(draft_k+1))
    min_rounds = -(-(N - 1) // (args.draft_k + 1))

    plain_tps = N * iters / dt_plain
    spec_tps = N * iters / dt_spec
    print(json.dumps({
        "metric": "speculative_decode_speedup",
        "plain_tokens_per_sec": round(plain_tps, 1),
        "spec_tokens_per_sec": round(spec_tps, 1),
        "speedup": round(spec_tps / plain_tps, 3),
        "draft_k": args.draft_k,
        "draft_layers": dcfg["num_layers"],
        "rounds": rounds,
        "round_efficiency": round(min_rounds / max(rounds, 1), 3),
        "lossless_check": "passed",
        "backend": jax.default_backend(),
    }), flush=True)


if __name__ == "__main__":
    main()
