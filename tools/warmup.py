#!/usr/bin/env python
"""Warm a serving engine's compile grid into a persistent cache directory.

Builds a GPT model + serving engine from a named config, runs the AOT
warmup pass (paddle_tpu.jit.aot) against ``--cache-dir``, and prints a
one-line JSON report.  A serving host runs this BEFORE taking traffic (or
once per image build): the engine process then warms from disk in seconds
instead of paying multi-second XLA compiles, and the first request on
every (token_budget, table-width) bucket is compile-free.

Examples::

    python tools/warmup.py --cache-dir /var/cache/paddle-tpu
    python tools/warmup.py --cache-dir cache --engine paged \\
        --preset gpt2-small --max-slots 8 --max-len 512 --buckets 64,128

The report's ``compile.cold`` / ``compile.disk`` split shows whether this
run paid XLA or reused the directory (docs/COMPILATION.md).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

ENGINES = ("ragged", "paged", "contiguous")
PRESETS = ("tiny", "gpt2-small", "gpt2-medium", "gpt2-large")


def _build_engine(args, tracer):
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, gpt_preset

    if args.preset == "tiny":
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=max(128, args.max_len),
                        compute_dtype="float32")
    else:
        cfg = gpt_preset(args.preset,
                         max_position_embeddings=max(1024, args.max_len))
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    buckets = [int(b) for b in args.buckets.split(",")]
    common = dict(max_slots=args.max_slots, max_len=args.max_len,
                  prompt_buckets=buckets, tracer=tracer)
    if args.engine == "ragged":
        from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
        return RaggedPagedContinuousBatchingEngine(
            model, params, block_size=args.block_size,
            token_budget=args.token_budget, **common)
    if args.engine == "paged":
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        return PagedContinuousBatchingEngine(
            model, params, block_size=args.block_size, **common)
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(model, params, **common)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="AOT-warm a serving engine's compile grid into a "
                    "persistent cache dir (prints a JSON report)")
    ap.add_argument("--cache-dir", required=True,
                    help="persistent cache directory (created if missing); "
                         "later processes pointing here skip XLA compiles")
    ap.add_argument("--engine", choices=ENGINES, default="ragged")
    ap.add_argument("--preset", choices=PRESETS, default="tiny",
                    help="model config: 'tiny' (CPU smoke) or a GPT preset")
    ap.add_argument("--max-slots", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--token-budget", type=int, default=24)
    ap.add_argument("--buckets", default="8,16",
                    help="comma-separated prompt buckets")
    ap.add_argument("--max-workers", type=int, default=1,
                    help=">1 compiles concurrently; provenance attribution "
                         "may smear across simultaneous tasks, and peak "
                         "scratch memory is max_workers KV-cache copies")
    args = ap.parse_args(argv)

    from paddle_tpu.telemetry import Tracer
    tracer = Tracer(capacity=8192)
    eng = _build_engine(args, tracer)
    report = eng.warmup(cache_dir=args.cache_dir,
                        max_workers=args.max_workers)
    compile_summary = tracer.summary()["compile"]
    print(json.dumps({
        "engine": args.engine,
        "preset": args.preset,
        "cache_dir": report["cache_dir"],
        "programs": report["programs"],
        "grid": [t["label"] for t in report["tasks"]],
        "wall_s": round(report["wall_s"], 3),
        "compile": compile_summary,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
