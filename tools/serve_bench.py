"""Continuous-batching serving benchmark: engine throughput vs the static
batch baseline.

Run on a healthy chip (guarded):
    TPU_GUARD_LOG=/tmp/serve_bench.log tools/tpu_guard.sh python tools/serve_bench.py
CPU smoke:
    JAX_PLATFORMS=cpu python tools/serve_bench.py --cpu

Prints one JSON line:
  - static_tok_s: model.generate on one full batch (all requests admitted
    and retired together — the reference generation_utils discipline);
  - engine_tok_s: ContinuousBatchingEngine over the same request set with
    STAGGERED budgets, where finished slots re-admit queued work instead of
    idling until the batch's longest request completes;
  - utilization win = engine_tok_s / static_tok_s (the continuous-batching
    claim measured, not argued).

The workload makes the discipline visible: budgets spread 1x-4x so a
static batch spends most ticks with retired rows still occupying slots.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="tiny CPU smoke shapes")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--ticks_per_sync", type=int, default=8,
                    help="decode ticks fused per host sync")
    ap.add_argument("--speculative", action="store_true",
                    help="also run the speculative engine (truncated-depth "
                         "draft) and report its tok/s + rounds")
    args = ap.parse_args()

    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    from paddle_tpu.serving import ContinuousBatchingEngine

    paddle.seed(0)
    k = args.ticks_per_sync
    if args.cpu:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=256,
                        compute_dtype="float32")
        P_bucket, n_req = 16, 12
        budgets = [8, 16, 24, 32] * 3
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12, max_position_embeddings=1024,
                        compute_dtype="bfloat16")
        P_bucket, n_req = 128, 24
        budgets = [64, 128, 256, 512] * 6
    # headroom for chunk rounding: budgets round up to multiples of k
    max_len = P_bucket + -(-max(budgets) // k) * k
    SPEC_SLACK = 5  # speculative engine adds draft_k(=4) + 1 headroom
    need = max_len + (SPEC_SLACK if args.speculative else 0)
    if need > cfg.max_position_embeddings:
        raise SystemExit(f"--ticks_per_sync {k}: max_len {need} exceeds "
                         f"the model's positions")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, cfg.vocab_size, rng.randint(
        P_bucket // 2, P_bucket + 1))) for _ in range(n_req)]

    S = args.slots
    total_tokens = sum(budgets)

    # --- static baseline: batches of S, each runs to its LONGEST budget
    # (batch members cannot retire early — generate() is all-or-nothing).
    # Same request set as the engine: LEFT-padded with a prompt_mask, the
    # generate() ragged contract.  The last chunk is padded up to S rows so
    # every call shares one compiled (batch=S) shape.
    def batch_of(chunk):
        rows, mask = [], []
        for p in (chunk + [chunk[-1]] * (S - len(chunk))):
            rows.append([0] * (P_bucket - len(p)) + p)
            mask.append([0] * (P_bucket - len(p)) + [1] * len(p))
        return (jnp.asarray(rows, jnp.int32),
                np.asarray(mask, np.int32))

    chunks = [prompts[i:i + S] for i in range(0, n_req, S)]
    chunk_budgets = [budgets[i:i + S] for i in range(0, n_req, S)]
    # warmup-compile every distinct (batch, max_new) signature
    for chunk, bud in zip(chunks, chunk_budgets):
        ids, mask = batch_of(chunk)
        # tpulint: disable=blocking-fetch-in-loop(bench warmup — compiles must finish before timing starts)
        model.generate(params, ids, max(bud), greedy=True,
                       prompt_mask=mask).block_until_ready()
    t0 = time.perf_counter()
    for chunk, bud in zip(chunks, chunk_budgets):
        ids, mask = batch_of(chunk)
        # tpulint: disable=blocking-fetch-in-loop(the per-chunk sync IS the static-batching cost being measured)
        model.generate(params, ids, max(bud), greedy=True,
                       prompt_mask=mask).block_until_ready()
    static_dt = time.perf_counter() - t0
    static_tok_s = total_tokens / static_dt  # useful tokens only

    # --- engine: same requests, staggered retirement + re-admission
    def run_engine():
        eng = ContinuousBatchingEngine(model, params, max_slots=S,
                                       max_len=max_len,
                                       prompt_buckets=[P_bucket],
                                       ticks_per_sync=args.ticks_per_sync)
        for p, n in zip(prompts, budgets):
            eng.add_request(p, n)
        out = eng.run_to_completion(max_ticks=100000)
        assert sum(len(v) for v in out.values()) == total_tokens
        return out

    run_engine()  # warmup compile (prefill + decode programs)
    t0 = time.perf_counter()
    run_engine()
    engine_dt = time.perf_counter() - t0
    engine_tok_s = total_tokens / engine_dt

    out = {
        "metric": "serve_continuous_batching_tok_s",
        "value": round(engine_tok_s, 1), "unit": "tokens/s/chip",
        "static_tok_s": round(static_tok_s, 1),
        "utilization_win": round(engine_tok_s / static_tok_s, 3),
        "requests": n_req, "slots": S, "total_tokens": total_tokens,
        "ticks_per_sync": args.ticks_per_sync,
        "backend": "cpu" if args.cpu else "tpu",
    }

    # --- paged engine: same workload over the block pool (lazy growth)
    try:
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        blk = 16 if args.cpu else 32
        max_len_pg = -(-max_len // blk) * blk

        def run_paged():
            eng = PagedContinuousBatchingEngine(
                model, params, max_slots=S, max_len=max_len_pg,
                block_size=blk, prompt_buckets=[P_bucket],
                ticks_per_sync=args.ticks_per_sync)
            for p, n in zip(prompts, budgets):
                eng.add_request(p, n)
            got = eng.run_to_completion(max_ticks=100000)
            assert sum(len(v) for v in got.values()) == total_tokens
            return eng

        run_paged()  # warmup compile
        t0 = time.perf_counter()
        eng_pg = run_paged()
        paged_dt = time.perf_counter() - t0
        out["paged_tok_s"] = round(total_tokens / paged_dt, 1)
        out["paged_vs_contiguous"] = round(engine_dt / paged_dt, 3)
        out["paged_blocks_high_water"] = eng_pg.blocks_high_water
        out["paged_positions_reserved_contiguous"] = S * max_len_pg
        out["paged_positions_high_water"] = eng_pg.blocks_high_water * blk

        # on TPU: A/B the Pallas in-kernel table walk vs the XLA gather
        # fallback (FLAGS_use_pallas_kernels is the kill switch; the flag
        # is part of the paged program-cache signature, so this recompiles
        # rather than silently reusing)
        import jax as _jax
        if _jax.default_backend() == "tpu":
            from paddle_tpu.core.flags import flag, set_flags
            prior = bool(flag("FLAGS_use_pallas_kernels"))
            try:
                set_flags({"FLAGS_use_pallas_kernels": False})
                run_paged()  # warmup the fallback programs
                t0 = time.perf_counter()
                run_paged()
                fb_dt = time.perf_counter() - t0
                out["paged_fallback_tok_s"] = round(total_tokens / fb_dt, 1)
                out["paged_kernel_speedup"] = round(fb_dt / paged_dt, 3)
            finally:
                # restore the OPERATOR's setting (they may have the kill
                # switch deliberately off after a Mosaic miscompile)
                set_flags({"FLAGS_use_pallas_kernels": prior})
    except Exception as e:  # noqa: BLE001 - report, don't lose the line
        out["paged_error"] = f"{type(e).__name__}: {e}"[:200]

    # --- ragged engine: the SAME workload AND the same upfront arrival
    # schedule as the contiguous/paged baselines (so ragged_vs_contiguous
    # is a clean engine A/B).  Admissions still mix into running decode:
    # with every slot busy the queue drains as slots retire, and
    # ragged_mixed_steps counts the fused prefill+decode ticks that
    # actually happened.
    try:
        from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
        blk = 16 if args.cpu else 32
        max_len_rg = -(-max_len // blk) * blk

        def run_ragged():
            eng = RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=S, max_len=max_len_rg,
                block_size=blk, prompt_buckets=[P_bucket],
                token_budget=S + P_bucket)
            for p, n in zip(prompts, budgets):
                eng.add_request(p, n)
            got = eng.run_to_completion(max_ticks=100000)
            assert sum(len(v) for v in got.values()) == total_tokens
            return eng

        run_ragged()  # warmup the (budget, C) family
        t0 = time.perf_counter()
        eng_rg = run_ragged()
        ragged_dt = time.perf_counter() - t0
        out["ragged_tok_s"] = round(total_tokens / ragged_dt, 1)
        out["ragged_vs_contiguous"] = round(engine_dt / ragged_dt, 3)
        out["ragged_mixed_steps"] = int(eng_rg.mixed_steps)
        out["ragged_steps"] = int(eng_rg.ragged_steps)
    except Exception as e:  # noqa: BLE001 - report, don't lose the line
        out["ragged_error"] = f"{type(e).__name__}: {e}"[:200]

    # --- prefix cache: repeated-prefix workload, TTFT A/B (sequential
    # single-slot requests so TTFT == admission prefill + first token)
    try:
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        # sharing needs multi-block prompts (F <= P/bs - 1): 4 blocks/bucket
        blk = P_bucket // 4
        pre = [int(t) for t in rng.randint(1, cfg.vocab_size,
                                           P_bucket - 4)]
        tails = [[int(t) for t in rng.randint(1, cfg.vocab_size, 4)]
                 for _ in range(8)]
        warm_pre = [int(t) for t in rng.randint(1, cfg.vocab_size,
                                                P_bucket - 4)]

        def mean_ttft(cache_on):
            eng = PagedContinuousBatchingEngine(
                model, params, max_slots=1,
                max_len=-(-(P_bucket + 8) // blk) * blk, block_size=blk,
                prompt_buckets=[P_bucket], enable_prefix_cache=cache_on)
            # compile warmup: two same-prefix requests force BOTH the
            # whole-bucket and the cached-prefill programs to build
            for t in ([9], [11]):
                eng.add_request(warm_pre + t + [1] * 3, 2)
                eng.run_to_completion(max_ticks=1000)
            s0 = eng._stats.value("ttft_seconds_sum")
            n0 = eng._stats.value("requests_finished")
            for t in tails:
                eng.add_request(pre + t, 4)
                eng.run_to_completion(max_ticks=1000)
            dt = (eng._stats.value("ttft_seconds_sum") - s0) \
                / (eng._stats.value("requests_finished") - n0)
            return dt, eng

        off_ttft, _ = mean_ttft(False)
        on_ttft, eng_on = mean_ttft(True)
        out["prefix_ttft_ms_off"] = round(off_ttft * 1e3, 2)
        out["prefix_ttft_ms_on"] = round(on_ttft * 1e3, 2)
        out["prefix_ttft_win"] = round(off_ttft / on_ttft, 3)
        out["prefix_hits"] = eng_on.prefix_hits
        out["prefix_blocks_reused"] = eng_on.prefix_blocks_reused
    except Exception as e:  # noqa: BLE001 - report, don't lose the line
        out["prefix_error"] = f"{type(e).__name__}: {e}"[:200]

    if args.speculative:
      try:  # the base metric must survive any speculative failure
        from paddle_tpu.serving import SpeculativeBatchingEngine
        dcfg_kw = dict(vocab_size=cfg.vocab_size,
                       hidden_size=cfg.hidden_size,
                       num_layers=max(cfg.num_layers // 6, 1),
                       num_attention_heads=cfg.num_attention_heads,
                       max_position_embeddings=cfg.max_position_embeddings,
                       compute_dtype=cfg.compute_dtype)
        draft = GPTModel(GPTConfig(**dcfg_kw))
        dparams = {n: p._data for n, p in draft.named_parameters()}

        def run_spec():
            eng = SpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=S,
                max_len=max_len + SPEC_SLACK, draft_k=4,
                prompt_buckets=[P_bucket])
            for p, n in zip(prompts, budgets):
                eng.add_request(p, n)
            got = eng.run_to_completion(max_ticks=100000)
            assert sum(len(v) for v in got.values()) == total_tokens
            return eng.rounds

        run_spec()  # warmup compile
        t0 = time.perf_counter()
        rounds = run_spec()
        spec_dt = time.perf_counter() - t0
        out["speculative_tok_s"] = round(total_tokens / spec_dt, 1)
        out["speculative_rounds"] = rounds
        out["speculative_speedup"] = round(engine_dt / spec_dt, 3)

        # the composed engine: speculative decoding over the paged pool
        from paddle_tpu.serving import PagedSpeculativeBatchingEngine
        blk_s = 16 if args.cpu else 32
        max_len_ps = -(-(max_len + SPEC_SLACK) // blk_s) * blk_s

        def run_spec_paged():
            eng = PagedSpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=S,
                max_len=max_len_ps, draft_k=4, prompt_buckets=[P_bucket],
                block_size=blk_s)
            for p, n in zip(prompts, budgets):
                eng.add_request(p, n)
            got = eng.run_to_completion(max_ticks=100000)
            assert sum(len(v) for v in got.values()) == total_tokens
            return eng

        run_spec_paged()  # warmup compile
        t0 = time.perf_counter()
        eng_sp = run_spec_paged()
        sp_dt = time.perf_counter() - t0
        out["spec_paged_tok_s"] = round(total_tokens / sp_dt, 1)
        out["spec_paged_rounds"] = eng_sp.rounds
        out["spec_paged_blocks_high_water"] = eng_sp.blocks_high_water
      except Exception as e:  # noqa: BLE001 - report, don't lose the line
        out["speculative_error"] = f"{type(e).__name__}: {e}"[:200]

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
