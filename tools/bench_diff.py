"""Compare BENCH_*.json artifacts and flag metric regressions.

The per-round bench artifacts were unreadable by machine: each round is a
driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` whose ``parsed``
may be one record, a list, or null, and failed rounds masquerade as
records with ``unit: "error"`` / ``unit: "skipped"`` (bench.py's probe
protocol).  This tool normalizes all of that and answers the only
question that matters between rounds: *did any metric regress beyond the
threshold?*

Usage:
  python tools/bench_diff.py OLD.json NEW.json [--threshold 0.1]
  python tools/bench_diff.py --scan . [--threshold 0.1]
      # orders BENCH_r*.json by round number and compares, per metric,
      # the previous comparable value against the latest comparable one

Accepted file shapes (auto-detected):
  - driver wrapper: {"n": 5, ..., "parsed": <record|list|null>}
    (when parsed is null, records are recovered from the "tail" lines)
  - a single bench record: {"metric": ..., "value": ..., "unit": ...}
  - a JSON list of records
  - raw bench.py stdout: one JSON record per line (JSONL)

Direction is inferred from the unit: throughputs (``.../s...``) regress
when they DROP, latencies (``ms``/``s``) regress when they RISE.  A
record (or synthetic sub-metric) may also carry an explicit
``direction`` of ``"lower"``/``"higher"`` which wins over the unit rule.
Records with ``unit`` of ``error``/``skipped`` or a null value are
classified as non-comparable, never as regressions — an infra-dead round
must not read as a code regression (and must not hide one either: it
simply doesn't participate).  Likewise two records whose ``backend``
labels differ (a CPU round against a TPU round): that delta is a
hardware change, not a code change, so the pair is reported
``not comparable (backend cpu -> tpu)`` — the first healthy-chip round
starts a fresh trajectory instead of reading as a giant "improvement".

Telemetry attachments participate too: when BOTH rounds of a metric
carry a ``telemetry`` snapshot, its known fields (TTFT/ITL/tick
percentiles, compile misses, goodput, MFU, model FLOPs/s — see
``_TELEMETRY_FIELDS``) are expanded into synthetic
``<metric>.telemetry.<field>`` rows with unit-direction-aware
thresholds, so a TTFT p99 regression or a goodput/MFU drop is flagged
even when the headline throughput number held.

``chaos`` attachments (the ``gpt_chaos`` record shape: fault-plan A/B
with per-phase outcome counts and resilience counters) expand the same
way through ``_CHAOS_FIELDS`` — a drop in the resilience-on finished
count, a rise in retries-exhausted, or a shrinking p99-TTFT improvement
factor between rounds is a resilience regression even when the headline
p99 held.  ``memory`` attachments (the memory-ledger block bench records
carry when their byte claims are measured) expand through
``_MEMORY_FIELDS`` — every leaf is a byte gauge judged lower-is-better,
so an HBM-footprint creep is flagged like a latency creep.

Records also carry a ``health`` stamp (the parent's backend probe
verdict: backend, device count/kind, ``compute_healthy``).  When the
probe backends of a pair disagree the pair is not comparable — the
probe actually touched the device, so it outranks the record label;
a device-kind change or an unhealthy probe on either side prints a
WARNING next to the row instead.

Exit codes:
  0  comparable data found, no regression beyond --threshold
  1  at least one regression beyond --threshold
  2  no comparable data at all (every record error/skipped/missing)
"""

import argparse
import glob
import json
import os
import re
import sys

#: units where a LOWER new value is better (latency-shaped)
_LOWER_IS_BETTER = ("ms", "s", "seconds")

#: telemetry-snapshot fields worth diffing between rounds: leaf name ->
#: (synthetic unit, direction).  Anything not listed (counts of ticks,
#: raw event tallies) is context, not a health signal.
_TELEMETRY_FIELDS = {
    "ttft_ms_p50": ("ms", "lower"),
    "ttft_ms_p99": ("ms", "lower"),
    "itl_ms_p50": ("ms", "lower"),
    "itl_ms_p99": ("ms", "lower"),
    "tick_ms_p50": ("ms", "lower"),
    "tick_ms_p95": ("ms", "lower"),
    "step_ms_p50": ("ms", "lower"),
    "step_ms_p95": ("ms", "lower"),
    "compile_misses": ("count", "lower"),
    "compile_wall_s": ("s", "lower"),
    "goodput": ("frac", "higher"),
    "mfu": ("frac", "higher"),
    "model_flops_per_s": ("flops/s", "higher"),
    "arithmetic_intensity": ("flops/byte", "higher"),
    "tokens_per_sec": ("tokens/s", "higher"),
    # spec-ragged serving A/B (bench.py gpt_serving speculative arm)
    "accepted_tokens_per_s": ("tokens/s", "higher"),
    "acceptance_rate": ("frac", "higher"),
    "spec_tokens_per_sec": ("tokens/s", "higher"),
}

#: kv-tier attachment fields worth diffing (bench.py gpt_kv_tier record
#: shape): leaf name -> (synthetic unit, direction).  migrated_bytes is
#: judged LOWER-is-better: moving fewer bytes for the same warm handoff
#: is less wire; restored_blocks/migration counts are scenario context.
_KVTIER_FIELDS = {
    "cold_ttft_ms_p50": ("ms", "lower"),
    "warm_ttft_ms_p50": ("ms", "lower"),
    "restore_ttft_p99": ("ms", "lower"),
    "migration_ttft_ms_p50": ("ms", "lower"),
    "warm_speedup": ("x", "higher"),
    "tier_hit_rate": ("frac", "higher"),
    "migrated_bytes": ("bytes", "lower"),
    # measured per-tier KV bytes (the memory-ledger rows under
    # tier_bytes/tier_peak_bytes): holding more bytes for the same
    # scenario is a capacity regression
    "hbm": ("bytes", "lower"),
    "dram": ("bytes", "lower"),
    "disk": ("bytes", "lower"),
}

#: weight-update-sharding attachment fields worth diffing (bench.py
#: gpt_weight_update_sharding record shape): leaf name -> (synthetic
#: unit, direction).  ``opt_bytes_per_replica``/``step_ms``/
#: ``wire_bytes`` appear once per arm (…replicated.* and …sharded.*
#: synthetic rows) and regress when they RISE; the reduction factor and
#: per-arm throughput regress when they DROP.  ``loss_delta`` rising
#: means the parity pin is eroding — judged lower-is-better; ``loss``
#: and ``replicas`` are scenario context, not health signals.
_UPDATE_SHARDING_FIELDS = {
    "opt_bytes_per_replica": ("bytes", "lower"),
    "opt_bytes_per_replica_measured": ("bytes", "lower"),
    "opt_bytes_reduction": ("x", "higher"),
    "opt_bytes_reduction_measured": ("x", "higher"),
    "step_ms": ("ms", "lower"),
    "wire_bytes": ("bytes", "lower"),
    "tokens_per_sec": ("tokens/s", "higher"),
    "loss_delta": ("abs", "lower"),
}

#: memory-ledger attachment fields worth diffing (the ``memory`` block a
#: record carries when its byte claims are measured — bench.py
#: ``_memory_block``): every leaf is a byte gauge, and holding MORE
#: bytes for the same scenario is the regression direction.
_MEMORY_FIELDS = {
    "device_bytes": ("bytes", "lower"),
    "host_bytes": ("bytes", "lower"),
    "device_peak_bytes": ("bytes", "lower"),
    "host_peak_bytes": ("bytes", "lower"),
    "bytes": ("bytes", "lower"),
    "peak_bytes": ("bytes", "lower"),
}

#: fleet-rollup attachment fields worth diffing (the ``fleet`` block a
#: record carries when a telemetry_fleet.FleetCollector federated the
#: run — bench.py gpt_gateway): leaf name -> (synthetic unit,
#: direction).  Global goodput, fleet MFU, and tokens/s regress when
#: they DROP; the merged latency percentiles and the straggler skew
#: (max/mean per-target compute — 1.0 is perfectly balanced) regress
#: when they RISE.  Target counts are scenario context, not judged.
_FLEET_FIELDS = {
    "goodput_global": ("frac", "higher"),
    "fleet_mfu": ("frac", "higher"),
    "fleet_ttft_p99": ("s", "lower"),
    "fleet_ttft_p50": ("s", "lower"),
    "fleet_itl_p99": ("s", "lower"),
    "straggler_skew": ("x", "lower"),
    "tokens_per_s": ("tokens/s", "higher"),
}

#: chaos-attachment fields worth diffing (bench.py gpt_chaos record
#: shape): leaf name -> (synthetic unit, direction).  Counts of hedges/
#: breaker transitions are scenario-shaped context, not judged.
_CHAOS_FIELDS = {
    "p99_ttft_improvement": ("x", "higher"),
    "ttft_s_p99": ("s", "lower"),
    "finished": ("count", "higher"),
    "failed": ("count", "lower"),
    "expired": ("count", "lower"),
    "shed_rate": ("frac", "lower"),
    "retries": ("count", "lower"),
    "retries_exhausted": ("count", "lower"),
}

#: train-resilience attachment fields worth diffing (bench.py
#: gpt_train_resilience record shape): leaf name -> (synthetic unit,
#: direction).  The recovery tax regresses when it RISES (more wall in
#: restore, more steps replayed, more saves lost); goodput and oracle
#: fidelity regress when they DROP.  Restart/skip counts are the
#: scenario's shape under a pinned fault plan — judged, because a rise
#: means recovery started thrashing under the SAME plan.
_TRAIN_RESILIENCE_FIELDS = {
    "recovery_time_s": ("s", "lower"),
    "steps_replayed": ("count", "lower"),
    "final_loss_delta": ("abs", "lower"),
    "goodput": ("frac", "higher"),
    "goodput_delta_vs_oracle": ("frac", "lower"),
    "wall_overhead_x": ("x", "lower"),
    "restarts": ("count", "lower"),
    "saves_abandoned": ("count", "lower"),
    "saves_committed": ("count", "higher"),
}


def _flatten(prefix, obj, out):
    for k, v in obj.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            _flatten(path, v, out)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((path, k, v))


def expand_telemetry(records):
    """records + synthetic ``<metric>.<attachment>.<field>`` rows for
    every whitelisted leaf of a comparable record's ``telemetry`` /
    ``chaos`` attachment.  Synthetic rows carry their own unit and
    explicit ``direction`` so the comparison stays direction-aware per
    field."""
    out = []
    for rec in records:
        out.append(rec)
        if classify(rec) != "ok":
            continue
        for attachment, fields in (("telemetry", _TELEMETRY_FIELDS),
                                   ("chaos", _CHAOS_FIELDS),
                                   ("kv_tier", _KVTIER_FIELDS),
                                   ("update_sharding",
                                    _UPDATE_SHARDING_FIELDS),
                                   ("memory", _MEMORY_FIELDS),
                                   ("fleet", _FLEET_FIELDS),
                                   ("train_resilience",
                                    _TRAIN_RESILIENCE_FIELDS)):
            sub = rec.get(attachment)
            if not isinstance(sub, dict):
                continue
            leaves = []
            _flatten(attachment, sub, leaves)
            for path, leaf, value in leaves:
                spec = fields.get(leaf)
                if spec is None:
                    continue
                unit, direction = spec
                row = {"metric": f"{rec['metric']}.{path}",
                       "value": value, "unit": unit,
                       "direction": direction}
                if rec.get("backend") is not None:
                    # synthetic rows inherit the parent's backend so the
                    # cross-backend non-comparability guard covers them
                    row["backend"] = rec["backend"]
                if isinstance(rec.get("health"), dict):
                    row["health"] = rec["health"]
                out.append(row)
    return out


def classify(record):
    """'ok' | 'error' | 'skipped' | 'invalid' for one bench record."""
    if not isinstance(record, dict) or "metric" not in record:
        return "invalid"
    unit = record.get("unit")
    if unit == "error" or "error" in record:
        return "error"
    if unit == "skipped" or "skipped" in record:
        return "skipped"
    if not isinstance(record.get("value"), (int, float)):
        return "invalid"
    return "ok"


def _records_from_payload(payload):
    """Normalize any accepted file shape into a list of record dicts."""
    if payload is None:
        return []
    if isinstance(payload, list):
        return [r for r in payload if isinstance(r, dict)]
    if not isinstance(payload, dict):
        return []
    if "metric" in payload:
        return [payload]
    if "parsed" in payload:                       # driver wrapper
        records = _records_from_payload(payload["parsed"])
        if not records:
            # parsed=null: the wrapper's "tail" keeps bench.py's stdout —
            # recover any JSON record lines from it
            for line in str(payload.get("tail") or "").splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "metric" in rec:
                        records.append(rec)
        return records
    return []


def load_records(path):
    """File → list of bench records (module docstring shapes)."""
    with open(path) as f:
        text = f.read()
    try:
        return _records_from_payload(json.loads(text))
    except ValueError:
        pass
    records = []                                   # JSONL stdout capture
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            records.append(rec)
    return records


def lower_is_better(unit):
    return (unit or "").strip().lower() in _LOWER_IS_BETTER


def record_lower_is_better(rec):
    """Direction for one record: an explicit ``direction`` key wins,
    else the unit rule."""
    d = rec.get("direction")
    if d in ("lower", "higher"):
        return d == "lower"
    return lower_is_better(rec.get("unit"))


def compare(old_records, new_records, threshold):
    """Per-metric comparison.  Returns (rows, n_regressions, n_compared);
    each row is a dict with metric/status/old/new/delta_frac."""
    old_by = {r["metric"]: r for r in old_records
              if isinstance(r, dict) and "metric" in r}
    new_by = {r["metric"]: r for r in new_records
              if isinstance(r, dict) and "metric" in r}
    rows = []
    n_reg = n_cmp = 0
    for metric in sorted(set(old_by) | set(new_by)):
        old, new = old_by.get(metric), new_by.get(metric)
        co = classify(old) if old is not None else "missing"
        cn = classify(new) if new is not None else "missing"
        row = {"metric": metric, "old": old, "new": new,
               "old_status": co, "new_status": cn, "delta_frac": None}
        if co != "ok" or cn != "ok":
            row["status"] = f"not comparable ({co} -> {cn})"
            rows.append(row)
            continue
        ob, nb = old.get("backend"), new.get("backend")
        if ob is not None and nb is not None and ob != nb:
            # a CPU round measured against a TPU round is a hardware
            # change, not a code change — cross-backend pairs are
            # reported, never judged (the honest-labeling contract:
            # every bench record carries its backend)
            row["status"] = f"not comparable (backend {ob} -> {nb})"
            rows.append(row)
            continue
        oh = old.get("health") or {}
        nh = new.get("health") or {}
        hb_o, hb_n = oh.get("backend"), nh.get("backend")
        if hb_o and hb_n and hb_o != hb_n:
            # the probe's verdict contradicts the record labels — trust
            # the probe: it actually touched the device
            row["status"] = ("not comparable (probe backend "
                             f"{hb_o} -> {hb_n})")
            rows.append(row)
            continue
        warns = []
        ok_o, ok_n = oh.get("device_kind"), nh.get("device_kind")
        if ok_o and ok_n and ok_o != ok_n:
            warns.append(f"device kind changed ({ok_o} -> {ok_n})")
        for side, h in (("old", oh), ("new", nh)):
            if h and h.get("compute_healthy") is False:
                warns.append(f"{side} round's backend probe was unhealthy")
        if warns:
            row["warnings"] = warns
        ov, nv = float(old["value"]), float(new["value"])
        if ov == 0.0:
            row["status"] = "not comparable (old value 0)"
            rows.append(row)
            continue
        n_cmp += 1
        delta = (nv - ov) / abs(ov)
        row["delta_frac"] = delta
        worse = delta if record_lower_is_better(new) else -delta
        if worse > threshold:
            n_reg += 1
            row["status"] = f"REGRESSION ({worse:+.1%} worse, " \
                            f"threshold {threshold:.1%})"
        else:
            row["status"] = "ok"
        rows.append(row)
    return rows, n_reg, n_cmp


def _round_key(path):
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    if m:
        return int(m.group(1))
    return os.path.basename(path)


def scan_trajectory(directory, pattern="BENCH_r*.json"):
    """Ordered [(path, records)] for the round trajectory in ``directory``."""
    paths = sorted(glob.glob(os.path.join(directory, pattern)),
                   key=_round_key)
    return [(p, load_records(p)) for p in paths]


def _fmt_value(rec):
    if rec is None:
        return "-"
    c = classify(rec)
    if c != "ok":
        return c
    return f"{rec['value']:g} {rec.get('unit', '')}".strip()


def _print_rows(rows, out):
    for row in rows:
        print(f"{row['metric']}: {_fmt_value(row['old'])} -> "
              f"{_fmt_value(row['new'])}"
              + (f" ({row['delta_frac']:+.1%})"
                 if row["delta_frac"] is not None else "")
              + f"  [{row['status']}]", file=out)
        for w in row.get("warnings", ()):
            print(f"  WARNING: {w}", file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter, epilog=__doc__)
    ap.add_argument("files", nargs="*",
                    help="two artifacts to compare (OLD NEW)")
    ap.add_argument("--scan", metavar="DIR", default=None,
                    help="scan DIR's BENCH_r*.json trajectory instead of "
                         "comparing two explicit files")
    ap.add_argument("--pattern", default="BENCH_r*.json",
                    help="glob for --scan (default: %(default)s)")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="regression threshold as a fraction "
                         "(default: %(default)s = 10%%)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    args = ap.parse_args(argv)

    if args.scan is not None:
        if args.files:
            ap.error("--scan and explicit files are mutually exclusive")
        traj = scan_trajectory(args.scan, args.pattern)
        if not traj:
            print(f"bench_diff: no {args.pattern} under {args.scan}",
                  file=sys.stderr)
            return 2
        # per metric: latest comparable value vs the comparable value
        # before it — intermediate error/skipped rounds are stepped over
        last = {}        # metric -> (round_path, record) previous comparable
        old_sel, new_sel = {}, {}
        for path, records in traj:
            for rec in expand_telemetry(records):
                if classify(rec) != "ok":
                    continue
                metric = rec["metric"]
                if metric in last:
                    old_sel[metric] = last[metric][1]
                    new_sel[metric] = rec
                last[metric] = (path, rec)
        if not args.json:
            for path, records in traj:
                states = [f"{r.get('metric')}={_fmt_value(r)}"
                          for r in records] or ["<no records>"]
                print(f"{os.path.basename(path)}: " + ", ".join(states))
        rows, n_reg, n_cmp = compare(list(old_sel.values()),
                                     list(new_sel.values()), args.threshold)
        if not n_cmp and last:
            # metrics exist but never twice — still nothing to diff
            rows = [{"metric": m, "old": None, "new": rec,
                     "old_status": "missing", "new_status": "ok",
                     "delta_frac": None,
                     "status": "only one comparable round"}
                    for m, (_p, rec) in sorted(last.items())]
    else:
        if len(args.files) != 2:
            ap.error("need exactly two files (or --scan DIR)")
        rows, n_reg, n_cmp = compare(
            expand_telemetry(load_records(args.files[0])),
            expand_telemetry(load_records(args.files[1])),
            args.threshold)

    if args.json:
        print(json.dumps({"threshold": args.threshold, "compared": n_cmp,
                          "regressions": n_reg,
                          "rows": [{k: v for k, v in r.items()
                                    if k not in ("old", "new")}
                                   | {"old": _fmt_value(r["old"]),
                                      "new": _fmt_value(r["new"])}
                                   for r in rows]}, indent=2))
    else:
        _print_rows(rows, sys.stdout)
    if n_cmp == 0:
        print("bench_diff: no comparable data (every record error/"
              "skipped/missing)", file=sys.stderr)
        return 2
    if n_reg:
        print(f"bench_diff: {n_reg} regression(s) beyond "
              f"{args.threshold:.1%}", file=sys.stderr)
        return 1
    print(f"bench_diff: OK — {n_cmp} metric(s) compared, no regression "
          f"beyond {args.threshold:.1%}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
