#!/usr/bin/env bash
# Fast import-time regression gate: `pytest --collect-only` over tests/
# must be CLEAN (a single broken import silently deselects a whole module
# from the tier-1 run — round 5's `from jax import shard_map` regression
# hid tests/test_spmd_vma_seam.py for a full round).  Run before pushing;
# tests/test_collect_smoke.py enforces the same invariant in-suite.
set -uo pipefail
cd "$(dirname "$0")/.."
out=$(JAX_PLATFORMS=cpu python -m pytest tests/ -q --collect-only \
      -p no:cacheprovider 2>&1)
rc=$?
echo "$out" | tail -3
if [ "$rc" -ne 0 ]; then
    echo "COLLECT SMOKE FAILED: import-time error in tests/ (rc=$rc)"
    exit 1
fi
# telemetry surface: the observability modules must import clean and the
# trace CLI must self-describe (its --help path exercises arg wiring
# without needing xprof)
if ! JAX_PLATFORMS=cpu python -c \
    "import paddle_tpu.telemetry, paddle_tpu.utils.stats, paddle_tpu.profiler" \
    >/dev/null 2>&1; then
    echo "COLLECT SMOKE FAILED: telemetry module import"
    exit 1
fi
if ! JAX_PLATFORMS=cpu python tools/trace_to_chrome.py --help >/dev/null 2>&1; then
    echo "COLLECT SMOKE FAILED: tools/trace_to_chrome.py --help"
    exit 1
fi
# training telemetry surface: TrainMonitor + the fit callback re-export must
# import clean, and a training JSONL dump must convert through the trace
# CLI's merge loader (the --engine-trace ingestion path, exercised without
# xprof/xplane files)
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'PYEOF'
import importlib.util, os, tempfile
from paddle_tpu.telemetry import TrainMonitor
from paddle_tpu.callbacks import TelemetryCallback  # noqa: F401 re-export
mon = TrainMonitor()
mon.record_step(0.01, trainer="smoke", examples=4, tokens=8)
mon.record_sync(0.001, loss=1.25)
path = os.path.join(tempfile.mkdtemp(), "train.jsonl")
mon.dump_jsonl(path)
spec = importlib.util.spec_from_file_location(
    "_t2c_smoke", "tools/trace_to_chrome.py")
t2c = importlib.util.module_from_spec(spec)
spec.loader.exec_module(t2c)
ct = t2c._load_engine_trace(path)
assert any(e.get("name") == "train_step" for e in ct["traceEvents"]), ct
assert any(e.get("name") == "sync" for e in ct["traceEvents"]), ct
PYEOF
then
    echo "COLLECT SMOKE FAILED: training telemetry import / JSONL merge"
    exit 1
fi
# grad-comm surface: the policy layer must import clean, the int8 local
# round trip must run, the byte model must clear the 3.5x contract, and
# the gpt_grad_comm bench config must be registered with a working
# --help path
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'GCEOF'
import jax.numpy as jnp
from paddle_tpu.distributed.grad_comm import (
    compressed_all_reduce, compressed_reduce_scatter,  # noqa: F401
    resolve_policy, wire_bytes)
p = resolve_policy("int8_ef")
tree = {"w": jnp.ones((8, 64), jnp.float32)}
out, e = p.apply_local(tree, None)
assert e is not None and out["w"].shape == (8, 64)
wb = wire_bytes(tree, p)
assert wb["pre_bytes"] / wb["post_bytes"] >= 3.5, wb
import bench
assert "gpt_grad_comm" in bench.CONFIGS
GCEOF
then
    echo "COLLECT SMOKE FAILED: grad_comm policy layer / bench config"
    exit 1
fi
if ! python bench.py --help >/dev/null 2>&1; then
    echo "COLLECT SMOKE FAILED: bench.py --help"
    exit 1
fi
# AOT surface: jit.aot must import clean, a tiny warmup→serve round trip
# must record ZERO in-serve compile misses (the compile-once contract),
# and the warmup CLI must self-describe
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'AOTEOF'
from paddle_tpu.jit.aot import (ExecutableCache, compile_aot,  # noqa: F401
                                fingerprint, run_warmup, warmup_async)
from paddle_tpu.jit import warm_train_step  # noqa: F401 functional seam
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                num_attention_heads=2, max_position_embeddings=64,
                compute_dtype="float32")
model = GPTModel(cfg)
params = {n: p._data for n, p in model.named_parameters()}
eng = RaggedPagedContinuousBatchingEngine(
    model, params, max_slots=2, max_len=32, block_size=8,
    prompt_buckets=[8], token_budget=12)
report = eng.warmup(max_workers=1)
assert report["programs"] == len(eng.compile_grid()) >= 1, report
before = eng._compile_misses
eng.add_request([1, 2, 3], 2)
out = eng.run_to_completion(max_ticks=50)
assert eng._compile_misses == before, "warmup missed a program family"
assert all(len(v) == 2 for v in out.values()), out
import bench
assert "gpt_serving_warmup" in bench.CONFIGS
AOTEOF
then
    echo "COLLECT SMOKE FAILED: jit.aot import / warmup round trip"
    exit 1
fi
if ! python tools/warmup.py --help >/dev/null 2>&1; then
    echo "COLLECT SMOKE FAILED: tools/warmup.py --help"
    exit 1
fi
# goodput ledger + ops server surface: modules import clean, a tiny train
# run's ledger buckets sum to its elapsed wall time (the exhaustiveness
# invariant), and a LIVE /metrics scrape returns the merged exposition
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'LEDEOF'
import urllib.request
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.callbacks import GoodputCallback
from paddle_tpu.telemetry_ledger import (FlightRecorder, RunLedger,  # noqa
                                         current_ledger)
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.optimizer import Adam
paddle.seed(0)
m = Model(nn.Linear(4, 2), inputs=[None])
m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
cb = GoodputCallback()
xs = np.ones((8, 4), "float32"); ys = np.zeros((8, 2), "float32")
m.fit([(xs, ys)] * 6, epochs=1, verbose=0, callbacks=[cb])
snap = cb.last_snapshot
total = sum(snap["buckets_s"].values())
assert abs(total - snap["elapsed_s"]) <= 0.01 * snap["elapsed_s"] + 1e-9, snap
assert snap["overflow_s"] == 0.0, snap
assert current_ledger() is None   # symmetric teardown
srv = OpsServer()
srv.attach(cb.ledger)
url = srv.start()
txt = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
assert "paddle_tpu_ledger_goodput" in txt, txt[:400]
code = urllib.request.urlopen(url + "/ledger", timeout=10).status
assert code == 200
srv.stop()
LEDEOF
then
    echo "COLLECT SMOKE FAILED: goodput ledger / ops server round trip"
    exit 1
fi
if ! python tools/bench_diff.py --help >/dev/null 2>&1; then
    echo "COLLECT SMOKE FAILED: tools/bench_diff.py --help"
    exit 1
fi
# serving gateway surface: the module must import clean, a tiny
# two-replica submit→stream→drain round trip must finish with zero drops
# (streamed tokens intact, drained replica stopped), and the gateway CLI
# must self-describe
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'GWEOF'
from paddle_tpu.gateway import (DeadlineExceeded, Overloaded,  # noqa: F401
                                ServingGateway)
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
from paddle_tpu.telemetry import Tracer
cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                num_attention_heads=2, max_position_embeddings=64,
                compute_dtype="float32")
model = GPTModel(cfg)
params = {n: p._data for n, p in model.named_parameters()}
def eng():
    return RaggedPagedContinuousBatchingEngine(
        model, params, max_slots=2, max_len=32, block_size=8,
        prompt_buckets=[8], token_budget=12, tracer=Tracer())
gw = ServingGateway(tracer=Tracer())
gw.add_replica(eng(), "a")
gw.add_replica(eng(), "b")
streams = {}
r1 = gw.submit([1, 2, 3], 3,
               on_token=lambda g, t, d: streams.setdefault(g, [])
               .append((t, d)))
r2 = gw.submit([4, 5], 2)
gw.step()
gw.drain("a")
got = gw.run_to_completion(max_ticks=200)
assert r1.status == r2.status == "finished", (r1.status, r2.status)
assert gw.is_drained("a")
assert [t for t, d in streams[r1.gid]] == r1.tokens
assert streams[r1.gid][-1][1] is True
assert sorted(got) == sorted([r1.gid, r2.gid])
assert gw.replica("a").engine.blocks_in_use == 0
import bench
assert "gpt_gateway" in bench.CONFIGS
GWEOF
then
    echo "COLLECT SMOKE FAILED: serving gateway round trip"
    exit 1
fi
if ! python tools/serve_gateway.py --help >/dev/null 2>&1; then
    echo "COLLECT SMOKE FAILED: tools/serve_gateway.py --help"
    exit 1
fi
# request-tracing + SLO surface: telemetry_slo must import clean, a tiny
# gateway round trip must serve live /slo + /requests + /request/<id>
# (one stitched trace, no orphan spans), and the chrome flow-event merge
# (gateway dump + engine dump through trace_to_chrome's loader) must
# carry matching s/f flow ids
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'SLOEOF'
import importlib.util, json, os, tempfile, urllib.request
from paddle_tpu.telemetry import RequestTraceIndex, TraceContext, Tracer
from paddle_tpu.telemetry_slo import Objective, PercentileSketch, SLOMonitor
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                num_attention_heads=2, max_position_embeddings=64,
                compute_dtype="float32")
model = GPTModel(cfg)
params = {n: p._data for n, p in model.named_parameters()}
def eng():
    return RaggedPagedContinuousBatchingEngine(
        model, params, max_slots=2, max_len=32, block_size=8,
        prompt_buckets=[8], token_budget=12, tracer=Tracer())
slo = SLOMonitor()
slo.add_objective(Objective.latency("ttft_p99", "ttft_s", 0.5))
gw = ServingGateway(tracer=Tracer())
gw.set_slo(slo)
gw.add_replica(eng(), "a")
gw.add_replica(eng(), "b")
r = gw.submit([1, 2, 3], 3)
gw.run_to_completion(max_ticks=200)
assert r.status == "finished" and r.trace is not None
srv = OpsServer()
srv.attach(gw); srv.attach(gw.replica("a").engine)
srv.attach(gw.replica("b").engine); srv.attach(slo)
url = srv.start()
snap = json.loads(urllib.request.urlopen(url + "/slo", timeout=10).read())
assert snap["objectives"][0]["name"] == "ttft_p99"
recents = json.loads(urllib.request.urlopen(
    url + "/requests", timeout=10).read())["requests"]
assert any(x["trace_id"] == r.trace.trace_id for x in recents)
one = json.loads(urllib.request.urlopen(
    url + f"/request/{r.trace.trace_id}", timeout=10).read())
ids = {s["span_id"] for s in one["spans"]}
assert all(s["parent_span_id"] in ids for s in one["spans"]
           if s["parent_span_id"] is not None), one["spans"]
assert sum(1 for s in one["spans"] if s["parent_span_id"] is None) == 1
srv.stop()
# flow-event chrome merge: gateway + engine dumps through the CLI loader
d = tempfile.mkdtemp()
gp, ep = os.path.join(d, "gw.jsonl"), os.path.join(d, "eng.jsonl")
gw.tracer.dump_jsonl(gp)
gw.replica(r.replica).engine.tracer.dump_jsonl(ep)
spec = importlib.util.spec_from_file_location(
    "_t2c_slo_smoke", "tools/trace_to_chrome.py")
t2c = importlib.util.module_from_spec(spec)
spec.loader.exec_module(t2c)
merged = []
for i, p in enumerate((gp, ep)):
    merged.extend(t2c._suffix_pids(
        t2c._load_engine_trace(p), i)["traceEvents"])
starts = {e["id"] for e in merged if e.get("ph") == "s"}
finishes = {e["id"] for e in merged if e.get("ph") == "f"}
assert starts and starts & finishes, (starts, finishes)
SLOEOF
then
    echo "COLLECT SMOKE FAILED: request-tracing / SLO round trip"
    exit 1
fi
# elastic autoscaler + simulation harness: both modules must import clean
# (no JAX needed — they are host-only), and a tiny fake-clock round trip
# must close the loop both ways — one SLO-driven scale-up (spawn → warm →
# activate, zero in-serve compiles) and one sustained-idle drain-down
# (zero drops) — with the decision timeline served by a live /autoscaler
# scrape
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'ASCEOF'
import json, urllib.request
from paddle_tpu.autoscaler import DECISIONS, ElasticAutoscaler
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                   TrafficSim, flash_crowd, steady)
from paddle_tpu.telemetry_slo import Objective, SLOMonitor
clock = SimClock()
tracer = SimTracer(clock, capacity=8192)
gw = ServingGateway(clock=clock, tracer=tracer)
spawned = []
def factory():
    spawned.append(SimEngine(max_slots=2, tracer=SimTracer(clock)))
    return spawned[-1]
seed = SimEngine(max_slots=2, tracer=SimTracer(clock))
seed.warmup()
gw.add_replica(seed, "r0")
slo = SLOMonitor([Objective.latency(
    "ttft_p99", "ttft_s", 1.0, compliance=0.9, windows=(20.0, 5.0),
    burn_threshold=1.0, for_s=1.0, clear_s=5.0)],
    clock=clock, resolution_s=1.0, tracer=tracer)
gw.set_slo(slo)
asc = ElasticAutoscaler(gw, factory, slo=slo, min_replicas=1,
                        max_replicas=2, scale_up_cooldown_s=2.0,
                        scale_down_cooldown_s=5.0, idle_utilization=0.3,
                        idle_dwell_s=8.0, tracer=tracer, clock=clock)
sim = TrafficSim(gw, clock, flash_crowd(0.02, 6.0, 5.0, 15.0),
                 dt=0.25, seed=0, autoscaler=asc)
rep = sim.run(90.0)
assert rep["dropped"] == [], rep["dropped"]
acts = [d["action"] for d in rep["decisions"]]
assert "scale_up" in acts and "activate" in acts, acts
assert "scale_down" in acts and "removed" in acts, acts
assert all(e.warmed and e.in_serve_compiles == 0 for e in spawned)
assert rep["fleet"]["active"] == 1
assert [e["what"] for e in tracer.events("autoscale")] == acts
srv = OpsServer()
srv.attach(asc, "asc")
url = srv.start()
snap = json.loads(urllib.request.urlopen(url + "/autoscaler",
                                         timeout=10).read())
assert [d["action"] for d in snap["decisions"]] == acts
assert snap["policy"]["max_replicas"] == 2
txt = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
assert "paddle_tpu_autoscaler_fleet_size 1" in txt
assert "paddle_tpu_autoscaler_last_decision" in txt
srv.stop()
assert DECISIONS[0] == "none"
ASCEOF
then
    echo "COLLECT SMOKE FAILED: autoscaler / simulation round trip"
    exit 1
fi
# fault-injection + gateway resilience: faults.py must import clean (no
# JAX — it is the host-only chaos layer), and a tiny fake-clock
# crash -> retry -> recover round trip must close the loop — a flaky
# dispatch window retried within budget, the breaker opening and
# re-closing through a half-open probe, a crashed replica quarantined
# with its work replayed token-exactly — with breaker/brownout state
# served by a live /resilience scrape
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'RESEOF'
import json, urllib.request
from paddle_tpu.faults import (Fault, FaultPlan, FaultyEngine,
                               TransientDispatchError)
from paddle_tpu.gateway import ResiliencePolicy, ServingGateway
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.simulation import SimClock, SimEngine, SimTracer, sim_tokens
clock = SimClock()
tracer = SimTracer(clock, capacity=8192)
pol = ResiliencePolicy(retry_budget=3, retry_backoff_s=0.1,
                       retry_jitter=0.0, breaker_failures=2,
                       breaker_open_s=2.0, hedge=False, brownout=False)
gw = ServingGateway(clock=clock, tracer=tracer, stall_threshold_s=4.0,
                    resilience=pol)
plan = FaultPlan([Fault("dispatch_error", at_s=0.0, duration_s=1.0),
                  Fault("crash", at_s=6.0)])
bad = SimEngine(max_slots=2, tracer=SimTracer(clock))
gw.add_replica(FaultyEngine(bad, plan, clock, replica="bad"), "bad")
gw.add_replica(SimEngine(max_slots=2, tracer=SimTracer(clock)), "ok")
hs = [gw.submit([i + 1, 2], 20) for i in range(4)]
for _ in range(200):
    gw.step()
    clock.advance(0.25)
    if not gw.pending():
        break
assert all(h.status == "finished" for h in hs), [h.status for h in hs]
assert all(h.tokens == sim_tokens(h.prompt, 20) for h in hs)
snap = gw.resilience_snapshot()
assert snap["counters"]["retries"] >= 1
assert snap["counters"]["breaker_opens"] >= 1
assert all(h.retries <= pol.retry_budget for h in hs)
assert gw.replica("bad").state == "quarantined"   # the crash, detected
whats = [e["what"] for e in tracer.events("resilience")]
assert "retry" in whats and "breaker_open" in whats
srv = OpsServer()
srv.attach(gw, "gw")
url = srv.start()
live = json.loads(urllib.request.urlopen(url + "/resilience",
                                         timeout=10).read())
assert live["breakers"]["bad"]["state"] in ("closed", "open", "half_open")
assert live["brownout"] is None                    # disabled -> honest
txt = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
assert "paddle_tpu_resilience_retries" in txt
srv.stop()
RESEOF
then
    echo "COLLECT SMOKE FAILED: faults / gateway-resilience round trip"
    exit 1
fi
# ragged speculative surface: a tiny draft+target round trip through the
# unified ragged spec engine — warmed grid (ZERO in-serve compiles), a
# mixed spec/non-spec tick, the stream equal to the plain-decode oracle
# (the greedy contract), and acceptance stats live in metrics/Prometheus
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'SPECEOF'
import numpy as np
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                num_attention_heads=2, max_position_embeddings=64,
                compute_dtype="float32")
paddle.seed(0)
model = GPTModel(cfg)
params = {n: p._data for n, p in model.named_parameters()}
paddle.seed(1)
draft = GPTModel(cfg)
dparams = {n: p._data for n, p in draft.named_parameters()}
eng = RaggedPagedContinuousBatchingEngine(
    model, params, max_slots=2, max_len=32, block_size=8,
    prompt_buckets=[8], draft_model=draft, draft_params=dparams,
    draft_k=2)
report = eng.warmup(max_workers=1)
assert report["programs"] == len(eng.compile_grid()) >= 1, report
before = eng._compile_misses
rid = eng.add_request([1, 2, 3], 4)
rid2 = eng.add_request([4, 5], 3, spec=False)   # mixed spec/non-spec tick
out = eng.run_to_completion(max_ticks=100)
assert eng._compile_misses == before, "spec grid missed a family"
oracle = model.generate(params, jnp.asarray([[1, 2, 3]], jnp.int32), 4,
                        greedy=True)
assert out[rid] == [int(t) for t in np.asarray(oracle)[0]], out
assert len(out[rid2]) == 3, out
m = eng.metrics()
assert m["tokens_drafted"] > 0 and 0.0 <= m["acceptance_rate"] <= 1.0
assert "tokens_accepted" in eng.prometheus_text()
SPECEOF
then
    echo "COLLECT SMOKE FAILED: ragged speculative round trip"
    exit 1
fi
# tiered KV store + disaggregation surface: kv_store must import, a tiny
# real-engine demote -> evict-from-HBM -> lookup -> restore round trip
# must stay token-exact vs the solo oracle with the allocator balanced,
# and a sim disaggregated fleet (prefill role -> byte-budgeted migration
# -> decode role) must serve a live /kvstore scrape with the migration
# counted and the kvstore gauge family on /metrics
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'KVEOF'
import json, urllib.request
import numpy as np
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu.kv_store import KVPage, PageMigration, TieredKVStore
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                num_attention_heads=2, max_position_embeddings=64,
                compute_dtype="float32")
paddle.seed(0)
model = GPTModel(cfg)
params = {n: p._data for n, p in model.named_parameters()}
store = TieredKVStore()
eng = RaggedPagedContinuousBatchingEngine(
    model, params, max_slots=2, max_len=48, block_size=8,
    prompt_buckets=[8, 32], enable_prefix_cache=True, kv_store=store)
prompt = list(range(1, 21))
rid = eng.add_request(prompt, 4)
out1 = eng.run_to_completion(max_ticks=200)
n = eng.flush_prefix()                     # demote: HBM empties
assert n > 0 and len(eng._prefix_cache) == 0
assert store.snapshot()["dram"]["pages"] == n
rid2 = eng.add_request(prompt, 4)          # lookup -> restore
out2 = eng.run_to_completion(max_ticks=200)
oracle = model.generate(params, jnp.asarray([prompt], jnp.int32), 4,
                        greedy=True)
want = [int(t) for t in np.asarray(oracle)[0]]
assert out1[rid] == want and out2[rid2] == want, "restore diverged"
m = eng.metrics()
assert m["kvstore_restored_blocks"] >= 1
assert m["blocks_allocated"] == m["blocks_released"]
assert eng.prefix_match(prompt)["total"] >= 1
# sim disaggregated fleet + live /kvstore
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.simulation import SimClock, SimEngine, SimTracer, sim_tokens
clock = SimClock()
gw = ServingGateway(clock=clock, tracer=SimTracer(clock),
                    migration_bytes_per_tick=1024)
gw.add_replica(SimEngine(max_slots=2, prefix_caching=True, block_size=4,
                         tracer=SimTracer(clock)), "pf", role="prefill")
gw.add_replica(SimEngine(max_slots=2, prefix_caching=True, block_size=4,
                         kv_store=TieredKVStore(),
                         tracer=SimTracer(clock)), "dc", role="decode")
h = gw.submit(list(range(1, 17)), 6)
for _ in range(200):
    gw.step(); clock.advance(0.25)
    if not gw.pending():
        break
assert h.status == "finished" and h.tokens == sim_tokens(h.prompt, 6)
assert gw.kvstore_snapshot()["counters"]["migrations_completed"] == 1
srv = OpsServer(); srv.attach(gw, "gw")
url = srv.start()
live = json.loads(urllib.request.urlopen(url + "/kvstore",
                                         timeout=10).read())
assert live["counters"]["migrated_bytes"] > 0
assert live["replicas"]["dc"]["store"] is not None
txt = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
assert "paddle_tpu_kvstore_migrations_completed" in txt
srv.stop()
KVEOF
then
    echo "COLLECT SMOKE FAILED: kv_store tiering / disaggregation round trip"
    exit 1
fi
# sharding-rules surface: the resolver must import clean and round-trip a
# tiny rule table, the rules digest must be LIVE in the AOT fingerprint
# environment (register -> fingerprint moves -> unregister -> restores),
# and a 2-replica weight-update-sharded train step (arXiv:2004.13336)
# must train while holding exactly half the replicated optimizer HBM
if ! JAX_PLATFORMS=cpu \
     XLA_FLAGS="--xla_force_host_platform_device_count=2" \
     python - >/dev/null 2>&1 <<'SREOF'
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec
from paddle_tpu.distributed import sharding_rules as sr
from paddle_tpu.distributed.update_sharding import (
    make_dp_update_sharded_train_step, update_sharding_rules)
from paddle_tpu.distributed.zero import per_device_state_bytes
from paddle_tpu.jit.aot import fingerprint
from paddle_tpu.optimizer import Adam
assert jax.device_count() == 2
specs = sr.ShardingRules([(r"w", ("data", None)), (r".*", None)]).resolve(
    {"w": np.zeros((8, 4), np.float32), "step": np.zeros((), np.float32)})
assert specs["w"] == PartitionSpec("data")
assert specs["step"] == PartitionSpec()
fp0 = fingerprint("smoke")
sr.register_rules(sr.ShardingRules([(r".*", ("data",))],
                                   name="smoke_probe"))
assert fingerprint("smoke") != fp0          # digest is in the env
sr.unregister_rules("smoke_probe")
assert fingerprint("smoke") == fp0
mesh = Mesh(np.array(jax.devices()), ("data",))
params = {"w": jnp.ones((8, 4), jnp.float32)}
def loss_of(p, x):
    return jnp.mean((x @ p["w"]) ** 2)
step, state = make_dp_update_sharded_train_step(
    loss_of, params, Adam(0.05), mesh)
assert per_device_state_bytes(state) == 2 * 8 * 4 * 4 // 2  # Adam m+v / R
x = jnp.ones((4, 8), jnp.float32)
state, l0 = step(state, np.float32(0.05), x)
state, l1 = step(state, np.float32(0.05), x)
assert float(l1) < float(l0)
flat = update_sharding_rules().resolve(
    {"opt": {"slots": {"flat": np.zeros((4,), np.float32)}}})
assert flat["opt"]["slots"]["flat"] == PartitionSpec("data")
SREOF
then
    echo "COLLECT SMOKE FAILED: sharding-rules / update-sharding round trip"
    exit 1
fi
# memory-ledger surface: telemetry_memory must import clean, one train
# step under an active ledger must attribute params + optimizer state,
# one census must conserve bytes (sum of pools == total, residual
# honest in `other`), the KV-store seam must resync tier bytes, and a
# LIVE /memory scrape beside /metrics must serve the snapshot with the
# memory gauge family merged in
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'MEMEOF'
import json, urllib.request
import numpy as np
import jax.numpy as jnp
import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.functional import make_train_step
from paddle_tpu.kv_store import KVPage, TieredKVStore
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.optimizer import Momentum
from paddle_tpu.telemetry import TrainMonitor
from paddle_tpu.telemetry_memory import (MemoryLedger,
                                         current_memory_ledger)
assert current_memory_ledger() is None      # off by default
paddle.seed(0)
ml = MemoryLedger()
with ml:
    # monitor= is the re-registration seam: the donated state is rebuilt
    # each step and the fresh ids re-registered after the call
    step, state = make_train_step(nn.Linear(4, 3), nn.MSELoss(),
                                  Momentum(learning_rate=0.1, momentum=0.9),
                                  monitor=TrainMonitor())
    state, _ = step(state, jax.random.key(0), np.float32(0.1),
                    [jnp.ones((8, 4))], [jnp.zeros((8, 3))])
    store = TieredKVStore()
    store.put(KVPage(b"k" * 32, (np.ones((64,), np.float32),), ["m"]))
walk = ml.census()
assert sum(walk["pools"].values()) == walk["total_bytes"], walk
assert walk["pools"]["params"] > 0 and walk["pools"]["optimizer_state"] > 0
snap = ml.memory_snapshot()
assert snap["kv_tiers"]["dram"]["bytes"] == 64 * 4, snap["kv_tiers"]
assert current_memory_ledger() is None      # symmetric teardown
srv = OpsServer()
srv.attach(ml, name="mem")
url = srv.start()
live = json.loads(urllib.request.urlopen(url + "/memory",
                                         timeout=10).read())
assert sum(p["device_bytes"] for p in live["pools"].values()) \
    == live["totals"]["device_bytes"], live["totals"]
txt = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
assert "paddle_tpu_memory_params_device_bytes" in txt, txt[:400]
assert "paddle_tpu_memory_total_device_bytes" in txt
srv.stop()
counters = [e for e in ml.to_chrome_counters() if e.get("ph") == "C"]
assert counters, "no chrome counter events"
MEMEOF
then
    echo "COLLECT SMOKE FAILED: memory-ledger round trip"
    exit 1
fi
# fleet observability plane: a FleetCollector over TWO live ops servers
# must federate both (rollups merged), flip a killed target to a labeled
# `stale` gap without corrupting the survivor's rollups, spool every
# sample durably, and RESUME the spool (seq continues, no duplicates)
# across a collector restart — the crash-survival contract
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'FLEETEOF'
import json, tempfile, urllib.request
from paddle_tpu.simulation import SimClock, SimFleetHost
from paddle_tpu.telemetry_fleet import FleetCollector
from paddle_tpu.ops_server import OpsServer

class FakeClock:
    t = 0.0
    def __call__(self):
        return self.t

clk, fclk = SimClock(), FakeClock()
spool_dir = tempfile.mkdtemp()
hosts = [SimFleetHost(clk, name=f"h{i}") for i in range(2)]
for h in hosts:
    h.submit([1, 2, 3, 4], 4)
for _ in range(12):
    clk.advance(0.05)
    for h in hosts:
        h.engine.step()
        h.ledger.record("compute", 0.05)
urls = [h.server.start() for h in hosts]
col = FleetCollector(interval_s=5.0, clock=fclk, timeout_s=5.0,
                     spool_dir=spool_dir)
for h, url in zip(hosts, urls):
    col.add_target(h.name, url)
snap = col.scrape_once()
assert snap["rollup"]["targets_ok"] == 2, snap["rollup"]
assert snap["rollup"]["fleet_ttft_p99"] is not None
# GET /fleet serves the SAME snapshot the collector holds
front = OpsServer()
front.attach(col, name="fleet")
furl = front.start()
live = json.loads(urllib.request.urlopen(furl + "/fleet",
                                         timeout=10).read())
assert live["rollup"] == json.loads(json.dumps(snap["rollup"]))
front.stop()
# kill one host: past the staleness window it is a LABELED gap and the
# survivor's rollup stands alone
hosts[1].server.stop()
fclk.t += 20.0
snap = col.scrape_once()
by = {r["target"]: r["status"] for r in snap["targets"]}
assert by == {"h0": "ok", "h1": "stale"}, by
assert snap["rollup"]["targets_stale"] == 1
seq_before = col.spool.stats()["seq"]
assert seq_before >= 6                   # 2 rounds * (targets + rollup)
records = col.spool.records()
col.stop()
hosts[0].server.stop()
# restart: the spool resumes — history intact, seq continues, no dups
col2 = FleetCollector(interval_s=5.0, clock=fclk, spool_dir=spool_dir)
assert col2.spool.records() == records
assert col2.spool.append({"kind": "probe"}) == seq_before + 1
FLEETEOF
then
    echo "COLLECT SMOKE FAILED: fleet federation round trip"
    exit 1
fi
# training resilience (ISSUE 20): a tiny train child starts an async
# two-phase checkpoint save and SIGKILLs itself mid-save; the parent must
# resume from the newest COMMITted step (the torn dir counted-skipped,
# never loaded) and the resumed loss curve must equal the uninterrupted
# oracle BIT-EXACTLY.  ckpt_fsck must agree the root is resumable.
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'RESEOF'
import os, signal, subprocess, sys, tempfile
import jax, jax.numpy as jnp, numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.functional import make_train_step
from paddle_tpu.optimizer import Momentum
from paddle_tpu.train_resilience import (CheckpointManager,
                                         ResumableIterator, TrainSupervisor)

def trainer():
    paddle.seed(0)
    layer = nn.Linear(8, 4)
    step, state = make_train_step(layer, nn.MSELoss(),
                                  Momentum(learning_rate=0.1, momentum=0.9))
    r = np.random.RandomState(1)
    batches = [([jnp.asarray(r.randn(4, 8), jnp.float32)],
                [jnp.asarray(r.randn(4, 4), jnp.float32)])
               for _ in range(8)]
    return step, state, ResumableIterator(batches)

def supervise(root, **kw):
    step, state, data = trainer()
    return TrainSupervisor(step, state, CheckpointManager(root),
                           base_key=jax.random.PRNGKey(0), lr=0.1,
                           data=data, save_every=4, backoff_s=0.0, **kw)

td = tempfile.mkdtemp()
oracle = supervise(os.path.join(td, "oracle")).run(16)
assert oracle["completed"] and len(oracle["losses"]) == 16

# the child trains 8 steps with async saves, then dies mid-async-save
root = os.path.join(td, "crash")
child = r'''
import os, signal, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import jax, jax.numpy as jnp, numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.functional import make_train_step
from paddle_tpu.optimizer import Momentum
from paddle_tpu.train_resilience import (CheckpointManager,
                                         ResumableIterator, TrainSupervisor)
paddle.seed(0)
layer = nn.Linear(8, 4)
step, state = make_train_step(layer, nn.MSELoss(),
                              Momentum(learning_rate=0.1, momentum=0.9))
r = np.random.RandomState(1)
batches = [([jnp.asarray(r.randn(4, 8), jnp.float32)],
            [jnp.asarray(r.randn(4, 4), jnp.float32)]) for _ in range(8)]
def die_mid_save(t, sup):
    if t == 8:
        sup._save(t)                      # async save now in flight
        os.kill(os.getpid(), signal.SIGKILL)
sup = TrainSupervisor(step, state, CheckpointManager(%r),
                      base_key=jax.random.PRNGKey(0), lr=0.1,
                      data=ResumableIterator(batches), save_every=4,
                      backoff_s=0.0, async_save=True,
                      on_boundary=die_mid_save)
sup.run(16)
''' % (os.getcwd(), root)
proc = subprocess.run([sys.executable, "-c", child], capture_output=True,
                      timeout=300)
assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()[-2000:]

# fsck agrees the root is resumable despite the kill
import tools.ckpt_fsck as fsck
assert fsck.main([root, "verify"]) == 0

# resume: the tail of the loss curve must equal the oracle bit-exactly
sup2 = supervise(root)
res = sup2.run(16)
assert res["completed"], res
first = res["first_step"]
assert 0 < first <= 8, first              # resumed from a committed step
assert res["losses"] == oracle["losses"][first:], "loss curve diverged"
assert res["final_loss"] == oracle["final_loss"]
RESEOF
then
    echo "COLLECT SMOKE FAILED: train-resilience crash/resume round trip"
    exit 1
fi
# tpulint gate, per-file rules + whole-program concurrency passes: any NEW
# violation vs tools/tpulint_baseline.json fails (exit 1, rule id +
# file:line printed above); a STALE baseline (violations burned down but
# baseline not shrunk) fails with exit 3 — regenerate via
# `python tools/tpulint.py --write-baseline --program paddle_tpu tools`.
# The linter is stdlib-only (no JAX import), so the full sweep costs
# seconds (<30 s is the budget tests/test_tpulint_gate.py enforces).
python tools/tpulint.py --program paddle_tpu tools
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    echo "COLLECT SMOKE FAILED: tpulint (rc=$lint_rc; 1=new violations," \
         "3=stale baseline — see docs/STATIC_ANALYSIS.md)"
    exit 1
fi
# lock-discipline sanitizer smoke: the runtime complement to --program.
# A sanitizer-instrumented threaded round trip over a real gateway's
# scrape surface must record ZERO violations, and the sanitizer itself
# must still CATCH a deliberate lock-order inversion (the detector is
# alive, not just silent).
if ! JAX_PLATFORMS=cpu python - >/dev/null 2>&1 <<'SANEOF'
import threading
from paddle_tpu.analysis import LockSanitizer
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.simulation import SimClock, SimEngine, SimTracer
san = LockSanitizer("smoke")
clock = SimClock()
gw = ServingGateway(clock=clock, tracer=SimTracer(clock))
gw.add_replica(SimEngine(max_slots=2, tracer=SimTracer(clock)), "r0")
san.instrument(gw)
stop = threading.Event()
errors = []
def scrape():
    try:
        while not stop.is_set():
            gw.gateway_snapshot(); gw.prometheus_text()
    except Exception as e:
        errors.append(e)
t = threading.Thread(target=scrape)
t.start()
h = gw.submit([1, 2, 3], 8)
for _ in range(200):
    gw.step(); clock.advance(0.25)
    if not gw.pending():
        break
stop.set(); t.join()
assert not errors, errors
assert h.status == "finished"
san.assert_clean()
bad = LockSanitizer("canary")
a = bad.wrap(threading.Lock(), "a")
b = bad.wrap(threading.Lock(), "b")
with a:
    with b: pass
with b:
    with a: pass
assert any(v["kind"] == "lock-order-inversion" for v in bad.violations())
SANEOF
then
    echo "COLLECT SMOKE FAILED: lock-sanitizer threaded smoke"
    exit 1
fi
echo "collect smoke OK"
