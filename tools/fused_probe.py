"""Profile-driven fused-kernel decision data (VERDICT r2 #9).

Measures, on the current backend, whether XLA already fuses the patterns the
reference hand-fuses (fused_layernorm_residual_dropout_bias.h,
distributed_fused_lamb): if the jitted composite runs at HBM-bandwidth
roofline, a Pallas kernel can't win and the justified decision is "delegate
to XLA fusion".

Prints one JSON line per pattern:
  {"pattern": ..., "ms": ..., "gbps": ..., "roofline_frac": ...}

roofline_frac = achieved bytes/s over the chip's HBM peak (v5e: 819 GB/s).
>0.6 → XLA is already memory-bound on the fused region; no kernel needed.

Run on TPU:  PYTHONPATH=/root/repo:/root/.axon_site \
             tools/tpu_guard.sh python tools/fused_probe.py
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

HBM_PEAK = {"v5e": 819e9, "v5p": 2765e9, "v4": 1228e9}


def _sync(x):
    return np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0:1])


def timeit(fn, *args, iters=50):
    fn = jax.jit(fn)
    out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def ln_residual_dropout(B=16, L=1024, H=768, dtype=jnp.bfloat16):
    """y = LayerNorm(x + dropout(residual)) — the reference's fused op."""
    r = np.random.RandomState(0)
    x = jnp.asarray(r.standard_normal((B, L, H)), dtype)
    res = jnp.asarray(r.standard_normal((B, L, H)), dtype)
    g = jnp.ones((H,), jnp.float32)
    b = jnp.zeros((H,), jnp.float32)
    key = jax.random.key(0)

    def f(x, res, g, b):
        keep = jax.random.bernoulli(key, 0.9, res.shape)
        h = x + jnp.where(keep, res / 0.9, 0).astype(x.dtype)
        m = h.mean(-1, keepdims=True).astype(jnp.float32)
        v = jnp.var(h.astype(jnp.float32), axis=-1, keepdims=True)
        return ((h - m) * jax.lax.rsqrt(v + 1e-5) * g + b).astype(x.dtype)

    dt = timeit(f, x, res, g, b)
    nbytes = (x.size + res.size) * x.dtype.itemsize * 2  # r+w of both streams
    return dt, nbytes


def adamw_update(n_params=124 * 10**6, dtype=jnp.float32):
    """Single fused AdamW step over one flat 124M buffer (gpt2s-sized)."""
    r = np.random.RandomState(0)
    p = jnp.asarray(r.standard_normal(n_params // 4), dtype)  # 31M fits CPU too
    g = jnp.asarray(r.standard_normal(p.size), dtype)
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)

    def f(p, g, m, v):
        m2 = 0.9 * m + 0.1 * g
        v2 = 0.999 * v + 0.001 * g * g
        up = m2 / (jnp.sqrt(v2) + 1e-8) + 0.01 * p
        return p - 3e-4 * up, m2, v2

    dt = timeit(f, p, g, m, v)
    nbytes = p.size * p.dtype.itemsize * 7  # r: p,g,m,v  w: p,m,v
    return dt, nbytes


def softmax_xent_block(B=16, L=1024, V=50304):
    """LM-head CE region: logits -> loss (the fused-CE bwd feed)."""
    r = np.random.RandomState(0)
    h = jnp.asarray(r.standard_normal((B * L, 768)), jnp.bfloat16)
    w = jnp.asarray(r.standard_normal((768, V)), jnp.bfloat16)
    y = jnp.asarray(r.randint(0, V, (B * L,)))

    def f(h, w, y):
        logits = (h @ w).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, y[:, None], axis=1)[:, 0]
        return (lse - gold).mean()

    dt = timeit(f, h, w, y, iters=10)
    nbytes = (h.size + w.size) * 2 + B * L * V * 4
    return dt, nbytes


def main():
    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = next((v for k, v in HBM_PEAK.items() if k in kind), None)
    for name, probe in [("ln_residual_dropout", ln_residual_dropout),
                        ("adamw_update", adamw_update),
                        ("softmax_xent_block", softmax_xent_block)]:
        dt, nbytes = probe()
        gbps = nbytes / dt / 1e9
        print(json.dumps({
            "pattern": name, "ms": round(dt * 1e3, 3),
            "gbps": round(gbps, 1),
            "roofline_frac": round(gbps * 1e9 / peak, 3) if peak else None,
            "backend": kind}), flush=True)


if __name__ == "__main__":
    main()
