#!/bin/bash
# Claim-hygiene wrapper for TPU-touching commands (VERDICT r2: a
# SIGTERM-killed profiler wedged the single-chip tunnel claim for ~8h).
#
# Only ONE process may hold the TPU claim; a claim-holder that dies to a
# signal leaves the claim poisoned until expiry.  This wrapper:
#   * ignores SIGTERM/SIGINT/SIGHUP itself, and
#   * runs the command in its own session (setsid), so group-targeted
#     signals (timeouts, Ctrl-C, driver cleanup) never reach the child —
#     the claim-holder always exits on its own and releases cleanly.
#
# Usage: tools/tpu_guard.sh python bench.py --config all
#        TPU_GUARD_LOG=/tmp/bench.log tools/tpu_guard.sh python bench.py
#
# There is deliberately NO timeout here: a hung claim-holder must be left
# to finish or error out on its own (killing it costs hours, waiting
# costs minutes).  Bound the *work*, not the process.

trap '' TERM INT HUP

if [ -n "$TPU_GUARD_LOG" ]; then
    setsid "$@" >"$TPU_GUARD_LOG" 2>&1 &
else
    setsid "$@" &
fi
child=$!
# wait is restartable; loop in case a non-fatal signal interrupts it
while kill -0 "$child" 2>/dev/null; do
    wait "$child"
    rc=$?
done
exit "${rc:-1}"
