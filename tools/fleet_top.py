#!/usr/bin/env python
"""Live fleet dashboard: a top-like terminal view over the fleet
observability plane (``paddle_tpu/telemetry_fleet.py``).

Two modes, one renderer:

- ``--url``      read ``GET /fleet`` from an ops server a FleetCollector
  is attached to (the rank-0 collector of a multi-host run) and render
  its snapshot — the dashboard and ``/fleet`` show the SAME object;
- ``--targets``  run a local collector right here over the named ops
  endpoints (``name=url`` pairs) and render its snapshots.

Every refresh paints a fleet header (targets up/stale/down, global
goodput, fleet MFU, merged TTFT p99, tokens/s, straggler skew, firing
fleet alerts) over one row per target: status, scrape age, goodput,
TTFT p50/p99, tokens/s, occupancy, queued, open breakers, brownout
rung.  A stale or down target stays VISIBLE with its age and last
error — a labeled gap, the same rule the rollups follow.

Examples::

    python tools/fleet_top.py --url http://127.0.0.1:9100
    python tools/fleet_top.py \\
        --targets host0=http://10.0.0.1:9100,host1=http://10.0.0.2:9100
    python tools/fleet_top.py --url http://127.0.0.1:9100 --once  # one frame

``--once`` renders a single frame and exits (scripts / tests);
``--interval`` paces the refresh loop.
"""

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

#: column spec: (header, width, row-key or callable)
_COLUMNS = (
    ("TARGET", 14, "target"),
    ("STATUS", 7, "status"),
    ("AGE", 7, "age_s"),
    ("GOODPUT", 8, "goodput"),
    ("TTFT50", 8, "ttft_p50"),
    ("TTFT99", 8, "ttft_p99"),
    ("TOK/S", 8, "tokens_per_s"),
    ("OCC", 6, "occupancy"),
    ("QUEUED", 6, "queued"),
    ("BRKRS", 6, "breakers_open"),
    ("BROWN", 5, "brownout_level"),
)


def _fmt(v, width):
    if v is None:
        s = "-"
    elif isinstance(v, float):
        s = f"{v:.3f}" if abs(v) < 100 else f"{v:.1f}"
    elif isinstance(v, (list, tuple)):
        s = str(len(v)) if v else "0"
    else:
        s = str(v)
    return s[:width].rjust(width)


def render_fleet(snap) -> str:
    """One dashboard frame from a fleet snapshot — the SAME dict
    ``GET /fleet`` serves and ``FleetCollector.fleet_snapshot()``
    returns, so every surface renders one object."""
    roll = snap.get("rollup") or {}
    slo = snap.get("slo") or {}
    lines = []
    up = roll.get("targets_ok", 0)
    lines.append(
        f"fleet: {up}/{roll.get('targets', 0)} up"
        f"  ({roll.get('targets_stale', 0)} stale,"
        f" {roll.get('targets_down', 0)} down)"
        f"   scrape #{snap.get('scrapes', 0)}"
        f"   t={snap.get('now') if snap.get('now') is not None else '-'}")
    lines.append(
        "goodput %s   mfu %s   ttft_p99 %s   tok/s %s   skew %s"
        "   alerts %s" % (
            _fmt(roll.get("goodput_global"), 7).strip(),
            _fmt(roll.get("fleet_mfu"), 7).strip(),
            _fmt(roll.get("fleet_ttft_p99"), 8).strip(),
            _fmt(roll.get("tokens_per_s"), 8).strip(),
            _fmt(roll.get("straggler_skew"), 6).strip(),
            slo.get("alerts_firing", 0) if slo else "-"))
    spool = snap.get("spool")
    if spool:
        lines.append(f"spool: {spool['segments']} segment(s), "
                     f"{spool['bytes']} bytes, seq {spool['seq']} "
                     f"@ {spool['directory']}")
    lines.append("")
    lines.append(" ".join(h.rjust(w) for h, w, _k in _COLUMNS))
    for row in snap.get("targets", []):
        lines.append(" ".join(_fmt(row.get(k), w) for _h, w, k in _COLUMNS))
        if row.get("status") != "ok" and row.get("error"):
            lines.append(f"    !! {row['error'][:120]}")
    if not snap.get("targets"):
        lines.append("  (no targets scraped yet)")
    return "\n".join(lines)


def _fetch_fleet(url: str, timeout_s: float):
    with urllib.request.urlopen(url.rstrip("/") + "/fleet",
                                timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="top-like live dashboard over the fleet "
                    "observability plane (GET /fleet, or a local "
                    "collector over --targets)")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--url",
                      help="ops-server base URL whose /fleet to render "
                           "(a FleetCollector must be attached there)")
    mode.add_argument("--targets",
                      help="comma-separated name=url ops endpoints to "
                           "scrape with a LOCAL collector")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh/scrape seconds")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-request timeout seconds")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--no-clear", action="store_true",
                    help="append frames instead of repainting the screen")
    args = ap.parse_args(argv)

    collector = None
    if args.targets:
        from paddle_tpu.telemetry_fleet import FleetCollector
        collector = FleetCollector(interval_s=args.interval,
                                   timeout_s=args.timeout)
        for pair in args.targets.split(","):
            name, _, url = pair.partition("=")
            if not name or not url:
                ap.error(f"bad --targets entry {pair!r} (want name=url)")
            collector.add_target(name.strip(), url.strip())

    try:
        while True:
            if collector is not None:
                snap = collector.scrape_once()
            else:
                try:
                    snap = _fetch_fleet(args.url, args.timeout)
                except Exception as e:  # noqa: BLE001 — a dashboard must
                    # outlive its collector's restarts
                    snap = {"targets": [], "rollup": {},
                            "error": repr(e)}
            frame = render_fleet(snap)
            if snap.get("error"):
                frame += f"\n  !! fleet endpoint unreachable: " \
                         f"{snap['error']}"
            if args.no_clear or args.once:
                print(frame)
            else:
                print("\x1b[2J\x1b[H" + frame, flush=True)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
