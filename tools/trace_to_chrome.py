"""Convert a captured profile to Chrome tracing JSON (≙ reference
tools/timeline.py, which converts profiler protos for chrome://tracing).

Usage:
  python tools/trace_to_chrome.py /tmp/profile_dir -o trace.json
  python tools/trace_to_chrome.py /tmp/profile_dir -o trace.json \
      --engine-trace gateway.jsonl --engine-trace replica_a.jsonl \
      --engine-trace replica_b.jsonl --ledger goodput.json

The input is a directory written by ``paddle_tpu.profiler`` /
``jax.profiler.trace`` (contains ``**/*.xplane.pb``).  ``--engine-trace``
(repeatable) merges serving-telemetry dumps (``Tracer.dump_jsonl`` JSONL
or ``Tracer.write_chrome_trace`` JSON) into the same output, so
scheduler ticks / request spans and XPlane device traces land in ONE
file; with more than one dump each gets its own chrome process row
(``paddle_tpu.serving#<i>`` — replica rids collide otherwise), and the
gateway↔engine flow arrows (``ph: "s"``/``"f"`` events keyed by the
dispatch span id, carrying the request's trace_id) still link rows
ACROSS files, so a request's journey through the fleet draws as arrows
in Perfetto.  ``--ledger`` merges a goodput-ledger dump
(``RunLedger.dump_json``) as a stacked counter track — cumulative
seconds per wall-clock bucket next to the event rows.  ``--memory``
merges a memory-ledger dump (``MemoryLedger.dump_json``) as per-pool
byte counter tracks (one per space: device HBM and host bytes stack
separately), so a watermark crossing lines up against the tick/compile
spans that caused it.  Open the output in chrome://tracing or
https://ui.perfetto.dev.
"""

import argparse
import glob
import json
import os
import sys


def _load_engine_trace(path):
    """Engine-telemetry file → chrome-trace dict.  Accepts the Tracer's
    JSONL event dump or an already-converted chrome JSON.  A multi-line
    JSONL fails the whole-file parse; a SINGLE-line JSONL parses as a dict
    but carries the tracer's ``kind`` field, not ``traceEvents`` — both
    route to the JSONL converter."""
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict) and "kind" not in data:
            return data
        if isinstance(data, list):
            return {"traceEvents": data}
    except json.JSONDecodeError:          # multi-line JSONL
        pass
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    from paddle_tpu.telemetry import chrome_trace_from_jsonl
    return chrome_trace_from_jsonl(path)


def _load_ledger(path):
    """RunLedger ``dump_json`` file → chrome-trace dict of counter events
    (the goodput buckets as a stacked counter track)."""
    with open(path) as f:
        data = json.load(f)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    from paddle_tpu.telemetry_ledger import chrome_counters_from_dump
    return {"traceEvents": chrome_counters_from_dump(data)}


def _load_memory(path):
    """MemoryLedger ``dump_json`` file → chrome-trace dict of counter
    events (per-pool byte gauges, one stacked track per space)."""
    with open(path) as f:
        data = json.load(f)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    from paddle_tpu.telemetry_memory import chrome_counters_from_memory_dump
    return {"traceEvents": chrome_counters_from_memory_dump(data)}


def _merge(device_payload, engine):
    """Append the engine trace's events to the device trace JSON."""
    data = json.loads(device_payload)
    if isinstance(data, list):
        data = {"traceEvents": data}
    data.setdefault("traceEvents", []).extend(engine.get("traceEvents", []))
    return json.dumps(data)


def _suffix_pids(trace, suffix):
    """Disambiguate one dump's chrome process ids (``pid#suffix``) so N
    merged replica dumps land on N separate rows — their per-request
    ``tid`` values (``req:<rid>``) collide otherwise.  Flow events keep
    their ids untouched: flows link by id, not pid, which is exactly how
    gateway→engine arrows survive the merge."""
    for ev in trace.get("traceEvents", []):
        if "pid" in ev:
            ev["pid"] = f"{ev['pid']}#{suffix}"
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev.setdefault("args", {})["name"] = ev["pid"]
    return trace


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logdir", help="profiler output dir (contains *.xplane.pb)")
    ap.add_argument("-o", "--output", default="trace.json")
    ap.add_argument("--engine-trace", action="append", default=None,
                    metavar="DUMP",
                    help="serving-telemetry dump (Tracer.dump_jsonl JSONL "
                         "or chrome JSON) to merge into the output; "
                         "repeatable — multiple dumps (gateway + N "
                         "replicas) each get their own process row, with "
                         "trace-id flow events linking them")
    ap.add_argument("--ledger", default=None,
                    help="goodput-ledger dump (RunLedger.dump_json) to "
                         "merge as a stacked counter track")
    ap.add_argument("--memory", default=None,
                    help="memory-ledger dump (MemoryLedger.dump_json) to "
                         "merge as per-pool byte counter tracks")
    args = ap.parse_args(argv)

    paths = glob.glob(os.path.join(args.logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        print(f"no *.xplane.pb under {args.logdir}", file=sys.stderr)
        return 1
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:
        print("trace_to_chrome: the 'xprof' package is not installed — the "
              "XPlane -> trace_viewer conversion needs it.\n"
              "Install it with:  pip install xprof   (ships with "
              "tensorboard-plugin-profile)\n"
              f"original error: {e}", file=sys.stderr)
        return 1

    data, _mime = rtd.xspace_to_tool_data(paths, "trace_viewer", {})
    payload = data if isinstance(data, (str, bytes)) else str(data)
    engine_traces = args.engine_trace or []
    for i, path in enumerate(engine_traces):
        if isinstance(payload, bytes):
            payload = payload.decode("utf-8")
        trace = _load_engine_trace(path)
        if len(engine_traces) > 1:
            trace = _suffix_pids(trace, i)
        payload = _merge(payload, trace)
    if args.ledger is not None:
        if isinstance(payload, bytes):
            payload = payload.decode("utf-8")
        payload = _merge(payload, _load_ledger(args.ledger))
    if args.memory is not None:
        if isinstance(payload, bytes):
            payload = payload.decode("utf-8")
        payload = _merge(payload, _load_memory(args.memory))
    mode = "wb" if isinstance(payload, bytes) else "w"
    with open(args.output, mode) as f:
        f.write(payload)
    print(f"wrote {args.output} ({len(payload)} bytes) — open in "
          f"chrome://tracing or perfetto")
    return 0


if __name__ == "__main__":
    sys.exit(main())
