"""Convert a captured profile to Chrome tracing JSON (≙ reference
tools/timeline.py, which converts profiler protos for chrome://tracing).

Usage:
  python tools/trace_to_chrome.py /tmp/profile_dir -o trace.json

The input is a directory written by ``paddle_tpu.profiler`` /
``jax.profiler.trace`` (contains ``**/*.xplane.pb``). Open the output in
chrome://tracing or https://ui.perfetto.dev.
"""

import argparse
import glob
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir", help="profiler output dir (contains *.xplane.pb)")
    ap.add_argument("-o", "--output", default="trace.json")
    args = ap.parse_args()

    paths = glob.glob(os.path.join(args.logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        print(f"no *.xplane.pb under {args.logdir}", file=sys.stderr)
        return 1
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from xprof.convert import raw_to_tool_data as rtd

    data, _mime = rtd.xspace_to_tool_data(paths, "trace_viewer", {})
    payload = data if isinstance(data, (str, bytes)) else str(data)
    mode = "wb" if isinstance(payload, bytes) else "w"
    with open(args.output, mode) as f:
        f.write(payload)
    print(f"wrote {args.output} ({len(payload)} bytes) — open in "
          f"chrome://tracing or perfetto")
    return 0


if __name__ == "__main__":
    sys.exit(main())
