"""Bucketized BERT serving: ragged request lengths, a BOUNDED set of
compiled programs, exact results (padding is masked out of attention and
pooling).

Run (CPU):  JAX_PLATFORMS=cpu python examples/serve_bucketed.py

This is the TPU-native replacement for the reference's LoD/variable-length
handling (fluid/lod_tensor.py): XLA needs static shapes, so a serving loop
pads every request up to the smallest admissible bucket
(paddle.jit.bucketize) and passes the true length in as a traced scalar —
lengths vary freely per request with zero recompiles within a bucket.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

import paddle_tpu as paddle


def main():
    import jax.numpy as jnp
    from paddle_tpu.jit import bucketize, length_mask
    from paddle_tpu.models.bert import BertConfig, BertModel

    paddle.seed(0)
    cfg = BertConfig(vocab_size=500, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=128,
                     max_position_embeddings=64, compute_dtype="float32",
                     use_flash_attention=False)
    model = BertModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}

    def embed(ids, length=None):
        """Mean-pooled sentence embedding over REAL tokens only; padding is
        masked out of both attention and the pool."""
        B, L = ids.shape
        if length is None:
            length = jnp.asarray(L, jnp.int32)
        m = length_mask(length, L)                        # (L,) 1=real 0=pad
        attn = jnp.broadcast_to((m - 1.0) * 1e30, (B, 1, 1, L))
        h = model.encode(params, ids, attn_mask=attn)
        pooled = jnp.sum(h * m[None, :, None], axis=1) / length
        return pooled

    serve = bucketize(embed, buckets=(16, 32), axis=1, length_arg="length")

    rng = np.random.RandomState(0)
    requests = [rng.randint(0, 500, (1, L)) for L in (5, 11, 16, 23, 9, 32)]
    outs = [np.asarray(serve(jnp.asarray(ids))) for ids in requests]

    # exactness: bucketed result == direct unpadded run, per request
    for ids, out in zip(requests, outs):
        direct = np.asarray(embed(jnp.asarray(ids)))
        np.testing.assert_allclose(out, direct, rtol=2e-5, atol=2e-5)

    print("served", len(requests), "ragged requests over buckets",
          serve.buckets, "- results exact vs unpadded runs")
    print("SERVE_OK")


if __name__ == "__main__":
    main()
