"""MNIST LeNet with the hapi Model API (≙ reference quick-start).

Run (CPU):  JAX_PLATFORMS=cpu python examples/train_mnist.py
Run (TPU):  python examples/train_mnist.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.datasets import MNIST


class Synth(paddle.io.Dataset):
    """Synthetic digits with MNIST shapes (this image has no network egress).
    Pass --images/--labels to train on a local IDX copy instead."""

    def __init__(self, n):
        rng = np.random.RandomState(0)
        self.x = rng.standard_normal((n, 1, 28, 28)).astype("float32")
        self.y = rng.randint(0, 10, (n,)).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", help="path to a local train-images IDX file")
    ap.add_argument("--labels", help="path to a local train-labels IDX file")
    args = ap.parse_args()

    paddle.seed(0)
    if args.images and args.labels:
        train_ds = MNIST(image_path=args.images, label_path=args.labels,
                         mode="train")
        test_ds = train_ds
    else:
        train_ds, test_ds = Synth(2048), Synth(512)

    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(1e-3, parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), Accuracy())
    model.fit(train_ds, epochs=2, batch_size=64, verbose=1)
    print(model.evaluate(test_ds, batch_size=64, verbose=0))


if __name__ == "__main__":
    main()
