"""Text generation with the KV-cache decode loop (≙ reference-ecosystem
generation_utils: greedy, temperature/top-k/top-p sampling, beam search).

Run (CPU):  JAX_PLATFORMS=cpu python examples/generate_gpt.py
Run (TPU):  python examples/generate_gpt.py

Loads a torch/HF GPT-2 checkpoint when --hf_dir points at one (via
models/convert.py); otherwise demonstrates on a small randomly initialized
model.  Prompt lengths are bucketized so a serving loop compiles a bounded
set of XLA programs.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

import paddle_tpu as paddle


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf_dir", help="local HuggingFace GPT-2 checkpoint dir")
    ap.add_argument("--max_new_tokens", type=int, default=16)
    ap.add_argument("--num_beams", type=int, default=0,
                    help=">0 switches to beam search")
    ap.add_argument("--top_p", type=float, default=None)
    ap.add_argument("--temperature", type=float, default=1.0)
    args = ap.parse_args()

    import jax
    from paddle_tpu.models.gpt import GPTConfig, GPTModel

    paddle.seed(0)
    if args.hf_dir:
        import transformers
        from paddle_tpu.models.convert import (gpt2_config_from_torch,
                                               gpt2_params_from_torch)
        hf = transformers.GPT2LMHeadModel.from_pretrained(args.hf_dir)
        cfg = gpt2_config_from_torch(hf.config, compute_dtype="float32")
        model = GPTModel(cfg)
        params = {k: paddle.to_tensor(v)._data
                  for k, v in gpt2_params_from_torch(hf).items()}
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}

    rs = np.random.RandomState(0)
    prompt = rs.randint(0, cfg.vocab_size, (2, 8))

    greedy = model.generate(params, prompt, args.max_new_tokens)
    print("greedy     :", np.asarray(greedy)[0].tolist())

    sampled = model.generate(params, prompt, args.max_new_tokens,
                             greedy=False, temperature=args.temperature,
                             top_k=40, top_p=args.top_p,
                             key=jax.random.key(0))
    print("sampled    :", np.asarray(sampled)[0].tolist())

    if args.num_beams > 0:
        seq, score = model.generate_beam(params, prompt,
                                         args.max_new_tokens,
                                         num_beams=args.num_beams)
        print(f"beam (k={args.num_beams}):", np.asarray(seq)[0].tolist(),
              "score", float(score[0]))

    # ragged serving: left-pad prompts of different lengths into one bucket;
    # pad lengths are traced data, so BOTH requests share ONE program
    short = rs.randint(0, cfg.vocab_size, (1, 3))
    padded = np.concatenate([np.zeros((1, 5), np.int64), short], axis=1)
    mask = np.concatenate([np.zeros((1, 5), np.int32),
                           np.ones((1, 3), np.int32)], axis=1)
    ragged = model.generate(params, padded, args.max_new_tokens,
                            prompt_mask=mask)
    exact = model.generate(params, short, args.max_new_tokens)
    assert np.array_equal(np.asarray(ragged), np.asarray(exact)), \
        "padded serving must be exact"
    print("ragged     :", np.asarray(ragged)[0].tolist(),
          "(== unpadded run)")

    # speculative decoding: a cheap draft proposes, the target verifies in
    # one chunk per round — greedy mode is bit-lossless
    paddle.seed(1)
    draft_cfg = GPTConfig(vocab_size=cfg.vocab_size, hidden_size=32,
                          num_layers=1, num_attention_heads=4,
                          max_position_embeddings=cfg.max_position_embeddings,
                          compute_dtype="float32")
    draft = GPTModel(draft_cfg)
    dparams = {n: p._data for n, p in draft.named_parameters()}
    spec, rounds = model.generate_speculative(
        params, prompt, args.max_new_tokens, draft, dparams, draft_k=3,
        return_rounds=True)
    assert np.array_equal(np.asarray(spec), np.asarray(greedy)), \
        "speculative decoding must be lossless"
    print(f"speculative: lossless in {int(rounds)} rounds "
          f"({args.max_new_tokens} tokens, draft_k=3)")
    print("GENERATION_OK")


if __name__ == "__main__":
    main()
