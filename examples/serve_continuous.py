"""Continuous-batching serving: requests join and leave a RUNNING decode
batch (paddle_tpu/serving.py — no reference counterpart; generation_utils
admits/retires whole batches).

Run (CPU):  JAX_PLATFORMS=cpu python examples/serve_continuous.py
Run (TPU):  python examples/serve_continuous.py   [--int8] [--mp N]

Shows the full serving story on one tiny model: staggered request budgets,
mid-flight admission, EOS retirement, the chunked host-sync knob, and the
int8 KV cache / tensor-parallel options.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

import paddle_tpu as paddle


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--int8", action="store_true",
                    help="store the KV cache as int8 (half the HBM traffic)")
    ap.add_argument("--mp", type=int, default=1,
                    help="tensor-parallel degree (needs that many devices)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--ticks_per_sync", type=int, default=4)
    ap.add_argument("--speculative", action="store_true",
                    help="speculative engine (1-layer draft): lossless, "
                         "fewer rounds")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache (block pool + tables): lazy HBM, "
                         "preemption, prefix caching")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    from paddle_tpu.serving import ContinuousBatchingEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=256,
                    compute_dtype="float32",
                    kv_cache_dtype="int8" if args.int8 else None)
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}

    mesh = None
    if args.mp > 1:
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:args.mp]), ("model",))

    if args.paged and args.mp > 1:
        raise SystemExit("--paged is single-mesh; drop --mp")
    if args.speculative:
        dcfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=1,
                         num_attention_heads=4, max_position_embeddings=256,
                         compute_dtype="float32")
        draft = GPTModel(dcfg)
        dparams = {n: p._data for n, p in draft.named_parameters()}
        if args.paged:
            from paddle_tpu.serving import PagedSpeculativeBatchingEngine
            eng = PagedSpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=args.slots,
                max_len=128, draft_k=3, prompt_buckets=[16, 32],
                block_size=16)
        else:
            from paddle_tpu.serving import SpeculativeBatchingEngine
            eng = SpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=args.slots,
                max_len=128, draft_k=3, prompt_buckets=[16, 32],
                mesh=mesh)
    elif args.paged:
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        # per-request sampling + prefix caching ride along: requests may
        # carry their own knobs, and repeated prompt prefixes reuse blocks
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=args.slots, max_len=128,
            block_size=16, prompt_buckets=[16, 32],
            ticks_per_sync=args.ticks_per_sync,
            enable_prefix_cache=True, per_request_sampling=True)
    else:
        eng = ContinuousBatchingEngine(
            model, params, max_slots=args.slots, max_len=128,
            prompt_buckets=[16, 32], ticks_per_sync=args.ticks_per_sync,
            mesh=mesh)

    rng = np.random.RandomState(0)
    t0 = time.time()
    # first wave: four requests with staggered budgets
    wave1 = [eng.add_request(list(rng.randint(1, 512, rng.randint(4, 17))),
                             int(n)) for n in (8, 16, 24, 32)]
    for _ in range(3):
        eng.step()
    # a second wave joins while the first is mid-decode
    perreq = args.paged and not args.speculative
    kw2 = [dict(repetition_penalty=1.5), dict()] if perreq else [{}, {}]
    wave2 = [eng.add_request(list(rng.randint(1, 512, rng.randint(4, 33))),
                             int(n), **k) for n, k in zip((12, 20), kw2)]
    out = eng.run_to_completion(max_ticks=10000)

    total = sum(len(v) for v in out.values())
    dt = time.time() - t0
    for rid in wave1 + wave2:
        print(f"request {rid}: {len(out[rid])} tokens, "
              f"first 8 = {out[rid][:8]}")
    extra = (f", spec rounds={eng.rounds}" if args.speculative else "")
    if args.paged:
        extra += f", blocks hw={eng.blocks_high_water}"
        if not args.speculative:
            extra += f", prefix hits={eng.prefix_hits}"
    m = eng.metrics()
    print(f"\n{len(out)} requests, {total} tokens in {dt:.2f}s "
          f"({total / dt:.0f} tok/s) — slots={args.slots}, "
          f"ticks_per_sync={args.ticks_per_sync}, "
          f"kv={'int8' if args.int8 else 'fp'}, mp={args.mp}{extra}; "
          f"mean TTFT {m['mean_ttft_s'] * 1e3:.0f}ms, "
          f"mean latency {m['mean_latency_s'] * 1e3:.0f}ms")


if __name__ == "__main__":
    main()
