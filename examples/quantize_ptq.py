"""Post-training int8 quantization of a conv classifier, end to end.

Trains a small conv net on synthetic data (eager), calibrates + converts it
to int8 (per-channel conv scales, int8 MXU matmul path on TPU), and compares
float vs int8 eval accuracy.

Run on CPU:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
                 python examples/quantize_ptq.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import PostTrainingQuantization


def make_data(n, rng):
    x = rng.randn(n, 3, 16, 16).astype("float32")
    # class = which quadrant carries the strongest mean signal
    y = rng.randint(0, 4, n)
    for i, c in enumerate(y):
        h, w = divmod(int(c), 2)
        x[i, :, h * 8:(h + 1) * 8, w * 8:(w + 1) * 8] += 1.5
    return x, y.astype("int64")


def accuracy(model, x, y):
    model.eval()
    preds = np.asarray(model(paddle.to_tensor(x))._data).argmax(-1)
    return float((preds == y).mean())


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    xtr, ytr = make_data(512, rng)
    xte, yte = make_data(256, rng)

    model = nn.Sequential(
        nn.Conv2D(3, 16, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2),
        nn.Conv2D(16, 32, 3, padding=1), nn.ReLU(),
        # pool to 2x2, NOT 1x1: the label is *which quadrant* lights up,
        # so the head needs spatial information
        nn.AdaptiveAvgPool2D(2), nn.Flatten(), nn.Linear(32 * 4, 4))
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())

    model.train()
    for epoch in range(4):
        for i in range(0, len(xtr), 64):
            xb = paddle.to_tensor(xtr[i:i + 64])
            yb = paddle.to_tensor(ytr[i:i + 64])
            loss = nn.functional.cross_entropy(model(xb), yb)
            loss.backward()
            opt.step(); opt.clear_grad()
        print(f"epoch {epoch}: loss {float(loss.numpy()):.4f}")

    acc_fp32 = accuracy(model, xte, yte)

    calib = [paddle.to_tensor(xtr[i:i + 64]) for i in range(0, 256, 64)]
    qmodel = PostTrainingQuantization(model).calibrate(calib).convert()
    acc_int8 = accuracy(qmodel, xte, yte)

    print(f"fp32 accuracy: {acc_fp32:.3f}")
    print(f"int8 accuracy: {acc_int8:.3f}")
    assert acc_int8 > acc_fp32 - 0.03, "int8 conversion lost >3% accuracy"


if __name__ == "__main__":
    main()
