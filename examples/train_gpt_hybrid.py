"""GPT pretraining with hybrid parallelism (dp x mp x pp) over a device mesh.

Single chip:      python examples/train_gpt_hybrid.py
8-device CPU sim: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                  JAX_PLATFORMS=cpu python examples/train_gpt_hybrid.py --dp 2 --mp 2 --pp 2

The same script scales to a pod slice: degrees multiply up to jax.device_count(),
GSPMD inserts the collectives (≙ fleet.distributed_model + HybridParallelOptimizer).
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
from paddle_tpu.nn.clip import ClipGradByGlobalNorm
from paddle_tpu.optimizer import AdamW


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--zero", type=int, default=0, help="ZeRO stage 0-3")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": args.dp, "mp_degree": args.mp,
                               "pp_degree": args.pp, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    # bf16 collectives crash XLA's CPU AllReducePromotion pass (simulator
    # only) — use fp32 on the virtual mesh, bf16 on real TPU
    dtype = "bfloat16" if jax.default_backend() == "tpu" else "float32"
    cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                    num_attention_heads=8, max_position_embeddings=256,
                    compute_dtype=dtype)
    model = GPTModel(cfg)
    opt = AdamW(3e-4, weight_decay=0.01, grad_clip=ClipGradByGlobalNorm(1.0))
    step, state = make_gpt_train_step(model, opt, hcg,
                                      n_microbatches=max(2 * args.pp, 2),
                                      remat=True, zero_stage=args.zero)

    B, L = 8 * max(args.dp, 1), 128
    rng = np.random.RandomState(0)
    for i in range(args.steps):
        x = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
        y = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
        state, loss = step(state, jax.random.key(i), np.float32(3e-4), x, y)
        print(f"step {i}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
