"""BERT masked-LM training on synthetic data (fused flash-attention path).

CPU: JAX_PLATFORMS=cpu python examples/finetune_bert.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.models.bert import BertConfig, BertModel, make_bert_train_step
from paddle_tpu.optimizer import AdamW


def main():
    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    cfg = BertConfig(vocab_size=2048, hidden_size=128, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     compute_dtype="float32")
    model = BertModel(cfg)
    step, state = make_bert_train_step(model, AdamW(1e-4, weight_decay=0.01), hcg)

    rng = np.random.RandomState(0)
    B, L = 8, 64
    for i in range(10):
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
        mlm = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
        nsp = jnp.asarray(rng.randint(0, 2, (B,)))
        state, loss = step(state, np.float32(1e-4), ids, mlm, nsp)
        print(f"step {i}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
