"""LocalSGD + Lars tests (SURVEY row 46 meta-optimizer equivalents).

Oracles:
- LocalSGD k=1 with SGD must equal plain every-step data parallelism
  (averaging linear updates commutes with averaging gradients).
- LocalSGD k=3 must equal a per-worker numpy simulation with periodic
  parameter averaging (the reference's program-rewrite semantics,
  localsgd_optimizer.py:26).
- Lars must match the lars_momentum_op.h update formula recomputed in numpy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.distributed.localsgd import make_localsgd_train_step
from paddle_tpu.optimizer import SGD, Lars

needs4 = pytest.mark.skipif(jax.device_count() < 4, reason="needs 4 devices")


def _params(seed=0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.standard_normal((6, 3)).astype(np.float32) * 0.3),
            "b": jnp.zeros((3,), jnp.float32)}


def _loss_of(params, x, y):
    logits = x @ params["w"] + params["b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def _data(seed=1, B=16):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.standard_normal((B, 6)).astype(np.float32)),
            jnp.asarray(r.randint(0, 3, B)))


@needs4
class TestLocalSGD:
    def test_k1_equals_dp(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        x, y = _data()
        lr = 0.1

        step, state = make_localsgd_train_step(_loss_of, _params(), SGD(lr),
                                               mesh, k_steps=1)
        # plain DP oracle: global-batch gradient step in numpy
        p = {k: np.asarray(v) for k, v in _params().items()}
        losses_dp, losses_ls = [], []
        for i in range(4):
            state, loss = step(state, np.float32(lr), x, y)
            losses_ls.append(float(loss))
            g = jax.grad(_loss_of)({k: jnp.asarray(v) for k, v in p.items()},
                                   x, y)
            losses_dp.append(float(_loss_of(
                {k: jnp.asarray(v) for k, v in p.items()}, x, y)))
            p = {k: v - lr * np.asarray(g[k]) for k, v in p.items()}
        np.testing.assert_allclose(losses_ls, losses_dp, rtol=1e-5)

    def test_k3_matches_per_worker_simulation(self):
        R = 4
        mesh = Mesh(np.array(jax.devices()[:R]), ("data",))
        x, y = _data(B=16)
        lr, k = 0.1, 3

        step, state = make_localsgd_train_step(_loss_of, _params(), SGD(lr),
                                               mesh, k_steps=k)
        losses = []
        for i in range(6):
            state, loss = step(state, np.float32(lr), x, y)
            losses.append(float(loss))

        # numpy oracle: R workers, each trains on its batch shard; params
        # block-averaged every k steps
        xs = np.split(np.asarray(x), R)
        ys = np.split(np.asarray(y), R)
        workers = [{kk: np.asarray(v) for kk, v in _params().items()}
                   for _ in range(R)]
        oracle_losses = []
        for i in range(6):
            step_losses = []
            for w in range(R):
                pw = {kk: jnp.asarray(v) for kk, v in workers[w].items()}
                bx, by = jnp.asarray(xs[w]), jnp.asarray(ys[w])
                step_losses.append(float(_loss_of(pw, bx, by)))
                g = jax.grad(_loss_of)(pw, bx, by)
                workers[w] = {kk: v - lr * np.asarray(g[kk])
                              for kk, v in workers[w].items()}
            oracle_losses.append(np.mean(step_losses))
            if (i + 1) % k == 0:
                avg = {kk: np.mean([workers[w][kk] for w in range(R)], axis=0)
                       for kk in workers[0]}
                workers = [dict(avg) for _ in range(R)]
        np.testing.assert_allclose(losses, oracle_losses, rtol=1e-4)

        # after the last sync step (step 6), replica rows must agree
        w_rows = np.asarray(state["params"]["w"])
        np.testing.assert_allclose(w_rows, np.broadcast_to(w_rows[0],
                                                           w_rows.shape),
                                   rtol=1e-6)


class TestLars:
    def test_matches_formula(self):
        paddle.seed(0)
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 3, bias_attr=False)
        w0 = np.asarray(lin.weight._data).copy()
        opt = Lars(learning_rate=0.5, momentum=0.9, lars_coeff=0.01,
                   lars_weight_decay=0.001, parameters=lin.parameters())
        x = paddle.to_tensor(np.random.RandomState(0)
                             .standard_normal((8, 4)).astype(np.float32))
        out = (lin(x) ** 2).mean()
        out.backward()
        g = np.asarray(lin.weight._grad)
        opt.step()

        p_norm = np.linalg.norm(w0)
        g_norm = np.linalg.norm(g)
        local_lr = 0.5 * 0.01 * p_norm / (g_norm + 0.001 * p_norm + 1e-9)
        v = local_lr * (g + 0.001 * w0)
        np.testing.assert_allclose(np.asarray(lin.weight._data), w0 - v,
                                   rtol=1e-5)

    def test_zero_param_guard(self):
        """zero-norm params take the plain path (local_lr = lr)."""
        from paddle_tpu.core.tensor import Parameter
        p = Parameter(jnp.zeros((3, 3)))
        opt = Lars(learning_rate=0.1, momentum=0.0, parameters=[p])
        p._grad = jnp.ones((3, 3))
        opt.step()
        np.testing.assert_allclose(np.asarray(p._data), -0.1 * np.ones((3, 3)),
                                   rtol=1e-6)


class TestLarsExclude:
    def test_excluded_param_skips_decay(self):
        paddle.seed(1)
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 4)
        lin.bias.name = "linear_bias"
        w0 = np.asarray(lin.weight._data).copy()
        b0 = np.asarray(lin.bias._data).copy()
        opt = paddle.optimizer.Lars(
            learning_rate=0.5, momentum=0.0, lars_coeff=0.01,
            lars_weight_decay=0.1, parameters=lin.parameters(),
            exclude_from_weight_decay=["bias"])
        g = np.ones((4, 4), np.float32)
        lin.weight._grad = jnp.asarray(g)
        lin.bias._grad = jnp.asarray(np.ones(4, np.float32))
        opt.step()
        # weight: decayed; bias: wd = 0 (b0 is zero-init so p_norm = 0 →
        # plain path local_lr = lr, and no +wd*p term either way)
        p_norm = np.linalg.norm(w0)
        llr = 0.5 * 0.01 * p_norm / (np.linalg.norm(g) + 0.1 * p_norm + 1e-9)
        np.testing.assert_allclose(np.asarray(lin.weight._data),
                                   w0 - llr * (g + 0.1 * w0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(lin.bias._data),
                                   b0 - 0.5 * np.ones(4), rtol=1e-5)


@needs4
class TestDGC:
    def test_single_replica_matches_momentum_accumulation_oracle(self):
        """R=1: no cross-replica effects; DGC must equal the reference
        recurrence u=m*u+g, v=v+u, send top-k(v), clear sent coords."""
        from paddle_tpu.distributed.dgc import make_dgc_train_step
        mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
        params = {"w": jnp.asarray(np.array([[1.0, -2.0, 0.5, 3.0]], np.float32))}

        def loss_of(p, x):
            return jnp.sum(p["w"] * x)  # grad = x

        opt = SGD(1.0)
        step, state = make_dgc_train_step(loss_of, params, opt, mesh,
                                          sparsity=0.5, momentum=0.9)
        x = jnp.asarray(np.array([[0.1, 0.4, -0.3, 0.2]], np.float32))

        # numpy oracle
        u = np.zeros(4); v = np.zeros(4); w = np.array([1.0, -2.0, 0.5, 3.0])
        g = np.array([0.1, 0.4, -0.3, 0.2])
        for i in range(3):
            u = 0.9 * u + g
            v = v + u
            k = 2  # 4 * (1-0.5)
            idx = np.argsort(-np.abs(v))[:k]
            dense = np.zeros(4); dense[idx] = v[idx]
            u[idx] = 0.0; v[idx] = 0.0
            w = w - dense  # SGD lr=1
            state, loss = step(state, np.float32(1.0), x)
        np.testing.assert_allclose(np.asarray(state["params"]["w"]).ravel(),
                                   w, rtol=1e-5)

    def test_multi_replica_converges_and_residuals_accumulate(self):
        from paddle_tpu.distributed.dgc import make_dgc_train_step
        R = 4
        mesh = Mesh(np.array(jax.devices()[:R]), ("data",))
        r = np.random.RandomState(0)
        params = {"w": jnp.asarray(r.standard_normal((6, 3)).astype(np.float32) * 0.3),
                  "b": jnp.zeros((4,), jnp.float32)}

        def loss_of(p, x, y):
            logits = x @ p["w"]
            return -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(logits), y[:, None], 1)) + 0.0 * jnp.sum(p["b"])

        step, state = make_dgc_train_step(loss_of, params, SGD(0.5), mesh,
                                          sparsity=0.75, momentum=0.9,
                                          rampup_begin_step=2)
        x = jnp.asarray(r.standard_normal((16, 6)).astype(np.float32))
        y = jnp.asarray(r.randint(0, 3, 16))
        losses = []
        for i in range(25):
            state, loss = step(state, np.float32(0.5), x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] / 2, (losses[0], losses[-1])
        # residuals exist and are per-replica (leading dim R)
        assert np.asarray(state["v"]["w"]).shape[0] == R
