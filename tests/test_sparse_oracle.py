"""Sparse COO/CSR numerics vs dense equivalents (previously surface-tested
only; ≙ reference test_sparse_utils_op.py / test_sparse_matmul_op.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import sparse


def _coo(shape=(4, 5), density=0.4, seed=5):
    rng = np.random.RandomState(seed)
    dense = rng.randn(*shape).astype("float32")
    mask = rng.rand(*shape) < density
    dense = np.where(mask, dense, 0.0).astype("float32")
    idx = np.argwhere(dense != 0).T
    vals = dense[tuple(idx)]
    t = sparse.sparse_coo_tensor(paddle.to_tensor(idx.astype("int64")),
                                 paddle.to_tensor(vals), shape)
    return t, dense


def test_coo_roundtrip_to_dense():
    t, dense = _coo()
    np.testing.assert_allclose(np.asarray(t.to_dense()._data), dense)
    assert sparse.is_sparse(t)


def test_csr_roundtrip_to_dense():
    dense = np.array([[1., 0., 2.], [0., 0., 3.], [4., 5., 0.]], "float32")
    crows = np.array([0, 2, 3, 5], "int64")
    cols = np.array([0, 2, 2, 0, 1], "int64")
    vals = np.array([1., 2., 3., 4., 5.], "float32")
    t = sparse.sparse_csr_tensor(paddle.to_tensor(crows),
                                 paddle.to_tensor(cols),
                                 paddle.to_tensor(vals), (3, 3))
    np.testing.assert_allclose(np.asarray(t.to_dense()._data), dense)


def test_elementwise_ops_match_dense():
    a, da = _coo(seed=11)
    b, db = _coo(seed=12)
    np.testing.assert_allclose(np.asarray(sparse.add(a, b).to_dense()._data),
                               da + db, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sparse.subtract(a, b).to_dense()._data), da - db,
        rtol=1e-6)
    # unary ops act on stored values only (reference sparse semantics)
    np.testing.assert_allclose(np.asarray(sparse.relu(a).to_dense()._data),
                               np.maximum(da, 0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sparse.sin(a).to_dense()._data),
                               np.where(da != 0, np.sin(da), 0.0),
                               rtol=1e-6, atol=1e-7)


def test_matmul_matches_dense():
    a, da = _coo(seed=13)
    dense_rhs = np.random.RandomState(14).randn(5, 3).astype("float32")
    out = sparse.matmul(a, paddle.to_tensor(dense_rhs))
    got = np.asarray(getattr(out, "_data", out))
    np.testing.assert_allclose(got, da @ dense_rhs, rtol=1e-5, atol=1e-5)


def test_masked_matmul():
    """masked_matmul(x, y, mask): dense@dense evaluated only at mask's
    sparsity pattern (reference sparse.masked_matmul contract)."""
    r = np.random.RandomState(15)
    x = r.randn(4, 6).astype("float32")
    y = r.randn(6, 5).astype("float32")
    m, dm = _coo((4, 5), density=0.3, seed=16)
    out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y), m)
    got = np.asarray(out.to_dense()._data)
    want = np.where(dm != 0, x @ y, 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_coalesce_merges_duplicates():
    idx = np.array([[0, 0, 1], [1, 1, 2]], "int64")
    vals = np.array([1.0, 2.0, 5.0], "float32")
    t = sparse.sparse_coo_tensor(paddle.to_tensor(idx),
                                 paddle.to_tensor(vals), (2, 3))
    c = sparse.coalesce(t)
    dense = np.zeros((2, 3), "float32")
    dense[0, 1] = 3.0
    dense[1, 2] = 5.0
    np.testing.assert_allclose(np.asarray(c.to_dense()._data), dense)
