"""Ragged paged attention kernel (ops/ragged_paged_attention.py): the
in-kernel block-table walk over a flattened mixed prefill+decode pack must
reproduce the gather fallback exactly — including per-row causal clocks,
left-pad masks, int8 (values, scales) pools with in-kernel dequant,
zero-length sequences, and padding rows.  CPU CI runs interpret mode; the
Mosaic lowering is exercised by the -m tpu smoke suite on hardware."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.models._decode import quantize_kv
from paddle_tpu.ops.ragged_paged_attention import (ragged_attention_ref,
                                                   ragged_paged_attention,
                                                   ragged_rows)


def _case(seed, S=4, nh=4, hd=16, NB1=11, bs=8, C=4, T=24, quantized=False):
    rng = np.random.RandomState(seed)
    pk = jnp.asarray(rng.randn(NB1, bs, nh, hd), jnp.float32)
    pv = jnp.asarray(rng.randn(NB1, bs, nh, hd), jnp.float32)
    if quantized:
        pk = quantize_kv(pk)
        pv = quantize_kv(pv)
    table = jnp.asarray(rng.randint(0, NB1, (S, C)), jnp.int32)
    # random ragged q lengths summing to <= T (zero-length rows included)
    q_lens = rng.randint(0, 6, S)
    while q_lens.sum() > T:
        q_lens[rng.randint(S)] = 0
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(q_lens)]), jnp.int32)
    # kv extent AFTER the writes: at least the sequence's own rows
    kv = jnp.asarray([rng.randint(q, C * bs + 1) if q else 0
                      for q in q_lens], jnp.int32)
    pad = jnp.asarray([rng.randint(0, max(int(k) - int(q), 0) + 1)
                       for k, q in zip(kv, q_lens)], jnp.int32)
    q = jnp.asarray(rng.randn(T, nh, hd), jnp.float32)
    return q, pk, pv, table, cu, kv, pad, int(q_lens.sum())


class TestRaggedRows:
    def test_row_expansion(self):
        cu = jnp.asarray([0, 3, 3, 4, 6], jnp.int32)   # q_lens 3, 0, 1, 2
        kv = jnp.asarray([10, 0, 5, 2], jnp.int32)
        seq, pos = ragged_rows(cu, kv, 8)
        np.testing.assert_array_equal(np.asarray(seq)[:6],
                                      [0, 0, 0, 2, 3, 3])
        # positions: seq0 rows at 7..9, seq2 decode row at 4, seq3 at 0..1
        np.testing.assert_array_equal(np.asarray(pos),
                                      [7, 8, 9, 4, 0, 1, -1, -1])


class TestRaggedKernelParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_matches_gather_fallback(self, seed):
        q, pk, pv, table, cu, kv, pad, n_real = _case(seed)
        rs, rp = ragged_rows(cu, kv, q.shape[0])
        ref = ragged_attention_ref(q, pk, pv, table, rs, rp, pad)
        got = ragged_paged_attention(q, pk, pv, table, cu, kv, pad,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got)[:n_real],
                                   np.asarray(ref)[:n_real],
                                   rtol=2e-5, atol=2e-5)
        assert np.isfinite(np.asarray(got)).all()

    @pytest.mark.parametrize("seed", [5, 6])
    def test_int8_pools_dequant_in_kernel(self, seed):
        """int8 (values, scales) pools take the kernel path with the
        dequantize fused into the k/v read — parity with the fallback's
        gather-then-dequantize."""
        q, pk, pv, table, cu, kv, pad, n_real = _case(seed, quantized=True)
        rs, rp = ragged_rows(cu, kv, q.shape[0])
        ref = ragged_attention_ref(q, pk, pv, table, rs, rp, pad)
        got = ragged_paged_attention(q, pk, pv, table, cu, kv, pad,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got)[:n_real],
                                   np.asarray(ref)[:n_real],
                                   rtol=2e-5, atol=2e-5)

    def test_pure_decode_matches_paged_decode_kernel(self):
        """A pack of q_len == 1 rows IS the old decode kernel's workload:
        outputs must match ops/paged_attention.py row for row (the ragged
        kernel strictly generalizes it)."""
        from paddle_tpu.ops.paged_attention import paged_decode_attention
        rng = np.random.RandomState(9)
        S, nh, hd, NB1, bs, C = 4, 4, 16, 11, 8, 4
        pk = jnp.asarray(rng.randn(NB1, bs, nh, hd), jnp.float32)
        pv = jnp.asarray(rng.randn(NB1, bs, nh, hd), jnp.float32)
        table = jnp.asarray(rng.randint(0, NB1, (S, C)), jnp.int32)
        t = jnp.asarray(rng.randint(0, C * bs, S), jnp.int32)
        pad = jnp.minimum(jnp.asarray(rng.randint(0, bs, S), jnp.int32), t)
        q = jnp.asarray(rng.randn(S, nh, hd), jnp.float32)
        old = paged_decode_attention(q, pk, pv, table, t, pad,
                                     interpret=True)
        cu = jnp.arange(S + 1, dtype=jnp.int32)         # one row per slot
        got = ragged_paged_attention(q, pk, pv, table, cu, t + 1, pad,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(old),
                                   rtol=2e-5, atol=2e-5)

    def test_no_pad_and_empty_pack(self):
        """pad_lens=None defaults to zeros; an all-padding pack (zero real
        rows) is garbage-but-finite."""
        q, pk, pv, table, cu, kv, pad, _ = _case(11)
        rs, rp = ragged_rows(cu, kv, q.shape[0])
        ref = ragged_attention_ref(q, pk, pv, table, rs, rp, None)
        got = ragged_paged_attention(q, pk, pv, table, cu, kv, None,
                                     interpret=True)
        n_real = int(np.asarray(cu)[-1])
        np.testing.assert_allclose(np.asarray(got)[:n_real],
                                   np.asarray(ref)[:n_real],
                                   rtol=2e-5, atol=2e-5)
        empty = ragged_paged_attention(
            q, pk, pv, table, jnp.zeros_like(cu), jnp.zeros_like(kv),
            None, interpret=True)
        assert np.isfinite(np.asarray(empty)).all()
