"""FFT + signal numeric oracles vs numpy/torch (these modules previously had
surface tests only — VERDICT r2 weak #10; ≙ reference test_fft.py,
test_stft_op.py)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t._data)


RNG = np.random.RandomState(42)
X1 = RNG.randn(16).astype("float32")
XC = (RNG.randn(16) + 1j * RNG.randn(16)).astype("complex64")
X2 = RNG.randn(4, 8).astype("float32")
XC2 = (RNG.randn(4, 8) + 1j * RNG.randn(4, 8)).astype("complex64")

FFT_CASES = [
    ("fft", (XC,), {}, np.fft.fft(XC)),
    ("ifft", (XC,), {}, np.fft.ifft(XC)),
    ("fft", (X1,), {"n": 8}, np.fft.fft(X1, n=8)),
    ("rfft", (X1,), {}, np.fft.rfft(X1)),
    ("irfft", (np.fft.rfft(X1).astype("complex64"),), {},
     np.fft.irfft(np.fft.rfft(X1))),
    ("hfft", (XC,), {}, np.fft.hfft(XC)),
    ("ihfft", (X1,), {}, np.fft.ihfft(X1)),
    ("fft2", (XC2,), {}, np.fft.fft2(XC2)),
    ("ifft2", (XC2,), {}, np.fft.ifft2(XC2)),
    ("rfft2", (X2,), {}, np.fft.rfft2(X2)),
    ("irfft2", (np.fft.rfft2(X2).astype("complex64"),), {},
     np.fft.irfft2(np.fft.rfft2(X2))),
    ("fftn", (XC2,), {}, np.fft.fftn(XC2)),
    ("ifftn", (XC2,), {}, np.fft.ifftn(XC2)),
    ("rfftn", (X2,), {}, np.fft.rfftn(X2)),
    ("irfftn", (np.fft.rfftn(X2).astype("complex64"),), {},
     np.fft.irfftn(np.fft.rfftn(X2))),
    ("fftshift", (X1,), {}, np.fft.fftshift(X1)),
    ("ifftshift", (np.fft.fftshift(X1),), {},
     np.fft.ifftshift(np.fft.fftshift(X1))),
    ("fftfreq", (), {"n": 10, "d": 0.5}, np.fft.fftfreq(10, 0.5)),
    ("rfftfreq", (), {"n": 10, "d": 0.5}, np.fft.rfftfreq(10, 0.5)),
    # hfft2/ihfft2/hfftn/ihfftn: numpy lacks them — torch-checked below
]


@pytest.mark.parametrize("name,args,kwargs,want", FFT_CASES,
                         ids=lambda v: v if isinstance(v, str) else None)
def test_fft_matches_numpy(name, args, kwargs, want):
    fn = getattr(paddle.fft, name)
    targs = [paddle.to_tensor(a) for a in args]
    got = _np(fn(*targs, **kwargs))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_hfft_family_roundtrip_and_torch():
    """hfft2/ihfft2/hfftn/ihfftn vs torch.fft (numpy lacks the 2d/nd
    Hermitian variants)."""
    for pname, tname, x in [("hfft2", "hfft2", XC2), ("ihfft2", "ihfft2", X2),
                            ("hfftn", "hfftn", XC2), ("ihfftn", "ihfftn", X2)]:
        got = _np(getattr(paddle.fft, pname)(paddle.to_tensor(x)))
        want = getattr(torch.fft, tname)(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3,
                                   err_msg=pname)


class TestSignal:
    def test_frame(self):
        x = paddle.to_tensor(X1)
        f = _np(paddle.signal.frame(x, frame_length=8, hop_length=4))
        # last axis walks frames (reference signal.py frame contract)
        assert f.shape == (8, 3)
        np.testing.assert_allclose(f[:, 0], X1[:8], rtol=1e-6)
        np.testing.assert_allclose(f[:, 1], X1[4:12], rtol=1e-6)

    def test_overlap_add_inverts_frame(self):
        x = paddle.to_tensor(X1)
        f = paddle.signal.frame(x, frame_length=8, hop_length=8)
        back = _np(paddle.signal.overlap_add(f, hop_length=8))
        np.testing.assert_allclose(back, X1, rtol=1e-6)

    def test_stft_matches_torch(self):
        x = RNG.randn(2, 64).astype("float32")
        win = np.hanning(16).astype("float32")
        got = _np(paddle.signal.stft(paddle.to_tensor(x), n_fft=16,
                                     hop_length=8,
                                     window=paddle.to_tensor(win),
                                     center=True, pad_mode="reflect"))
        want = torch.stft(torch.from_numpy(x), n_fft=16, hop_length=8,
                          window=torch.from_numpy(win), center=True,
                          pad_mode="reflect", return_complex=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_istft_roundtrip(self):
        x = RNG.randn(1, 64).astype("float32")
        win = np.hanning(16).astype("float32") + 0.1
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=16, hop_length=4,
                                  window=paddle.to_tensor(win), center=True)
        back = _np(paddle.signal.istft(spec, n_fft=16, hop_length=4,
                                       window=paddle.to_tensor(win),
                                       center=True, length=64))
        np.testing.assert_allclose(back.ravel(), x.ravel(),
                                   rtol=1e-3, atol=1e-3)
