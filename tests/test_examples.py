"""The examples/ scripts must stay runnable — they are the first thing a
reference user tries.  Each runs as a subprocess on the CPU backend with
tiny step counts."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(ROOT, "examples")


def _run(script, *args, env_extra=None, timeout=420):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU dial-out from CI
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.run([sys.executable, os.path.join(EXAMPLES, script)]
                          + list(args),
                          capture_output=True, text=True, env=env,
                          timeout=timeout, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


@pytest.mark.slow
class TestExamples:
    def test_mnist(self):
        out = _run("train_mnist.py")
        assert "loss" in out

    def test_quantize_ptq(self):
        out = _run("quantize_ptq.py")
        assert "int8 accuracy" in out

    def test_bert(self):
        out = _run("finetune_bert.py")
        assert "step 9" in out

    def test_gpt_hybrid_2x2x2(self):
        out = _run("train_gpt_hybrid.py", "--dp", "2", "--mp", "2",
                   "--pp", "2", "--steps", "2",
                   env_extra={"XLA_FLAGS":
                              "--xla_force_host_platform_device_count=8"})
        assert "step 1" in out

    def test_gpt_hybrid_zero2(self):
        out = _run("train_gpt_hybrid.py", "--dp", "4", "--zero", "2",
                   "--steps", "2",
                   env_extra={"XLA_FLAGS":
                              "--xla_force_host_platform_device_count=8"})
        assert "step 1" in out

    def test_generate_gpt(self):
        out = _run("generate_gpt.py", "--max_new_tokens", "6",
                   "--num_beams", "2")
        assert "GENERATION_OK" in out

    def test_serve_bucketed(self):
        out = _run("serve_bucketed.py")
        assert "SERVE_OK" in out

    def test_serve_continuous(self):
        out = _run("serve_continuous.py", "--int8", "--ticks_per_sync", "2")
        assert "6 requests, 112 tokens" in out
