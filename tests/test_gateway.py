"""Serving gateway (paddle_tpu/gateway.py, ISSUE 9): admission control /
shedding thresholds, TTFT + total deadlines (pre-dispatch and mid-decode),
prefix-affinity and least-outstanding routing, quarantine + re-admission
with the documented replay signal, graceful drain with zero drops, and
output parity with a solo engine.

All timing runs on a DETERMINISTIC fake clock injected via ``clock=`` —
deadline behavior is exact, never sleep-based.  No reference counterpart:
the reference snapshot has no service layer at all (SURVEY §2.3)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.gateway import (DeadlineExceeded, Overloaded,
                                ServingGateway)
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (PagedContinuousBatchingEngine,
                                RaggedPagedContinuousBatchingEngine)
from paddle_tpu.telemetry import Tracer


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo_greedy(model, params, prompt, n):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         greedy=True)
    return [int(t) for t in np.asarray(out)[0]]


def _paged(model, params, tracer=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_buckets", [8, 16])
    return PagedContinuousBatchingEngine(model, params, tracer=tracer,
                                         **kw)


def _ragged(model, params, tracer=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_buckets", [8, 16])
    kw.setdefault("token_budget", 16)
    return RaggedPagedContinuousBatchingEngine(model, params,
                                               tracer=tracer, **kw)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


PROMPTS = [([5, 17, 3], 8), ([40, 2], 6), ([61], 5), ([9, 9, 1], 7),
           ([8, 30, 12, 4], 4)]


class TestParity:
    def test_single_replica_matches_solo(self, model_and_params):
        """One healthy replica behind the gateway = the solo engine:
        token-for-token parity, intact streams, clean terminal flags."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params), "a")
        streams = {}
        handles = [gw.submit(p, n, on_token=lambda g, t, d:
                             streams.setdefault(g, []).append((t, d)))
                   for p, n in PROMPTS]
        got = gw.run_to_completion(max_ticks=300)
        assert sorted(got) == sorted(r.gid for r in handles)
        for r, (p, n) in zip(handles, PROMPTS):
            want = _solo_greedy(model, params, p, n)
            assert r.status == "finished"
            assert r.tokens == want and got[r.gid] == want
            assert [t for t, _ in streams[r.gid]] == want
            assert streams[r.gid][-1][1] is True
        assert gw.replica("a").engine.blocks_in_use == 0

    def test_mixed_engine_fleet(self, model_and_params):
        """A heterogeneous fleet (paged + ragged replicas) serves the
        same oracle-exact outputs — the gateway only relies on the shared
        engine surface."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params), "paged")
        gw.add_replica(_ragged(model, params), "ragged")
        handles = [gw.submit(p, n) for p, n in PROMPTS]
        gw.run_to_completion(max_ticks=300)
        assert {r.replica for r in handles} == {"paged", "ragged"}
        for r, (p, n) in zip(handles, PROMPTS):
            assert r.tokens == _solo_greedy(model, params, p, n), r


class TestAdmission:
    def test_depth_threshold_sheds_structured(self, model_and_params):
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), max_queue_depth=2)
        gw.add_replica(_paged(model, params), "a")
        sig = []
        handles = [gw.submit([1, 2], 3,
                             on_token=lambda g, t, d: sig.append((g, t, d)))
                   for _ in range(5)]
        # nothing stepped yet: 2 queued, 3 shed immediately
        assert [r.status for r in handles] == \
            ["queued", "queued", "shed", "shed", "shed"]
        for r in handles[2:]:
            assert isinstance(r.error, Overloaded)
            assert r.error.queue_depth == 2
            assert r.error.max_queue_depth == 2
            assert (r.gid, None, True) in sig     # never silent
        got = gw.run_to_completion(max_ticks=200)
        assert sorted(got) == [handles[0].gid, handles[1].gid]
        m = gw.metrics()
        assert m["shed"] == 3 and m["finished"] == 2

    def test_token_budget_sheds(self, model_and_params):
        """The token-budget-aware limit: a deep budget bound sheds by
        queued (prompt + max_new_tokens) mass even under the depth
        limit."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), max_queue_depth=100,
                            max_queued_tokens=20)
        gw.add_replica(_paged(model, params), "a")
        r0 = gw.submit([1, 2, 3], 9)            # est 12
        r1 = gw.submit([4, 5], 5)               # est 7, total 19 <= 20
        r2 = gw.submit([6], 3)                  # est 4 → would be 23: shed
        assert r0.status == r1.status == "queued"
        assert r2.status == "shed"
        assert isinstance(r2.error, Overloaded)
        assert r2.error.queued_tokens == 19 and r2.error.est_tokens == 4
        gw.run_to_completion(max_ticks=200)
        assert r0.status == r1.status == "finished"

    def test_priority_dispatch_order(self, model_and_params):
        """Priority 0 dispatches before priority 1 regardless of
        submission order; each priority has its own bounded queue."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), max_queue_depth=4,
                            priorities=2)
        gw.add_replica(_paged(model, params, max_slots=1), "a")
        lo = gw.submit([1, 2], 3, priority=1)
        hi = gw.submit([3, 4], 3, priority=0)
        gw.step()
        assert hi.status == "dispatched"
        assert lo.status == "queued"           # one slot: hi went first
        gw.run_to_completion(max_ticks=200)
        assert hi.status == lo.status == "finished"


class TestDeadlines:
    def test_ttft_deadline_expires_pre_dispatch(self, model_and_params):
        model, params = model_and_params
        clk = FakeClock()
        gw = ServingGateway(clock=clk, max_queue_depth=10)
        gw.add_replica(_paged(model, params), "a")
        busy = [gw.submit([1, 2, 3], 20) for _ in range(2)]  # hold slots
        gw.step()
        late = gw.submit([4, 5], 6, ttft_deadline_s=1.0)
        clk.advance(2.0)
        gw.step()
        assert late.status == "expired"
        assert isinstance(late.error, DeadlineExceeded)
        assert late.error.kind == "ttft"
        assert late.engine_rid is None          # never touched an engine
        assert late.error.tokens_delivered == 0
        gw.run_to_completion(max_ticks=300)
        assert all(r.status == "finished" for r in busy)

    def test_total_deadline_cancels_mid_decode(self, model_and_params):
        """A running request past its total deadline is cancelled through
        Engine.cancel: partial tokens stay on the handle, the consumer
        gets the terminal signal, and the engine releases every block."""
        model, params = model_and_params
        clk = FakeClock()
        gw = ServingGateway(clock=clk)
        gw.add_replica(_paged(model, params), "a")
        sig = []
        r = gw.submit([6, 7], 20, deadline_s=5.0,
                      on_token=lambda g, t, d: sig.append((t, d)))
        for _ in range(4):
            gw.step()
        assert r.status == "dispatched" and len(r.tokens) > 0
        clk.advance(10.0)
        gw.step()
        assert r.status == "expired"
        assert r.error.kind == "total"
        assert r.error.tokens_delivered == len(r.tokens) > 0
        assert r.tokens == _solo_greedy(model, params, [6, 7],
                                        20)[:len(r.tokens)]
        assert sig[-1] == (None, True)
        eng = gw.replica("a").engine
        assert eng.blocks_in_use == 0
        assert eng.metrics()["requests_cancelled"] == 1

    def test_client_cancel_queued_and_inflight(self, model_and_params):
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params), "a")
        r0 = gw.submit([5, 17, 3], 20)
        r1 = gw.submit([40, 2], 6)
        r2 = gw.submit([61], 5)                 # queued behind 2 slots
        gw.step()
        assert gw.cancel(r2.gid)                # queued-side
        assert r2.status == "cancelled"
        assert gw.cancel(r0.gid)                # in-flight via Engine.cancel
        assert r0.status == "cancelled"
        assert not gw.cancel(999)
        gw.run_to_completion(max_ticks=200)
        assert r1.status == "finished"
        assert r1.tokens == _solo_greedy(model, params, [40, 2], 6)
        assert not gw.cancel(r1.gid)            # terminal already


class TestRouting:
    def test_least_outstanding_tokens(self, model_and_params):
        """With no prefix signal the emptier replica wins."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params), "a")
        gw.add_replica(_paged(model, params), "b")
        r0 = gw.submit([1, 2, 3], 20)           # heavy
        gw.step()
        first = r0.replica
        r1 = gw.submit([4, 5], 3)               # light: the OTHER replica
        gw.step()
        assert r1.replica != first
        gw.run_to_completion(max_ticks=300)

    def test_prefix_affinity_overrides(self, model_and_params):
        """A prompt whose chain-digest prefix is cached on one replica
        routes there even when the other replica is emptier."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        ea = _paged(model, params, enable_prefix_cache=True)
        eb = _paged(model, params, enable_prefix_cache=True)
        gw.add_replica(ea, "warm")
        gw.add_replica(eb, "cold")
        sysp = [9] * 8 + [1, 2]                 # first block cacheable
        ea.add_request(list(sysp), 3)           # warm replica a directly
        while ea.pending():
            ea.step()
        ea.pop_finished()
        assert len(ea._prefix_cache) > 0
        # load the warm replica so least-outstanding alone would pick cold
        filler = gw.submit([70, 71], 3)
        gw.step()
        r = gw.submit(sysp[:8] + [3, 4], 3)
        gw.step()
        assert r.replica == "warm", (r.replica, filler.replica)
        gw.run_to_completion(max_ticks=300)
        assert r.status == "finished"


class TestQuarantine:
    def test_stalled_replica_quarantined_and_replayed(self,
                                                      model_and_params):
        """PR 7 /healthz stall logic per replica: a stalled tracer while
        work is in flight quarantines the replica; its incomplete
        requests re-admit elsewhere AFTER the documented replay signal
        ``on_token(gid, None, False)``, and still finish oracle-exact."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), stall_threshold_s=5.0)
        gw.add_replica(_paged(model, params, tracer=Tracer()), "a")
        gw.add_replica(_paged(model, params, tracer=Tracer()), "b")
        sig = []
        r = gw.submit([5, 17, 3], 8,
                      on_token=lambda g, t, d: sig.append((t, d)))
        gw.step()
        victim = r.replica
        rep = gw.replica(victim)
        rep.engine.tracer.last_event_age_s = lambda: 99.0   # wedge it
        gw.step()
        assert rep.state == "quarantined"
        assert "stalled tick" in rep.reason
        assert r.replays >= 1
        assert (None, False) in sig             # the replay signal
        gw.run_to_completion(max_ticks=300)
        assert r.status == "finished" and r.replica != victim
        want = _solo_greedy(model, params, [5, 17, 3], 8)
        assert r.tokens == want
        # post-replay stream re-delivers from token one
        assert [t for t, _ in sig[sig.index((None, False)) + 1:]] == want
        assert gw.metrics()["rerouted"] >= 1
        # quarantined replicas take no new work until reinstated
        r2 = gw.submit([61], 4)
        gw.run_to_completion(max_ticks=300)
        assert r2.replica != victim
        gw.reinstate(victim)
        assert gw.replica(victim).state == "active"

    def test_raising_consumer_does_not_strand_reroute(self,
                                                      model_and_params):
        """A consumer whose on_token raises on the replay signal must not
        abort the quarantine mid-way — every in-flight request still
        reroutes and finishes (the gateway's consumer-bugs-don't-break-
        the-loop contract)."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), stall_threshold_s=5.0)
        gw.add_replica(_paged(model, params, tracer=Tracer()), "a")
        gw.add_replica(_paged(model, params, tracer=Tracer()), "b")

        def bad_cb(gid, tok, done):
            if tok is None and not done:
                raise RuntimeError("consumer exploded on replay")
        r0 = gw.submit([5, 17, 3], 6, on_token=bad_cb)
        r1 = gw.submit([40, 2], 5, on_token=bad_cb)
        gw.step()
        victim = r0.replica
        rep = gw.replica(victim)
        both_there = sum(r.replica == victim for r in (r0, r1))
        rep.engine.tracer.last_event_age_s = lambda: 99.0
        gw.step()
        assert rep.state == "quarantined"
        assert not rep.inflight            # nothing stranded
        gw.run_to_completion(max_ticks=300)
        assert r0.status == r1.status == "finished", (r0, r1, both_there)
        assert r0.tokens == _solo_greedy(model, params, [5, 17, 3], 6)

    def test_quarantine_during_drain_still_hands_over(self,
                                                      model_and_params):
        """A DRAINING replica that stalls is quarantined AND its drain
        completes: the warmed replacement joins the fleet and the
        rerouted work finishes there — the rolling restart survives a
        mid-drain wedge."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), stall_threshold_s=5.0)
        gw.add_replica(_paged(model, params, tracer=Tracer()), "a")
        r = gw.submit([5, 17, 3], 6)
        gw.step()
        assert r.replica == "a"
        gw.drain("a", replacement=_paged(model, params),
                 replacement_name="a2", warm=False)
        gw.replica("a").engine.tracer.last_event_age_s = lambda: 99.0
        gw.step()                          # stall fires mid-drain
        # the wedge quarantined "a" AND completed the drain: is_drained
        # turns True (operator wait-loops unblock), the reason is kept,
        # and the replacement took over with the rerouted work
        assert gw.is_drained("a")
        assert "stalled tick" in gw.replica("a").reason
        assert gw.replica("a2").state == "active"
        gw.run_to_completion(max_ticks=300)
        assert r.status == "finished" and r.replica == "a2"
        assert r.tokens == _solo_greedy(model, params, [5, 17, 3], 6)

    def test_completed_work_not_replayed(self, model_and_params):
        """Quarantine harvests finished-but-unpopped requests instead of
        replaying them (the 'not in-flight-completed' clause)."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), stall_threshold_s=5.0)
        gw.add_replica(_paged(model, params, tracer=Tracer()), "a")
        r = gw.submit([40, 2], 2)
        # drive the ENGINE directly to completion without gateway harvest
        eng = None
        for _ in range(6):
            gw.step()
            if r.status == "finished":
                break
        assert r.status == "finished" and r.replays == 0
        assert r.tokens == _solo_greedy(model, params, [40, 2], 2)


class TestDrain:
    def test_drain_completes_inflight_zero_drops(self, model_and_params):
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params), "a")
        gw.add_replica(_paged(model, params), "b")
        handles = [gw.submit(p, n) for p, n in PROMPTS]
        gw.step()
        inflight_on_a = [r for r in handles if r.replica == "a"]
        gw.drain("a")
        assert gw.replica("a").state in ("draining", "stopped")
        late = gw.submit([7, 8, 9], 4)          # post-drain: routes to b
        got = gw.run_to_completion(max_ticks=400)
        assert gw.is_drained("a")
        # ZERO drops: every pre-drain request finished, replays included
        for r, (p, n) in zip(handles, PROMPTS):
            assert r.status == "finished", (r, inflight_on_a)
            assert r.tokens == _solo_greedy(model, params, p, n)
        assert late.status == "finished" and late.replica == "b"
        assert set(got) == {r.gid for r in handles} | {late.gid}
        assert gw.replica("a").engine.blocks_in_use == 0

    def test_drain_swaps_in_replacement(self, model_and_params):
        """The rolling-restart shape: drain(a, replacement=...) activates
        the replacement once the drain completes, and traffic flows to
        it."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params), "a")
        r0 = gw.submit([5, 17, 3], 6)
        gw.step()
        gw.drain("a", replacement=_paged(model, params),
                 replacement_name="a2", warm=False)
        gw.run_to_completion(max_ticks=300)
        assert gw.is_drained("a") and r0.status == "finished"
        assert gw.replica("a2").state == "active"
        r1 = gw.submit([40, 2], 5)
        gw.run_to_completion(max_ticks=300)
        assert r1.status == "finished" and r1.replica == "a2"
        assert r1.tokens == _solo_greedy(model, params, [40, 2], 5)


class TestObservability:
    def test_tracer_events_prometheus_chrome(self, model_and_params):
        model, params = model_and_params
        tr = Tracer()
        gw = ServingGateway(clock=FakeClock(), max_queue_depth=1,
                            tracer=tr)
        gw.add_replica(_paged(model, params), "a")
        r0 = gw.submit([1, 2], 3)
        shed = gw.submit([3, 4], 3)             # depth 1: shed
        gw.step()                               # dispatch r0 first —
        gw.drain("a")                           # draining stops admission
        gw.run_to_completion(max_ticks=200)
        assert shed.status == "shed"
        assert r0.status == "finished"          # drain drops nothing
        kinds = {e["what"] for e in tr.events("gateway")}
        assert "shed" in kinds and "drain_start" in kinds \
            and "drain_done" in kinds
        summ = tr.summary()["gateway"]
        assert summ["events"]["shed"] == 1
        ct = tr.to_chrome_trace()
        assert any(e.get("name", "").startswith("gateway:")
                   for e in ct["traceEvents"])
        prom = gw.prometheus_text()
        assert "paddle_tpu_gateway_shed 1" in prom
        snap = gw.gateway_snapshot()
        assert snap["queues"][0]["depth"] == 0
        assert any(rep["name"] == "a" for rep in snap["replicas"])

    def test_ops_server_gateway_route(self, model_and_params):
        import json
        import urllib.request
        from paddle_tpu.ops_server import OpsServer
        model, params = model_and_params
        gw = ServingGateway(tracer=Tracer())
        gw.add_replica(_paged(model, params), "a")
        r = gw.submit([5, 17, 3], 3)
        gw.run_to_completion(max_ticks=200)
        srv = OpsServer()
        srv.attach(gw, "gw")
        url = srv.start()
        try:
            body = urllib.request.urlopen(url + "/gateway",
                                          timeout=10).read()
            snap = json.loads(body)
            assert snap["counters"]["finished"] == 1
            assert snap["replicas"][0]["state"] == "active"
            txt = urllib.request.urlopen(url + "/metrics",
                                         timeout=10).read().decode()
            assert "paddle_tpu_gateway_finished" in txt
        finally:
            srv.stop()
        assert r.status == "finished"

    def test_ops_server_404_without_gateway(self):
        import urllib.error
        import urllib.request
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer()
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/gateway", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()
