"""Per-request sampling params in the serving engines (VERDICT r4 #6):
temperature/top_k/top_p/greedy/repetition_penalty/min_new_tokens/
eos_token_id ride per-slot traced data planes — any mixture of configs
shares ONE compiled decode program, and each request's output equals solo
generate() with its own knobs (deterministic configs verified exactly).

Matches the reference's per-call generate() contract (SURVEY §2.3) lifted
into continuous batching — beyond the reference, which has no serving
scheduler at all."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine)


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo(model, params, prompt, n, **kw):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         **kw)
    toks = [int(t) for t in np.asarray(out)[0]]
    eos = kw.get("eos_token_id")
    if eos is not None and eos in toks:
        toks = toks[:toks.index(eos) + 1]
    return toks


PROMPTS = [[5, 17, 3], [40, 2], [9, 9, 9, 9, 9, 1], [61], [8, 30, 12, 4]]


def _engines(model, params, **kw):
    yield ContinuousBatchingEngine(model, params, **kw)
    yield PagedContinuousBatchingEngine(model, params, block_size=4, **kw)


class TestPerRequestSampling:
    @pytest.mark.parametrize("paged", [False, True])
    @pytest.mark.parametrize("k", [1, 3])
    def test_mixed_deterministic_configs_match_solo(self, model_and_params,
                                                    paged, k):
        """Heterogeneous deterministic configs in one batch: plain greedy,
        greedy+penalty, top_k=1 sampling (argmax-equivalent), and
        greedy+min_new+eos — each equals its own solo generate() run, on
        both cache layouts and for chunked sync."""
        model, params = model_and_params
        cls = PagedContinuousBatchingEngine if paged \
            else ContinuousBatchingEngine
        kw = dict(block_size=4) if paged else {}
        eng = cls(model, params, max_slots=3, max_len=48,
                  prompt_buckets=[8], ticks_per_sync=k,
                  per_request_sampling=True, **kw)
        # find an eos that plain greedy emits early, to make min_new bite
        probe = _solo(model, params, PROMPTS[0], 8, greedy=True)
        eos = probe[1]
        cases = [
            (PROMPTS[0], 8, {}),
            (PROMPTS[1], 7, dict(repetition_penalty=5.0)),
            (PROMPTS[2], 6, dict(greedy=False, top_k=1, temperature=0.7)),
            (PROMPTS[0], 8, dict(min_new_tokens=4, eos_token_id=eos)),
            (PROMPTS[3], 9, dict(repetition_penalty=2.0,
                                 min_new_tokens=3, eos_token_id=eos)),
        ]
        rids = [eng.add_request(p, n, **c) for p, n, c in cases]
        got = eng.run_to_completion(max_ticks=300)
        for rid, (p, n, c) in zip(rids, cases):
            solo_kw = dict(c)
            if solo_kw.pop("greedy", True):
                solo_kw["greedy"] = True
            else:
                # top_k=1 sampling is argmax: oracle is the greedy run
                solo_kw.pop("top_k"), solo_kw.pop("temperature")
                solo_kw["greedy"] = True
            want = _solo(model, params, p, n, **solo_kw)
            assert got[rid] == want, f"request {rid} cfg={c} (paged={paged})"

    def test_slot_reuse_switches_config(self, model_and_params):
        """A slot that served a penalized request must serve a plain one
        next with NO carryover (the planes are rewritten at admission) —
        and vice versa."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=48, prompt_buckets=[8],
                                       per_request_sampling=True)
        r0 = eng.add_request(PROMPTS[0], 6, repetition_penalty=5.0)
        r1 = eng.add_request(PROMPTS[0], 6)              # same prompt, plain
        r2 = eng.add_request(PROMPTS[0], 6, repetition_penalty=5.0)
        got = eng.run_to_completion(max_ticks=200)
        assert got[r0] == got[r2] == _solo(model, params, PROMPTS[0], 6,
                                           greedy=True,
                                           repetition_penalty=5.0)
        assert got[r1] == _solo(model, params, PROMPTS[0], 6, greedy=True)
        assert got[r0] != got[r1]       # the knob demonstrably did something

    def test_one_program_for_any_mixture(self, model_and_params):
        """The whole point of data planes: admission order, config mixture,
        and fresh engines never add compiled programs — and engines with
        DIFFERENT defaults share them too."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)

        def make(**defaults):
            return ContinuousBatchingEngine(
                model, params, max_slots=2, max_len=48, prompt_buckets=[8],
                per_request_sampling=True, **defaults)

        eng = make()
        eng.add_request(PROMPTS[0], 5, repetition_penalty=3.0)
        eng.add_request(PROMPTS[1], 4, min_new_tokens=2, eos_token_id=7)
        eng.run_to_completion(max_ticks=100)
        n = len(model._serving_programs)
        eng2 = make(repetition_penalty=2.0, temperature=0.5, greedy=False)
        eng2.add_request(PROMPTS[2], 5)
        eng2.add_request(PROMPTS[3], 4, greedy=True)
        eng2.run_to_completion(max_ticks=100)
        assert len(model._serving_programs) == n

    def test_per_request_eos_retires_each_row_independently(
            self, model_and_params):
        """Two requests with DIFFERENT eos ids: each stops at its own."""
        model, params = model_and_params
        base = _solo(model, params, PROMPTS[0], 10, greedy=True)
        base1 = _solo(model, params, PROMPTS[1], 10, greedy=True)
        e0, e1 = base[2], base1[3]
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=48, prompt_buckets=[8],
                                       per_request_sampling=True)
        r0 = eng.add_request(PROMPTS[0], 10, eos_token_id=e0)
        r1 = eng.add_request(PROMPTS[1], 10, eos_token_id=e1)
        got = eng.run_to_completion(max_ticks=200)
        assert got[r0] == _solo(model, params, PROMPTS[0], 10, greedy=True,
                                eos_token_id=e0)
        assert got[r1] == _solo(model, params, PROMPTS[1], 10, greedy=True,
                                eos_token_id=e1)
        assert got[r0][-1] == e0 and got[r1][-1] == e1

    def test_chunked_prefill_with_per_request_planes(self,
                                                     model_and_params):
        """Chunked admission samples its first token through the planes
        set at admission — config must hold across the fill rounds."""
        model, params = model_and_params
        for eng in _engines(model, params, max_slots=2, max_len=48,
                            prompt_buckets=[16], prefill_chunk=4,
                            per_request_sampling=True):
            long_p = list(range(3, 16))
            r0 = eng.add_request(PROMPTS[0], 6)
            r1 = eng.add_request(long_p, 6, repetition_penalty=5.0)
            got = eng.run_to_completion(max_ticks=200)
            assert got[r0] == _solo(model, params, PROMPTS[0], 6,
                                    greedy=True)
            assert got[r1] == _solo(model, params, long_p, 6, greedy=True,
                                    repetition_penalty=5.0)

    def test_sampled_rows_complete_and_respect_filters(self,
                                                       model_and_params):
        """Smoke for true sampling rows: a top_k=2 request only ever emits
        tokens from the per-position greedy top-2 (checked against the
        model's own logits), alongside a greedy row that stays exact."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=48, prompt_buckets=[8],
                                       per_request_sampling=True)
        r0 = eng.add_request(PROMPTS[0], 8)
        r1 = eng.add_request(PROMPTS[1], 8, greedy=False, top_k=2,
                             temperature=0.9)
        got = eng.run_to_completion(max_ticks=200)
        assert got[r0] == _solo(model, params, PROMPTS[0], 8, greedy=True)
        # verify every sampled token was in that position's top-2, scored
        # by the model's own prefill+decode_logits on the growing context
        ctx = list(PROMPTS[1])
        for tok in got[r1]:
            P = 16
            pad = P - len(ctx)
            ids = jnp.asarray([[0] * pad + ctx], jnp.int32)
            h, _ = model.prefill(params, ids, P,
                                 pad_lens=jnp.asarray([pad], jnp.int32))
            l2 = np.asarray(model.decode_logits(params, h[:, -1:]))[0, -1]
            top2 = set(np.argsort(l2)[-2:])
            assert tok in top2, (tok, top2)
            ctx.append(tok)

    def test_validation(self, model_and_params):
        model, params = model_and_params
        classic = ContinuousBatchingEngine(model, params, max_slots=1,
                                           max_len=32, prompt_buckets=[8])
        with pytest.raises(ValueError, match="per_request_sampling"):
            classic.add_request(PROMPTS[0], 4, repetition_penalty=2.0)
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=32, prompt_buckets=[8],
                                       per_request_sampling=True)
        with pytest.raises(ValueError, match="top_k"):
            eng.add_request(PROMPTS[0], 4, top_k=0)
        with pytest.raises(ValueError, match="top_p"):
            eng.add_request(PROMPTS[0], 4, top_p=1.5)
        with pytest.raises(ValueError, match="temperature"):
            eng.add_request(PROMPTS[0], 4, temperature=0.0)
        with pytest.raises(ValueError, match="eos_token_id"):
            eng.add_request(PROMPTS[0], 4, min_new_tokens=2)
        with pytest.raises(TypeError, match="unknown"):
            eng.add_request(PROMPTS[0], 4, banana=1)

    def test_sampling_knobs_under_greedy_raise(self, model_and_params):
        """Sampling-only knobs while the effective greedy flag is True
        would silently decode argmax (ADVICE r5): loud failure instead —
        passing greedy=False alongside them is the accepted form, and an
        engine whose DEFAULT is greedy=False accepts them bare."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=32, prompt_buckets=[8],
                                       per_request_sampling=True)
        with pytest.raises(ValueError, match="greedy"):
            eng.add_request(PROMPTS[0], 4, temperature=0.8)
        with pytest.raises(ValueError, match="greedy"):
            eng.add_request(PROMPTS[0], 4, top_k=5, greedy=True)
        eng.add_request(PROMPTS[0], 4, temperature=0.8, greedy=False)
        # NEUTRAL values are no-ops, not sampling requests — clients
        # forwarding their defaults must not be rejected
        eng.add_request(PROMPTS[0], 4, temperature=1.0)
        eng.add_request(PROMPTS[0], 4, top_p=1.0)
        # classic mode: the CTOR knobs are the engine-wide sampler — the
        # same guard applies where the effective tuple is formed
        with pytest.raises(ValueError, match="greedy"):
            ContinuousBatchingEngine(model, params, max_slots=1,
                                     max_len=32, prompt_buckets=[8],
                                     temperature=0.7)
        sampling_default = ContinuousBatchingEngine(
            model, params, max_slots=1, max_len=32, prompt_buckets=[8],
            per_request_sampling=True, greedy=False,
            key=jax.random.key(0))
        sampling_default.add_request(PROMPTS[0], 4, temperature=0.8)


class TestPerRequestTP:
    def test_tp_mesh_with_per_request_planes(self, model_and_params):
        """Per-request planes compose with tensor-parallel serving: the
        (S,) config operands replicate under GSPMD while params/cache
        shard — mixed configs stay oracle-exact on a 4-device mesh."""
        import jax
        from jax.sharding import Mesh
        model, params = model_and_params
        if len(jax.devices()) < 4:
            pytest.skip("needs the 8-device CPU mesh conftest")
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("model",))
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=2, mesh=mesh,
                                       per_request_sampling=True)
        r0 = eng.add_request(PROMPTS[0], 8, repetition_penalty=5.0)
        r1 = eng.add_request(PROMPTS[1], 6)
        got = eng.run_to_completion(max_ticks=100)
        assert got[r0] == _solo(model, params, PROMPTS[0], 8, greedy=True,
                                repetition_penalty=5.0)
        assert got[r1] == _solo(model, params, PROMPTS[1], 6, greedy=True)
