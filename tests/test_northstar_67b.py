"""North-star scale check (BASELINE.json: GPT-3 6.7B, Fleet sharding-3).

Nothing allocates here: the ZeRO-3 sharding specs are evaluated at the REAL
6.7B tensor shapes and accounted analytically — proving every large tensor
partitions over the sharding axis and that per-device param+optimizer bytes
fit a v5e/v5p HBM long before real hardware is attached.  (Execution-level
ZeRO parity runs at small shapes in test_zero.py; the full step executes in
dryrun_multichip.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.device import local_devices

needs8 = pytest.mark.skipif(len(local_devices()) < 8, reason="needs 8 devices")


@needs8
class TestNorthStar67B:
    def _build(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import GPT_CONFIGS, GPTConfig, GPTModel

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 8}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        paddle.seed(0)
        cfg = GPTConfig(max_position_embeddings=2048,
                        compute_dtype="bfloat16", **GPT_CONFIGS["gpt3-6.7B"])
        return hcg, cfg

    def test_param_and_opt_bytes_shard_to_one_eighth(self):
        """Analytic ZeRO-3 accounting at REAL 6.7B shapes: sharded fp32
        params + Adam moments must come to ~(16 bytes x N)/8 per device."""
        from paddle_tpu.distributed.spmd import (_slot_spec, _spec_for_param)

        hcg, cfg = self._build()
        mesh = hcg.mesh
        H, I, L, V = (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
                      cfg.vocab_size)
        shapes = {
            "wte": (V, H), "wpe": (cfg.max_position_embeddings, H),
            "blocks_ln1_w": (L, H), "blocks_ln1_b": (L, H),
            "blocks_qkv_w": (L, H, 3 * H), "blocks_qkv_b": (L, 3 * H),
            "blocks_proj_w": (L, H, H), "blocks_proj_b": (L, H),
            "blocks_ln2_w": (L, H), "blocks_ln2_b": (L, H),
            "blocks_fc1_w": (L, H, I), "blocks_fc1_b": (L, I),
            "blocks_fc2_w": (L, I, H), "blocks_fc2_b": (L, H),
            "lnf_w": (H,), "lnf_b": (H,),
        }
        n_params = sum(int(np.prod(s)) for s in shapes.values())
        assert 6.0e9 < n_params < 7.5e9  # it really is the 6.7B config

        class Fake:
            def __init__(self, shape):
                self.shape = shape
                self._dims_mapping = None

        deg = mesh.shape["sharding"]
        total_dev_bytes = 0
        unsharded = []
        for name, shape in shapes.items():
            p = Fake(shape)
            spec = _spec_for_param(name, p, mesh, {}, 3, False)
            shard = deg if "sharding" in tuple(spec) else 1
            if shard == 1 and int(np.prod(shape)) > 8 * 1024 * 1024:
                unsharded.append(name)
            # fp32 param + m1 + m2, all sharded identically at stage 3
            slot = _slot_spec(spec, p, mesh, 3)
            slot_shard = deg if "sharding" in tuple(slot) else 1
            total_dev_bytes += int(np.prod(shape)) * 4 / shard \
                + 2 * int(np.prod(shape)) * 4 / slot_shard
        assert not unsharded, f"large tensors left unsharded: {unsharded}"
        # 6.7B x 12 bytes / 8 devices ≈ 10.0 GB < v5e's 16 GB HBM
        per_dev_gb = total_dev_bytes / 1e9
        assert per_dev_gb < 12.0, per_dev_gb
        # and sharding actually bought ~8x vs replicated
        assert per_dev_gb < (n_params * 12 / 1e9) / (deg / 1.3)
