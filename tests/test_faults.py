"""Fault-injection layer (paddle_tpu/faults.py, ISSUE 12): the typed
fault vocabulary, the seeded JSON-able FaultPlan, the FaultyEngine
wrapper's injection points (crash / stall / slow / dispatch_error /
warmup_fail / garble), the SimEngine fault modes, and whole-trajectory
seeded replayability through TrafficSim.

Everything runs on the fake clock — no JAX, no sleeps, milliseconds per
test.  No reference counterpart: the reference snapshot has no failure
model at all (SURVEY §2.3)."""

import json

import pytest

from paddle_tpu.faults import (Fault, FaultInjectionError, FaultPlan,
                               FaultyEngine, StreamCorruption,
                               TransientDispatchError)
from paddle_tpu.gateway import ServingGateway, ResiliencePolicy
from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                   TrafficSim, sim_tokens, steady)


def _gw(clock, resilience=None, **kw):
    kw.setdefault("stall_threshold_s", 5.0)
    tracer = SimTracer(clock, capacity=16384)
    return ServingGateway(clock=clock, tracer=tracer,
                          resilience=resilience, **kw), tracer


def _drive(gw, clock, max_ticks=400, dt=0.25):
    for _ in range(max_ticks):
        gw.step()
        clock.advance(dt)
        if not gw.pending():
            return
    raise AssertionError("gateway did not drain")


class TestFaultTypes:
    def test_kind_validation(self):
        with pytest.raises(ValueError):
            Fault("meteor")
        with pytest.raises(ValueError):
            Fault("stall", at_s=-1.0)
        with pytest.raises(ValueError):
            Fault("stall", duration_s=0.0)
        with pytest.raises(ValueError):
            Fault("slow", factor=0.5)
        with pytest.raises(ValueError):
            Fault("dispatch_error", count=0)

    def test_window_semantics(self):
        f = Fault("stall", at_s=10.0, duration_s=5.0)
        assert not f.active(9.9)
        assert f.active(10.0) and f.active(14.9)
        assert not f.active(15.0)
        crash = Fault("crash", at_s=3.0)          # no end
        assert crash.active(1e9) and not crash.active(2.9)

    def test_plan_ordering_targeting_and_json_round_trip(self):
        plan = FaultPlan([Fault("stall", at_s=20.0),
                          Fault("crash", at_s=5.0, replica="r1")], seed=3)
        assert [f.at_s for f in plan.faults] == [5.0, 20.0]
        assert [f.kind for f in plan.for_replica("r0")] == ["stall"]
        assert [f.kind for f in plan.for_replica("r1")] == ["crash",
                                                           "stall"]
        back = FaultPlan.from_dict(plan.to_dict())
        assert back.seed == 3 and len(back) == 2
        assert back.to_dict() == plan.to_dict()
        # bare-list JSON shape (the serve_gateway --chaos CLI input)
        lst = FaultPlan.from_json(json.dumps(
            [{"kind": "slow", "at_s": 1.0, "factor": 4.0}]))
        assert lst.faults[0].kind == "slow" and lst.faults[0].factor == 4.0

    def test_plan_rng_is_per_replica_deterministic(self):
        plan = FaultPlan(seed=9)
        a1 = [plan.rng("a").random() for _ in range(3)]
        a2 = [plan.rng("a").random() for _ in range(3)]
        b = [plan.rng("b").random() for _ in range(3)]
        assert a1 == a2 and a1 != b


class TestFaultyEngine:
    def test_crash_freezes_and_gateway_replays_exactly(self):
        """A crashed replica goes silent mid-work; the stall health-check
        quarantines it and every stranded request re-delivers its EXACT
        oracle stream elsewhere — zero drops, zero duplicate tokens."""
        clock = SimClock()
        gw, _ = _gw(clock)
        plan = FaultPlan([Fault("crash", at_s=2.0, replica="bad")])
        bad = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(FaultyEngine(bad, plan, clock, replica="bad"),
                       "bad")
        gw.add_replica(SimEngine(max_slots=2, tracer=SimTracer(clock)),
                       "ok")
        streams = {}
        handles = [gw.submit([i + 1, i + 2], 30, on_token=lambda g, t, d:
                             streams.setdefault(g, []).append((t, d)))
                   for i in range(4)]
        _drive(gw, clock)
        for h in handles:
            assert h.status == "finished"
            assert h.tokens == sim_tokens(h.prompt, 30)
            s = streams[h.gid]
            cut = max((i for i, (t, d) in enumerate(s)
                       if t is None and not d), default=-1)
            assert [t for t, d in s[cut + 1:] if t is not None] == h.tokens
        assert gw.replica("bad").state == "quarantined"
        assert any(ev["kind"] == "crash"
                   for ev in gw.replica("bad").engine.injected())

    def test_stall_window_resumes(self):
        """A stall shorter than the quarantine threshold: the engine
        freezes, then resumes and finishes its own work — no quarantine,
        no replay."""
        clock = SimClock()
        gw, _ = _gw(clock, stall_threshold_s=50.0)
        plan = FaultPlan([Fault("stall", at_s=1.0, duration_s=3.0)])
        eng = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(FaultyEngine(eng, plan, clock, replica="s"), "s")
        h = gw.submit([5, 6], 40)
        _drive(gw, clock)
        assert h.status == "finished" and h.replays == 0
        assert h.tokens == sim_tokens([5, 6], 40)
        assert gw.replica("s").state == "active"

    def test_slow_is_alive_but_straggling(self):
        """slow delivers ~1/factor of the token rate but emits liveness
        ticks — the stall health-check must NOT quarantine a straggler."""
        clock = SimClock()
        gw, _ = _gw(clock, stall_threshold_s=1.0)   # hair-trigger
        plan = FaultPlan([Fault("slow", at_s=0.0, factor=8)])
        eng = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(FaultyEngine(eng, plan, clock, replica="slow"),
                       "slow")
        h = gw.submit([3], 4)
        ticks = 0
        while gw.pending():
            gw.step()
            clock.advance(0.25)
            ticks += 1
            assert ticks < 500
        assert h.status == "finished"
        assert h.tokens == sim_tokens([3], 4)
        assert gw.replica("slow").state == "active"     # never benched
        assert ticks >= 8 * 4                           # actually slow

    def test_dispatch_error_window_and_count(self):
        clock = SimClock()
        plan = FaultPlan([Fault("dispatch_error", at_s=0.0,
                                duration_s=10.0, count=2)])
        eng = FaultyEngine(SimEngine(max_slots=2), plan, clock)
        with pytest.raises(TransientDispatchError):
            eng.add_request([1], 2)
        with pytest.raises(TransientDispatchError):
            eng.add_request([1], 2)
        rid = eng.add_request([1], 2)             # count exhausted
        assert isinstance(rid, int)
        clock.advance(20.0)                       # window over anyway
        eng.add_request([2], 2)
        assert [e["kind"] for e in eng.injected()] == ["dispatch_error",
                                                       "dispatch_error"]

    def test_warmup_fail_count(self):
        clock = SimClock()
        plan = FaultPlan([Fault("warmup_fail", count=1)])
        eng = FaultyEngine(SimEngine(max_slots=2), plan, clock)
        with pytest.raises(FaultInjectionError):
            eng.warmup()
        report = eng.warmup()                     # second call succeeds
        assert report["programs"] > 0 and eng.warmed

    def test_garble_raises_after_partial_delivery(self):
        """The truncated/garbled-stream fault: step forwards the tick
        (a partial prefix reaches the consumer) then raises
        StreamCorruption — and only ``count`` times."""
        clock = SimClock()
        plan = FaultPlan([Fault("garble", at_s=0.0, count=1)])
        eng = FaultyEngine(SimEngine(max_slots=2), plan, clock)
        got = []
        eng.add_request([4, 4], 5,
                        on_token=lambda r, t, d: got.append(t))
        with pytest.raises(StreamCorruption):
            eng.step()
        assert got == sim_tokens([4, 4], 5)[:len(got)] and got
        for _ in range(10):                       # count spent: clean now
            eng.step()
        assert eng.pop_finished()

    def test_delegation_preserves_engine_surface(self):
        clock = SimClock()
        inner = SimEngine(max_slots=3, tracer=SimTracer(clock),
                          prompt_buckets=(4, 8))
        eng = FaultyEngine(inner, FaultPlan(), clock)
        assert eng.tracer is inner.tracer
        assert eng.compile_grid() == inner.compile_grid()
        assert len(eng._free_slots()) == 3
        assert eng.S == 3 and eng._queue == []
        rid = eng.add_request([1, 2], 3)
        assert eng.pending()
        assert eng.cancel(rid) and not eng.pending()


class TestSimEngineModes:
    def test_stall_mode_counts_down(self):
        eng = SimEngine(max_slots=1)
        got = []
        eng.add_request([2], 3, on_token=lambda r, t, d: got.append(t))
        eng.stall(3)
        for _ in range(3):
            eng.step()
        assert got == []
        for _ in range(3):
            eng.step()
        assert got == sim_tokens([2], 3)

    def test_slow_mode_emits_liveness(self):
        clock = SimClock()
        tr = SimTracer(clock)
        eng = SimEngine(max_slots=1, tracer=tr)
        eng.add_request([7], 2)
        eng.slow(4)
        before = len(tr.events("tick"))
        for _ in range(3):
            eng.step()
            clock.advance(1.0)
        assert not eng.pop_finished()             # nothing served yet
        assert len(tr.events("tick")) > before    # but visibly alive
        eng.step()                                # 4th call: real round
        eng.slow(1)
        eng.step()
        assert eng.pop_finished()

    def test_flaky_mode_raises_then_recovers(self):
        eng = SimEngine(max_slots=1)
        eng.flaky(2)
        for _ in range(2):
            with pytest.raises(TransientDispatchError):
                eng.add_request([1], 1)
        assert isinstance(eng.add_request([1], 1), int)
        assert eng.metrics()["dispatch_errors"] == 2


class TestSeededReplay:
    def _run_chaos(self, seed):
        plan = FaultPlan([
            Fault("slow", at_s=5.0, duration_s=20.0, factor=10,
                  replica="r0"),
            Fault("crash", at_s=10.0, replica="r1"),
            Fault("dispatch_error", at_s=15.0, duration_s=4.0,
                  replica="r2"),
        ], seed=seed)
        clock = SimClock()
        pol = ResiliencePolicy(retry_budget=3, retry_backoff_s=0.25,
                               seed=seed, breaker_failures=3,
                               breaker_open_s=2.0, hedge=True,
                               hedge_ttft_frac=0.1, brownout=False)
        gw, tracer = _gw(clock, resilience=pol)
        wrappers = []
        for i in range(3):
            eng = SimEngine(max_slots=4, tracer=SimTracer(clock))
            w = FaultyEngine(eng, plan, clock, replica=f"r{i}")
            wrappers.append(w)
            gw.add_replica(w, f"r{i}")
        sim = TrafficSim(gw, clock, steady(2.0), dt=0.25, seed=seed,
                         ttft_deadline_s=30.0)
        rep = sim.run(40.0)
        rep["resilience_events"] = [
            (e["what"], e.get("replica"), e.get("gid"))
            for e in tracer.events("resilience")]
        rep["injected"] = [(w.replica, e["kind"], e["t"])
                           for w in wrappers for e in w.injected()]
        del rep["timeline"]
        return rep

    def test_whole_trajectory_replays_identically(self):
        """The chaos contract: one (plan seed, workload seed) value IS
        one trajectory — outcomes, TTFT percentiles, every resilience
        transition, every injected fault, in the same order."""
        a = self._run_chaos(11)
        b = self._run_chaos(11)
        assert a["dropped"] == [] and b["dropped"] == []
        assert a["outcomes"] == b["outcomes"]
        assert a["ttft_s"] == b["ttft_s"]
        assert a["resilience_events"] == b["resilience_events"]
        assert a["injected"] == b["injected"]
