"""Live ops endpoint (ISSUE 8): in-process HTTP round trips over all four
routes, the 503 stall flip, merged /metrics namespaces, and the
zero-cost-when-not-started contract."""

import json
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.ops_server import OpsServer, compute_probe
from paddle_tpu.telemetry import Tracer, TrainMonitor
from paddle_tpu.telemetry_ledger import RunLedger


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def _get_json(url, timeout=10):
    status, body = _get(url, timeout)
    return status, json.loads(body)


@pytest.fixture
def server():
    tr = Tracer()
    tr.tick("Engine", 0.01, queue_depth=0, active=1)
    tr.compile_event("Engine", ("prefill", 8), hit=False, wall_s=0.2)
    mon = TrainMonitor()
    mon.record_step(0.02, trainer="t", examples=4, tokens=8)
    led = RunLedger()
    led.record("compute", 0.25)
    led.record("data_wait", 0.05)
    srv = OpsServer(stall_threshold_s=60.0)
    srv.attach(tr, name="serving").attach(mon, name="train").attach(led)
    url = srv.start()
    yield srv, url, tr, mon, led
    srv.stop()


class TestEndpoints:
    def test_metrics_merges_all_namespaces(self, server):
        _srv, url, *_ = server
        status, body = _get(url + "/metrics")
        assert status == 200
        # serving + train + ledger + the server's own gauges, ONE scrape
        assert "paddle_tpu_serving_ticks 1" in body
        assert "paddle_tpu_train_train_steps 1" in body
        assert "paddle_tpu_ledger_goodput" in body
        assert "paddle_tpu_ledger_compute_seconds 0.25" in body
        assert "paddle_tpu_ops_uptime_seconds" in body
        assert "# TYPE paddle_tpu_ledger_goodput gauge" in body

    def test_ledger_endpoint_round_trips_snapshot(self, server):
        _srv, url, *_rest, led = server
        status, snap = _get_json(url + "/ledger")
        assert status == 200
        assert snap["buckets_s"]["compute"] == pytest.approx(0.25)
        assert set(snap["buckets_s"]) == set(
            led.snapshot()["buckets_s"])

    def test_trace_tail(self, server):
        _srv, url, tr, *_ = server
        status, out = _get_json(url + "/trace?n=10")
        assert status == 200
        assert set(out["events"]) == {"serving", "train"}
        kinds = [e["kind"] for e in out["events"]["serving"]]
        assert "tick" in kinds and "compile" in kinds
        # kind filter + n cap
        _, out = _get_json(url + "/trace?n=1&kind=tick")
        assert [e["kind"] for e in out["events"]["serving"]] == ["tick"]

    def test_healthz_ok_then_unknown_route_404(self, server):
        _srv, url, *_ = server
        status, h = _get_json(url + "/healthz")
        assert status == 200 and h["ok"] and not h["stalled"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(url + "/nope")
        assert ei.value.code == 404
        assert "/metrics" in json.load(ei.value)["routes"]


class TestHealthStall:
    def test_healthz_flips_503_on_stall_and_recovers(self):
        mon = TrainMonitor()
        mon.record_step(0.01, trainer="t")
        srv = OpsServer(stall_threshold_s=0.2)
        srv.attach(mon, name="train")
        url = srv.start()
        try:
            status, h = _get_json(url + "/healthz")
            assert status == 200 and h["ok"]
            time.sleep(0.35)                     # simulated stall
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(url + "/healthz")
            assert ei.value.code == 503
            body = json.load(ei.value)
            assert body["stalled"] and not body["ok"]
            assert body["last_step_age_s"] > 0.2
            # a new step (or heartbeat) recovers without restart
            mon.record_step(0.01, trainer="t")
            status, h = _get_json(url + "/healthz")
            assert status == 200 and h["ok"]
            time.sleep(0.35)
            srv.heartbeat()                      # explicit liveness works too
            status, h = _get_json(url + "/healthz")
            assert status == 200
        finally:
            srv.stop()

    def test_healthz_probe_param_runs_compute_probe(self):
        srv = OpsServer(stall_threshold_s=60.0, probe_timeout_s=30.0)
        srv.attach(RunLedger())
        url = srv.start()
        try:
            srv.heartbeat()
            status, h = _get_json(url + "/healthz?probe=1", timeout=60)
            assert status == 200
            assert h["probe"]["ok"] and h["probe"]["devices"] >= 1
            # the probe is a ROUND TRIP: it fetched a real matmul value
            assert h["probe"]["value"] == pytest.approx(256.0)
        finally:
            srv.stop()


class TestLifecycle:
    def test_not_started_binds_nothing(self):
        srv = OpsServer(port=0)
        assert srv._httpd is None                # construction is passive
        srv.attach(RunLedger())
        assert srv._httpd is None

    def test_start_idempotent_and_stop_closes(self):
        srv = OpsServer()
        srv.attach(RunLedger())
        url = srv.start()
        assert srv.start() == url                # second start is a no-op
        status, _ = _get_json(url + "/ledger")
        assert status == 200
        srv.stop()
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            _get(url + "/ledger", timeout=2)

    def test_ledger_404_when_none_attached(self):
        srv = OpsServer()
        srv.attach(TrainMonitor(), name="train")
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(url + "/ledger")
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_attach_rejects_unknown(self):
        with pytest.raises(TypeError):
            OpsServer().attach(object())

    def test_attach_engine_picks_up_its_tracer(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import ContinuousBatchingEngine
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        tr = Tracer()
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=16, prompt_buckets=[8],
                                       tracer=tr)
        tr.tick("ContinuousBatchingEngine", 0.01, queue_depth=0)
        srv = OpsServer()
        srv.attach(eng, name="cb")
        url = srv.start()
        try:
            _status, out = _get_json(url + "/trace?n=5")
            assert "cb.tracer" in out["events"]
            _status, body = _get(url + "/metrics")
            assert "ticks 1" in body             # engine registry exposition
        finally:
            srv.stop()


def test_compute_probe_times_out_cleanly(monkeypatch):
    import paddle_tpu.ops_server as om

    real_thread = om.threading.Thread

    class Hung(real_thread):
        def __init__(self, *a, target=None, **kw):
            super().__init__(*a, target=lambda: time.sleep(30), **kw)

    monkeypatch.setattr(om.threading, "Thread", Hung)
    out = compute_probe(timeout_s=0.2)
    assert not out["ok"] and "timed out" in out["error"]
