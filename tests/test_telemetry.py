"""Serving telemetry subsystem (paddle_tpu/telemetry.py + the engine
wiring in serving.py / serving_paged.py, the unified utils/stats.py
registry, and the profiler satellites).

The tentpole contract under test (ISSUE 2 acceptance): a mixed workload
through RaggedPagedContinuousBatchingEngine with tracing ON yields
per-tick events whose summed packed-token counts exactly reconcile with
tokens emitted + prefill tokens consumed; compile-cache events show ≥1
miss then only hits for repeated shapes; everything round-trips through
the JSONL and Prometheus exports.  With tracing OFF (the default) the
engines compile the exact same programs and never touch a tracer lock."""

import json
import logging
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import telemetry
from paddle_tpu.jit.bucketing import select_bucket
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                RaggedPagedContinuousBatchingEngine)
from paddle_tpu.telemetry import Tracer
from paddle_tpu.utils import stats


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


PROMPTS = [[5, 17, 3], [40, 2], [9, 9, 9, 9, 9, 1], [61], [8, 30, 12, 4],
           [77, 13, 2, 5, 6, 7, 8]]
BUDGETS = [10, 4, 7, 12, 3, 8]


def _ragged(model, params, tracer=None, **kw):
    cfg = dict(max_slots=3, max_len=32, block_size=4,
               prompt_buckets=[8, 16], token_budget=12)
    cfg.update(kw)
    return RaggedPagedContinuousBatchingEngine(model, params,
                                               tracer=tracer, **cfg)


class TestEndToEnd:
    def test_tick_accounting_compiles_and_export_roundtrip(
            self, model_and_params, tmp_path):
        """THE acceptance test: exact per-tick packed-token accounting,
        compile miss→hit transition, JSONL + Prometheus round-trips."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)
        tr = Tracer()
        eng = _ragged(model, params, tracer=tr)
        rids = [eng.add_request(p, n) for p, n in zip(PROMPTS, BUDGETS)]
        got = eng.run_to_completion(max_ticks=300)
        assert sorted(got) == sorted(rids)

        # --- exact accounting: every packed row is a decode row (one
        # emitted token) or a prefill row (one padded prompt position
        # consumed); first tokens ride the last prefill row for free
        ticks = tr.events("tick")
        assert ticks, "no tick events emitted"
        dec = sum(e.get("decode_rows", 0) for e in ticks)
        pf = sum(e.get("prefill_tokens", 0) for e in ticks)
        toks = sum(len(v) for v in got.values())
        padded = sum(select_bucket(len(p), eng.buckets) for p in PROMPTS)
        assert dec == toks - len(PROMPTS)
        assert pf == padded
        # the same totals flow through the per-tick counter deltas
        assert sum(e["tokens_emitted"] for e in ticks) == toks
        assert sum(e["requests_finished"] for e in ticks) == len(PROMPTS)
        # budget is never exceeded and utilization fields are coherent
        for e in ticks:
            used = e.get("budget_used", 0)
            assert used <= e.get("token_budget", eng.token_budget)
            assert used == e.get("decode_rows", 0) + e.get(
                "prefill_tokens", 0)

        # --- compile cache: fresh model ⇒ ≥1 miss ring event with wall
        # time; a second engine with identical shapes fetches ONLY hits
        # (counter-only — no ring events, so steady state can't evict
        # tick history)
        misses = tr.events("compile")
        assert len(misses) >= 1
        assert all(not e["hit"] and e["wall_s"] > 0 for e in misses)
        assert int(tr.registry.value("compile_hits")) > 0
        tr2 = Tracer()
        eng2 = _ragged(model, params, tracer=tr2)
        eng2.add_request(PROMPTS[0], 6)
        eng2.run_to_completion(max_ticks=100)
        assert tr2.events("compile") == []     # no misses, hits no events
        assert int(tr2.registry.value("compile_hits")) > 0
        assert eng2.metrics()["compile_misses"] == 0
        assert eng2.metrics()["compile_hits"] > 0

        # --- JSONL round-trip: every event survives byte-identical
        path = tmp_path / "events.jsonl"
        n = tr.dump_jsonl(str(path))
        lines = [json.loads(ln) for ln in
                 path.read_text().splitlines() if ln]
        assert len(lines) == n
        evs = [ln for ln in lines if ln["kind"] != "timeline"]
        assert evs == tr.events()
        tls = [ln for ln in lines if ln["kind"] == "timeline"]
        assert {t["rid"] for t in tls} == set(rids)

        # --- Prometheus round-trip: counters parse back to the source
        text = tr.prometheus_text()
        vals = {ln.split()[0]: ln.split()[1]
                for ln in text.splitlines()
                if ln and not ln.startswith("#") and "{" not in ln}
        assert int(vals["paddle_tpu_serving_compile_misses"]) == len(misses)
        assert int(vals["paddle_tpu_serving_requests_retired"]) == len(rids)
        assert "paddle_tpu_serving_tick_seconds_count" in vals
        assert int(vals["paddle_tpu_serving_tick_seconds_count"]) \
            == len(ticks)
        etext = eng.prometheus_text()
        evals = {ln.split()[0]: ln.split()[1]
                 for ln in etext.splitlines()
                 if ln and not ln.startswith("#") and "{" not in ln}
        assert int(evals["paddle_tpu_serving_tokens_emitted"]) == toks
        assert float(evals["paddle_tpu_serving_mean_ttft_s"]) > 0

    def test_outputs_and_programs_identical_with_tracing(
            self, model_and_params):
        """Telemetry is a pure observer: token outputs are identical and
        NO additional programs exist with tracing on (same cache keys ⇒
        no extra operands ever reached a compiled program)."""
        model, params = model_and_params

        def run(tracer):
            eng = _ragged(model, params, tracer=tracer)
            rids = [eng.add_request(p, n)
                    for p, n in zip(PROMPTS[:4], BUDGETS[:4])]
            got = eng.run_to_completion(max_ticks=200)
            return [got[r] for r in rids]

        model.__dict__.pop("_serving_programs", None)
        out_off = run(None)
        keys_off = set(model._serving_programs)
        out_on = run(Tracer())
        assert out_on == out_off
        assert set(model._serving_programs) == keys_off

    def test_tracing_off_takes_no_tracer_lock(self, model_and_params,
                                              monkeypatch):
        """Default engines never construct tracer state: every Tracer
        entry point is boobytrapped and a full serve completes anyway."""
        model, params = model_and_params

        def boom(*a, **kw):
            raise AssertionError("tracer touched with tracing off")

        for meth in ("emit", "tick", "compile_event", "request_event"):
            monkeypatch.setattr(Tracer, meth, boom)
        eng = _ragged(model, params)          # tracer=None default
        assert eng.tracer is None
        eng.add_request(PROMPTS[0], 5)
        got = eng.run_to_completion(max_ticks=100)
        assert len(got) == 1


class TestRequestTimelines:
    def test_preemption_span_and_single_ttft(self, model_and_params):
        """Satellite: the preemption/replay path.  The engine's
        on_token(rid, None, False) reset shows up as a closed
        ``preempted`` span, the timeline's TTFT counts queued → the
        SURVIVING first token once (no double-counted replayed prefill),
        and the TTFT histogram holds exactly one sample per retired
        request."""
        model, params = model_and_params
        tr = Tracer()
        signals = []
        eng = _ragged(model, params, max_slots=2, num_blocks=8,
                      prompt_buckets=[8], token_budget=10, tracer=tr)
        r0 = eng.add_request(PROMPTS[0], 14)
        r1 = eng.add_request(PROMPTS[1], 14,
                             on_token=lambda rid, tok, done:
                             signals.append((rid, tok)))
        got = eng.run_to_completion(max_ticks=500)
        assert eng.preemptions >= 1
        assert (r1, None) in signals           # documented reset signal

        tls = {tl.rid: tl for tl in tr.timelines()}
        victim = tls[r1] if tls[r1].replays else tls[r0]
        assert victim.replays >= 1
        spans = victim.spans()
        pre = [s for s in spans if s["name"] == "preempted"]
        assert pre, "preemption never became a span"
        assert all(s["end"] >= s["start"] for s in pre)
        # single final TTFT: first token of the surviving attempt only
        assert victim.first_token_at is not None
        assert victim.ttft_s == victim.first_token_at - victim.queued_at
        # the surviving attempt began after the last preemption
        assert victim.first_token_at > pre[-1]["start"]
        # histogram: one TTFT sample per retired request, not per attempt
        h = tr.registry.histogram("ttft_seconds").snapshot()
        assert h["count"] == len(got)
        # preemption surfaced in tick deltas too
        assert sum(e.get("preemptions", 0)
                   for e in tr.events("tick")) == eng.preemptions

    def test_timeline_token_accounting_and_percentiles(
            self, model_and_params):
        model, params = model_and_params
        tr = Tracer()
        eng = _ragged(model, params, tracer=tr)
        rids = [eng.add_request(p, n)
                for p, n in zip(PROMPTS[:3], BUDGETS[:3])]
        got = eng.run_to_completion(max_ticks=200)
        tls = {tl.rid: tl for tl in tr.timelines()}
        for rid in rids:
            tl = tls[rid]
            assert tl.tokens_delivered == len(got[rid])
            assert tl.retired_at is not None
            assert tl.queued_at <= tl.admitted_at <= tl.first_token_at
        s = tr.request_summary()
        assert s["requests_retired"] == len(rids)
        for key in ("ttft_s", "inter_token_s"):
            pct = s[key]
            assert pct is not None
            assert pct["p50"] <= pct["p95"] <= pct["p99"] <= pct["max"]

    def test_chrome_trace_contract(self, model_and_params):
        """{"traceEvents": [...]} with ticks/compiles as X events and
        request rows — the same shape tools/trace_to_chrome.py emits."""
        model, params = model_and_params
        tr = Tracer()
        eng = _ragged(model, params, tracer=tr)
        eng.add_request(PROMPTS[0], 6)
        eng.run_to_completion(max_ticks=100)
        ct = tr.to_chrome_trace()
        assert set(ct) == {"traceEvents", "displayTimeUnit"}
        evs = ct["traceEvents"]
        phases = {e["ph"] for e in evs}
        assert "X" in phases
        ticks = [e for e in evs if e["name"] == "tick"]
        assert ticks and all(e["tid"] == "scheduler" for e in ticks)
        req_rows = {e["tid"] for e in evs
                    if str(e.get("tid", "")).startswith("req:")}
        assert req_rows
        spans = [e for e in evs if e.get("cat") == "request"
                 and e["ph"] == "X"]
        assert {"queued", "prefill", "decode"} <= {e["name"] for e in spans}
        json.dumps(ct)                         # fully serializable


class TestRingBufferAndStorm:
    def test_ring_buffer_bounds_and_drop_count(self):
        tr = Tracer(capacity=8)
        for i in range(20):
            tr.emit("tick", i=i)
        assert len(tr.events()) == 8
        assert tr.events_dropped == 12
        assert tr.events()[0]["i"] == 12       # oldest dropped first

    def test_recompile_storm_warns_once(self, model_and_params, caplog):
        """Post-warmup compile misses past the threshold log ONE warning
        (the ragged engine naturally compiles a wider table-cols bucket
        mid-run as decode depth grows)."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)
        tr = Tracer(recompile_warn_threshold=1)
        eng = _ragged(model, params, tracer=tr)
        for p, n in zip(PROMPTS, BUDGETS):
            eng.add_request(p, n)
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.telemetry"):
            eng.run_to_completion(max_ticks=300)
        storm = [r for r in caplog.records
                 if "recompile storm" in r.getMessage()]
        assert len(storm) == 1
        assert tr.summary()["compile"]["post_warmup_misses"] >= 1


class TestStatsRegistry:
    def test_histogram_observe_and_percentile(self):
        reg = stats.StatRegistry()
        h = reg.histogram("lat", bounds=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 5
        assert snap["counts"] == (2, 1, 1, 1)
        assert abs(snap["sum"] - 5.56) < 1e-9
        assert h.percentile(0.5) == 0.1
        assert reg.histogram("lat") is h       # idempotent fetch
        reg.reset()
        assert h.snapshot()["count"] == 0

    def test_counter_gauge_kinds_in_exposition(self):
        reg = stats.StatRegistry()
        reg.add("reqs", 3)
        reg.set("depth", 7)
        reg.observe("lat", 0.02, bounds=(0.01, 0.1))
        text = stats.prometheus_text(reg, namespace="t")
        assert "# TYPE t_reqs counter" in text
        assert "t_reqs 3" in text
        assert "# TYPE t_depth gauge" in text
        assert "t_depth 7" in text
        assert '# TYPE t_lat histogram' in text
        assert 't_lat_bucket{le="0.1"} 1' in text
        assert 't_lat_bucket{le="+Inf"} 1' in text
        assert "t_lat_count 1" in text
        # invalid chars sanitized, extras exported as gauges
        text2 = stats.prometheus_text(reg, namespace="t",
                                      extra_gauges={"mean ttft.s": 0.5})
        assert "t_mean_ttft_s 0.5" in text2

    def test_global_registry_backcompat(self):
        stats.stat_registry().reset("X_tel_t")
        stats.stat_add("X_tel_t", 2)
        stats.stat_sub("X_tel_t", 1)
        assert stats.get_stat("X_tel_t") == 1
        assert "X_tel_t" in stats.get_all_stats()


class TestMetricsSchema:
    def test_metrics_match_schema(self, model_and_params):
        """Satellite: every metrics() key is schema-documented with the
        right python type, and the PR-1 backward-compatible keys stay."""
        model, params = model_and_params
        eng = _ragged(model, params, enable_prefix_cache=True)
        eng.add_request(PROMPTS[0], 4)
        eng.run_to_completion(max_ticks=100)
        m = eng.metrics()
        schema = type(eng).metrics_schema()
        assert set(m) <= set(schema), set(m) - set(schema)
        for k, v in m.items():
            kind, pytype = schema[k]
            assert kind in ("counter", "gauge")
            assert isinstance(v, pytype), (k, type(v))
        for k in ("requests_finished", "tokens_emitted", "mean_ttft_s",
                  "mean_latency_s", "tokens_per_sec", "blocks_in_use",
                  "preemptions", "ragged_steps", "mixed_steps",
                  "blocks_cached", "prefix_hits"):
            assert k in m

    def test_plain_engine_schema(self, model_and_params):
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8])
        eng.add_request(PROMPTS[0], 4)
        eng.run_to_completion(max_ticks=100)
        m = eng.metrics()
        schema = ContinuousBatchingEngine.metrics_schema()
        # exact coverage, minus the documented-conditional memory pair
        # (present only with attach_memory — test_telemetry_memory.py
        # pins both sides of that conditionality)
        assert set(schema) - set(m) == {"memory_device_bytes",
                                        "memory_host_bytes"}
        assert set(m) <= set(schema)
        assert m["requests_finished"] == 1
        assert m["compile_misses"] >= 0


class TestBucketizeInstrumentation:
    def test_bucket_compiles_counted_and_traced(self):
        import jax.numpy as jnp
        from paddle_tpu.jit.bucketing import bucketize
        stats.stat_registry().reset("bucketize_bucket_compiles")
        tr = Tracer()
        fn = bucketize(lambda x: x * 2, buckets=(4, 8), tracer=tr)
        fn(jnp.ones((1, 3)))
        fn(jnp.ones((1, 2)))                   # same bucket: hit
        fn(jnp.ones((1, 7)))                   # new bucket: compile
        assert stats.get_stat("bucketize_bucket_compiles") == 2
        assert fn.bucket_calls == {4: 2, 8: 1}
        comp = tr.events("compile")                # ring: misses only
        assert len(comp) == 2 and all(not e["hit"] for e in comp)
        assert int(tr.registry.value("compile_hits")) == 1
        assert all(e["key"].startswith("bucketize:") for e in comp)


class TestProfilerSatellites:
    def test_reset_and_snapshot(self):
        from paddle_tpu import profiler
        profiler.reset_profiler()
        with profiler.RecordEvent("tel_test_evt"):
            pass
        snap = profiler.snapshot_events()
        assert snap["tel_test_evt"][0] == 1
        assert "tel_test_evt" in profiler.summary()
        rows = stats.op_summary()
        assert any(r[0] == "tel_test_evt" for r in rows)
        profiler.reset_profiler()
        assert profiler.snapshot_events() == {}

    def test_record_event_thread_safety(self):
        """Concurrent RecordEvent exits from callback threads must not
        lose counts (the _events defaultdict is lock-guarded now)."""
        from paddle_tpu import profiler
        profiler.reset_profiler()

        def work():
            for _ in range(200):
                with profiler.RecordEvent("tel_mt_evt"):
                    pass

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert profiler.snapshot_events()["tel_mt_evt"][0] == 800
        profiler.reset_profiler()

    def test_stop_profiler_summary_routing(self, tmp_path, caplog,
                                           monkeypatch):
        """stop_profiler reports through on_summary / logging — stdout
        stays clean (the no-print lint pins the source side)."""
        from paddle_tpu import profiler
        profiler.reset_profiler()
        seen = []
        monkeypatch.setattr(profiler.jax.profiler, "start_trace",
                            lambda d: None)
        monkeypatch.setattr(profiler.jax.profiler, "stop_trace",
                            lambda: None)
        profiler.start_profiler(str(tmp_path / "p1"))
        profiler.stop_profiler(on_summary=seen.append)
        assert len(seen) == 1 and "Event" in seen[0]
        with caplog.at_level(logging.INFO, logger="paddle_tpu.profiler"):
            profiler.start_profiler(str(tmp_path / "p2"))
            profiler.stop_profiler()
        assert any("trace written" in r.getMessage()
                   for r in caplog.records)
