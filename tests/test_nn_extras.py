"""nn parity extras: unpool, zeropad, hsigmoid, margin CE, class-center
sampling, gather_tree, beam search decode, spectral_norm."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestUnpoolPad:
    def test_max_unpool2d_inverts_pool(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.standard_normal((2, 3, 8, 8))
                             .astype(np.float32))
        pooled, idx = F.max_pool2d(x, kernel_size=2, stride=2,
                                   return_mask=True)
        up = F.max_unpool2d(pooled, idx, kernel_size=2, stride=2)
        # every pooled max lands back at its original coordinate
        orig = np.asarray(x._data)
        rec = np.asarray(up._data)
        assert rec.shape == orig.shape
        nz = rec != 0
        np.testing.assert_allclose(rec[nz], orig[nz])
        assert nz.sum() == 2 * 3 * 4 * 4
        layer = nn.MaxUnPool2D(kernel_size=2, stride=2)
        rec2 = layer(pooled, idx)
        np.testing.assert_allclose(np.asarray(rec2._data), rec)

    def test_zeropad2d(self):
        x = paddle.to_tensor(np.ones((1, 1, 2, 2), np.float32))
        out = np.asarray(F.zeropad2d(x, [1, 2, 3, 4])._data)
        assert out.shape == (1, 1, 2 + 3 + 4, 2 + 1 + 2)
        assert out.sum() == 4.0 and out[0, 0, 3, 1] == 1.0


class TestHSigmoid:
    def test_matches_bruteforce(self):
        """Oracle: explicitly walk the heap tree and sum BCE terms."""
        rng = np.random.RandomState(1)
        N, D, C = 5, 6, 7
        x = rng.standard_normal((N, D)).astype(np.float32)
        w = rng.standard_normal((C - 1, D)).astype(np.float32)
        b = rng.standard_normal((C - 1,)).astype(np.float32)
        lbl = rng.randint(0, C, N)
        out = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lbl), C,
                              paddle.to_tensor(w), paddle.to_tensor(b))
        got = np.asarray(out._data).ravel()

        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))

        for n in range(N):
            node = lbl[n] + C - 1
            path = []
            while node > 0:
                parent = (node - 1) // 2
                path.append((parent, 1.0 if node == 2 * parent + 2 else 0.0))
                node = parent
            loss = 0.0
            for p, code in path:
                z = x[n] @ w[p] + b[p]
                pr = sigmoid(z)
                loss += -(code * np.log(pr) + (1 - code) * np.log(1 - pr))
            np.testing.assert_allclose(got[n], loss, rtol=1e-4)

    def test_layer_trains(self):
        paddle.seed(0)
        rng = np.random.RandomState(2)
        xv = rng.standard_normal((64, 8)).astype(np.float32)
        x = paddle.to_tensor(xv)
        y = paddle.to_tensor(np.argmax(xv[:, :6], axis=1))  # learnable labels
        layer = nn.HSigmoidLoss(8, 6)
        opt = paddle.optimizer.Adam(0.1, parameters=layer.parameters())
        first = None
        for _ in range(60):
            loss = layer(x, y).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
        assert float(loss) < first / 2, (first, float(loss))


class TestMarginCE:
    def test_matches_manual_formula(self):
        rng = np.random.RandomState(3)
        N, C = 4, 5
        cos = np.clip(rng.uniform(-0.9, 0.9, (N, C)), -1, 1).astype(np.float32)
        lbl = rng.randint(0, C, N)
        loss, soft = F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(lbl), margin1=1.0,
            margin2=0.3, margin3=0.1, scale=10.0, return_softmax=True,
            reduction=None)
        theta = np.arccos(cos[np.arange(N), lbl])
        tgt = np.cos(theta + 0.3) - 0.1
        adj = cos.copy()
        adj[np.arange(N), lbl] = tgt
        z = adj * 10.0
        logp = z - np.log(np.exp(z).sum(1, keepdims=True))
        ref = -logp[np.arange(N), lbl]
        np.testing.assert_allclose(np.asarray(loss._data).ravel(), ref,
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(soft._data), np.exp(logp),
                                   rtol=1e-4)

    def test_group_raises(self):
        with pytest.raises(ValueError, match="GSPMD|shard"):
            F.margin_cross_entropy(paddle.to_tensor(np.zeros((2, 3), np.float32)),
                                   paddle.to_tensor(np.array([0, 1])),
                                   group="data")


class TestClassCenterSample:
    def test_contract(self):
        paddle.seed(11)
        lbl = np.array([2, 9, 2, 31, 9], np.int64)
        remapped, sampled = F.class_center_sample(
            paddle.to_tensor(lbl), num_classes=40, num_samples=8)
        s = np.asarray(sampled._data)
        r = np.asarray(remapped._data)
        assert len(s) == 8 and len(set(s.tolist())) == 8
        assert np.all(np.diff(s) > 0)           # sorted
        for pos in {2, 9, 31}:
            assert pos in s                      # positives survive
        # remapped labels point at their class's position in `sampled`
        np.testing.assert_array_equal(s[r], lbl)


class TestGatherTreeAndBeam:
    def test_gather_tree_oracle(self):
        ids = np.array([[[2, 5]], [[6, 1]], [[3, 8]]], np.int64)   # (T=3,B=1,K=2)
        parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
        out = np.asarray(F.gather_tree(paddle.to_tensor(ids),
                                       paddle.to_tensor(parents))._data)
        # beam 0 at t=2 came from parent 0 (t=1 beam 0 ← parent 1 at t=0)
        np.testing.assert_array_equal(out[:, 0, 0], [5, 6, 3])
        np.testing.assert_array_equal(out[:, 0, 1], [2, 1, 8])

    def test_beam_search_finds_argmax_sequence(self):
        """Cell with state-independent fixed logits: beam search must return
        the top-probability token at every step, better than greedy ties."""
        V, K = 6, 3

        class FixedCell(nn.Layer):
            def __init__(self):
                super().__init__()
                logits = np.full((V,), -5.0, np.float32)
                logits[4] = 2.0
                logits[1] = 1.0
                self.logits = logits

            def forward(self, inputs, states):
                B = inputs.shape[0]
                out = paddle.to_tensor(np.tile(self.logits, (B, 1)))
                return out, states

        cell = FixedCell()
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=5,
                                   beam_size=K)
        init = {"h": jnp.zeros((2, 4), jnp.float32)}  # batch of 2
        ids, logp = nn.dynamic_decode(dec, inits=init, max_step_num=4)
        arr = np.asarray(ids._data)                   # (B, K, T)
        assert arr.shape[0] == 2 and arr.shape[1] == K
        # the best beam repeats token 4 (highest prob, never the end token)
        np.testing.assert_array_equal(arr[0, 0], [4] * arr.shape[2])
        # scores sorted across beams
        lp = np.asarray(logp._data)
        assert np.all(np.diff(lp, axis=1) <= 1e-6)


class TestSpectralNormHook:
    def test_weight_normalized(self):
        paddle.seed(0)
        lin = nn.Linear(6, 4)
        lin.weight._data = lin.weight._data * 10.0   # big spectral norm
        nn.spectral_norm(lin, n_power_iterations=20)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .standard_normal((2, 6)).astype(np.float32))
        lin(x)  # runs the pre-hook
        w = np.asarray(lin.weight._data)
        s = np.linalg.svd(w, compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=1e-2)
        # trainable param is the raw weight, not the normalized view
        names = [n for n, _ in lin.named_parameters()]
        assert any("weight_orig" in n for n in names)
        assert not any(n == "weight" for n in names)


class TestNNExtrasReviewRegressions:
    def test_hsigmoid_bias_false(self):
        layer = nn.HSigmoidLoss(8, 6, bias_attr=False)
        assert layer.bias is None
        out = layer(paddle.to_tensor(np.ones((2, 8), np.float32)),
                    paddle.to_tensor(np.array([1, 3])))
        assert np.isfinite(np.asarray(out._data)).all()

    def test_spectral_norm_persists_power_iteration(self):
        """iters=1 must converge ACROSS calls (u/v written back), not stay
        at the random-init estimate forever."""
        paddle.seed(3)
        lin = nn.Linear(6, 4)
        lin.weight._data = lin.weight._data * 10.0
        nn.spectral_norm(lin, n_power_iterations=1)
        x = paddle.to_tensor(np.zeros((1, 6), np.float32))
        for _ in range(30):   # each forward advances the power iteration
            lin(x)
        s = np.linalg.svd(np.asarray(lin.weight._data), compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=2e-2)

    def test_margin_ce_saturated_cosine_grad_finite(self):
        cos = paddle.to_tensor(np.array([[1.0, -0.2], [-1.0, 0.3]],
                                        np.float32), stop_gradient=False)
        lbl = paddle.to_tensor(np.array([0, 0]))
        loss = F.margin_cross_entropy(cos, lbl, margin2=0.3, scale=4.0)
        loss.backward()
        assert np.isfinite(np.asarray(cos.grad._data)).all()

    def test_hsigmoid_per_sample_path_table(self):
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        lbl = paddle.to_tensor(np.array([0, 1, 2]))
        w = paddle.to_tensor(np.zeros((5, 4), np.float32))
        ptab = paddle.to_tensor(np.array([[0, -1], [1, -1], [2, 3]], np.int64))
        pcode = paddle.to_tensor(np.array([[1, 0], [0, 0], [1, 0]], np.float32))
        out = np.asarray(F.hsigmoid_loss(x, lbl, 6, w, None, path_table=ptab,
                                         path_code=pcode)._data).ravel()
        # zero weights → every BCE term is log(2); row path lengths 1,1,2
        np.testing.assert_allclose(out, np.log(2) * np.array([1, 1, 2]),
                                   rtol=1e-5)
        with pytest.raises(ValueError, match="per sample"):
            F.hsigmoid_loss(x, lbl, 6, w, None,
                            path_table=paddle.to_tensor(
                                np.zeros((6, 2), np.int64)),
                            path_code=paddle.to_tensor(
                                np.zeros((6, 2), np.float32)))

    def test_dynamic_decode_forwards_kwargs(self):
        seen = {}

        class KwCell(nn.Layer):
            def forward(self, inputs, states):
                return paddle.to_tensor(
                    np.zeros((inputs.shape[0], 4), np.float32)), states

        class KwDecoder(nn.BeamSearchDecoder):
            def step(self, time, inputs, states, **kw):
                seen.update(kw)
                return super().step(time, inputs, states)

        dec = KwDecoder(KwCell(), start_token=0, end_token=3, beam_size=2)
        nn.dynamic_decode(dec, inits=jnp.zeros((1, 4), jnp.float32),
                          max_step_num=1, encoder_output="ctx")
        assert seen.get("encoder_output") == "ctx"


class TestSpectralNormUnderJit:
    def test_uv_persist_through_jitted_steps(self):
        """round-2 review: u/v must be buffers so functionalize writes them
        back — a jitted training loop with power_iters=1 must converge."""
        from paddle_tpu.jit.functional import make_train_step
        paddle.seed(4)
        lin = nn.Linear(6, 4)
        lin.weight._data = lin.weight._data * 10.0
        nn.spectral_norm(lin, n_power_iterations=1)
        names = [n for n, _ in lin.named_buffers()] \
            if hasattr(lin, "named_buffers") else []
        opt = paddle.optimizer.SGD(0.0, parameters=lin.parameters())  # lr 0
        step, state = make_train_step(
            lin, lambda o, y: (o ** 2).mean() * 0.0 + o.mean() * 0.0, opt)
        x = jnp.zeros((2, 6), jnp.float32)
        u0 = None
        for i in range(25):
            state, _ = step(state, jax.random.key(i), np.float32(0.0),
                            (x,), (jnp.zeros((2, 4), jnp.float32),))
            ukey = [k for k in state["buffers"] if "weight_u" in k][0]
            if u0 is None:
                u0 = np.asarray(state["buffers"][ukey]).copy()
        u_last = np.asarray(state["buffers"][ukey])
        assert not np.allclose(u0, u_last), "u did not advance under jit"
        # weight_orig is unchanged (lr=0) but the sigma estimate converged:
        # eager forward now normalizes to ~unit spectral norm
        from paddle_tpu.jit.functional import sync_state_to_layer
        sync_state_to_layer(lin, state)
        lin(paddle.to_tensor(np.zeros((1, 6), np.float32)))
        s = np.linalg.svd(np.asarray(lin.weight._data), compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=5e-2)

    def test_class_center_sample_validation(self):
        with pytest.raises(ValueError, match="num_samples"):
            F.class_center_sample(paddle.to_tensor(np.array([0])), 4, 9)
