"""Tests for the advertised API modules (VERDICT round-1 #9: every name in
``_LAZY`` must import and carry real behavior).

Oracles are numpy/brute-force recomputations (reference op_test.py style).
"""

import itertools
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_all_lazy_modules_import():
    for name in paddle._LAZY:
        mod = getattr(paddle, name)
        assert mod is not None, name


# ---------------------------------------------------------------------------
# fft / signal
# ---------------------------------------------------------------------------

class TestFFT:
    def test_fft_matches_numpy(self):
        x = np.random.RandomState(0).standard_normal((4, 32)).astype(np.float32)
        out = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out._data), np.fft.fft(x),
                                   rtol=1e-4, atol=1e-4)

    def test_rfft_irfft_roundtrip(self):
        x = np.random.RandomState(1).standard_normal((8, 64)).astype(np.float32)
        spec = paddle.fft.rfft(paddle.to_tensor(x))
        back = paddle.fft.irfft(spec, n=64)
        np.testing.assert_allclose(np.asarray(back._data), x, atol=1e-4)

    def test_norm_ortho_and_shift(self):
        x = np.random.RandomState(2).standard_normal((16,)).astype(np.float32)
        o = paddle.fft.fft(paddle.to_tensor(x), norm="ortho")
        np.testing.assert_allclose(np.asarray(o._data),
                                   np.fft.fft(x, norm="ortho"), rtol=1e-4,
                                   atol=1e-4)
        s = paddle.fft.fftshift(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(s._data), np.fft.fftshift(x))
        with pytest.raises(ValueError):
            paddle.fft.fft(paddle.to_tensor(x), norm="bogus")

    def test_fft2(self):
        x = np.random.RandomState(3).standard_normal((4, 8, 8)).astype(np.float32)
        out = paddle.fft.fft2(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out._data), np.fft.fft2(x),
                                   rtol=1e-3, atol=1e-3)

    def test_hfft2_matches_numpy_composition(self):
        # oracle: c2c over the leading axis first, then hermitian c2r over
        # the last (the order the reference's fftn_c2r kernel uses)
        rng = np.random.RandomState(9)
        x = (rng.standard_normal((4, 5)) + 1j * rng.standard_normal((4, 5))
             ).astype(np.complex64)
        out = paddle.fft.hfft2(paddle.to_tensor(x))
        ref = np.fft.hfft(np.fft.fft(x, axis=0), axis=-1)
        np.testing.assert_allclose(np.asarray(out._data), ref, rtol=1e-3,
                                   atol=1e-3)


class TestSignal:
    def test_frame_matches_oracle(self):
        x = np.arange(16, dtype=np.float32)
        out = paddle.signal.frame(paddle.to_tensor(x), frame_length=4,
                                  hop_length=2, axis=0)
        ref = np.stack([x[i:i + 4] for i in range(0, 13, 2)])
        np.testing.assert_array_equal(np.asarray(out._data), ref)

    def test_overlap_add_is_frame_adjoint(self):
        rng = np.random.RandomState(0)
        fr = rng.standard_normal((7, 4)).astype(np.float32)  # (F, L) axis=0
        out = paddle.signal.overlap_add(paddle.to_tensor(fr), hop_length=2,
                                        axis=0)
        ref = np.zeros(6 * 2 + 4, np.float32)
        for i in range(7):
            ref[i * 2:i * 2 + 4] += fr[i]
        np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-6)

    def test_stft_onesided_complex_raises(self):
        x = np.zeros((256,), np.complex64)
        with pytest.raises(ValueError, match="onesided"):
            paddle.signal.stft(paddle.to_tensor(x), n_fft=64)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(4)
        x = rng.standard_normal((2, 512)).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=128, hop_length=32,
                                  window=paddle.to_tensor(win))
        # padded len 640 → frames = 1 + (640-128)//32 = 17; bins = 128//2+1
        assert np.asarray(spec._data).shape == (2, 65, 17)
        back = paddle.signal.istft(spec, n_fft=128, hop_length=32,
                                   window=paddle.to_tensor(win), length=512)
        np.testing.assert_allclose(np.asarray(back._data), x, atol=1e-3)


# ---------------------------------------------------------------------------
# distribution
# ---------------------------------------------------------------------------

class TestDistribution:
    def test_normal_log_prob_and_kl(self):
        d = paddle.distribution.Normal(loc=1.0, scale=2.0)
        v = paddle.to_tensor(np.array([0.0, 1.0, 3.0], np.float32))
        lp = np.asarray(d.log_prob(v)._data)
        ref = -((np.array([0.0, 1.0, 3.0]) - 1.0) ** 2) / 8.0 \
            - np.log(2.0) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, ref, rtol=1e-5)
        q = paddle.distribution.Normal(loc=0.0, scale=1.0)
        kl = float(np.asarray(paddle.distribution.kl_divergence(d, q)._data))
        ref_kl = np.log(1.0 / 2.0) + (4.0 + 1.0) / 2.0 - 0.5
        np.testing.assert_allclose(kl, ref_kl, rtol=1e-5)

    def test_sampling_moments(self):
        paddle.seed(7)
        d = paddle.distribution.Normal(loc=3.0, scale=0.5)
        s = np.asarray(d.sample([20000])._data)
        assert abs(s.mean() - 3.0) < 0.05 and abs(s.std() - 0.5) < 0.05
        u = paddle.distribution.Uniform(low=-1.0, high=1.0)
        su = np.asarray(u.sample([20000])._data)
        assert su.min() >= -1.0 and su.max() < 1.0 and abs(su.mean()) < 0.05

    def test_categorical(self):
        paddle.seed(8)
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = paddle.distribution.Categorical(logits)
        s = np.asarray(d.sample([8000])._data)
        freq = np.bincount(s, minlength=3) / 8000.0
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)
        lp = np.asarray(d.log_prob(paddle.to_tensor(np.array([2]))). _data)
        np.testing.assert_allclose(lp, np.log(0.5), rtol=1e-4)
        ent = float(np.asarray(d.entropy()._data))
        np.testing.assert_allclose(
            ent, -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)),
            rtol=1e-4)


# ---------------------------------------------------------------------------
# sparse
# ---------------------------------------------------------------------------

class TestSparse:
    def test_coo_roundtrip_and_matmul(self):
        ind = np.array([[0, 0, 1, 2], [0, 2, 1, 0]])
        val = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        s = paddle.sparse.sparse_coo_tensor(ind, val, [3, 3])
        dense = np.zeros((3, 3), np.float32)
        dense[ind[0], ind[1]] = val
        np.testing.assert_array_equal(np.asarray(s.to_dense()._data), dense)
        assert s.nnz() == 4
        rhs = np.random.RandomState(0).standard_normal((3, 2)).astype(np.float32)
        out = paddle.sparse.matmul(s, paddle.to_tensor(rhs))
        np.testing.assert_allclose(np.asarray(out._data), dense @ rhs,
                                   rtol=1e-5, atol=1e-5)

    def test_csr_and_unary(self):
        crows = np.array([0, 2, 3, 4])
        cols = np.array([0, 2, 1, 0])
        vals = np.array([-1.0, 2.0, -3.0, 4.0], np.float32)
        s = paddle.sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
        r = paddle.sparse.relu(s)
        np.testing.assert_array_equal(
            np.asarray(r.values()._data), [0.0, 2.0, 0.0, 4.0])
        dense = np.asarray(s.to_dense()._data)
        ref = np.zeros((3, 3), np.float32)
        ref[[0, 0, 1, 2], [0, 2, 1, 0]] = vals
        np.testing.assert_array_equal(dense, ref)


# ---------------------------------------------------------------------------
# autograd: PyLayer + functional transforms
# ---------------------------------------------------------------------------

class TestPyLayer:
    def test_custom_backward_is_used(self):
        from paddle_tpu.autograd import PyLayer

        class ScaledTanh(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = paddle.tanh(x)
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor()
                return dy * (1 - y * y) * 10.0   # deliberately 10x

        x = paddle.to_tensor(np.array([0.3, -0.7], np.float32),
                             stop_gradient=False)
        out = ScaledTanh.apply(x)
        out.backward(paddle.to_tensor(np.ones(2, np.float32)))
        ref = (1 - np.tanh([0.3, -0.7]) ** 2) * 10.0
        np.testing.assert_allclose(np.asarray(x.grad._data), ref, rtol=1e-5)

    def test_multi_input_output(self):
        from paddle_tpu.autograd import PyLayer

        class MulAdd(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a * b, a + b

            @staticmethod
            def backward(ctx, g1, g2):
                a, b = ctx.saved_tensor()
                return g1 * b + g2, g1 * a + g2

        a = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        p, s = MulAdd.apply(a, b)
        (p + 2 * s).backward()
        np.testing.assert_allclose(np.asarray(a.grad._data), [3.0 + 2.0])
        np.testing.assert_allclose(np.asarray(b.grad._data), [2.0 + 2.0])


class TestAutogradFunctional:
    def test_multi_root_backward(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        y1 = (x * x).sum()
        y2 = (3 * x).sum()
        paddle.autograd.backward([y1, y2])
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   2 * np.array([1.0, 2.0]) + 3.0)

    def test_jacobian_hessian(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        jac = paddle.autograd.jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(np.asarray(jac._data),
                                   np.diag([2.0, 4.0, 6.0]), rtol=1e-5)
        hes = paddle.autograd.hessian(lambda t: (t * t).sum(), x)
        np.testing.assert_allclose(np.asarray(hes._data), 2 * np.eye(3),
                                   rtol=1e-5)

    def test_vjp_jvp(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        v = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        out, cot = paddle.autograd.vjp(lambda t: t * t, x, v)
        np.testing.assert_allclose(np.asarray(cot._data), [2.0, 0.0])
        out, tan = paddle.autograd.jvp(lambda t: t * t, x, v)
        np.testing.assert_allclose(np.asarray(tan._data), [2.0, 0.0])


# ---------------------------------------------------------------------------
# static facade, device, callbacks
# ---------------------------------------------------------------------------

class TestStatic:
    def test_data_and_accuracy(self):
        spec = paddle.static.data("x", [None, 4], "float32")
        assert spec.shape[-1] == 4
        pred = np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)
        lbl = np.array([1, 1])
        acc = paddle.static.accuracy(paddle.to_tensor(pred),
                                     paddle.to_tensor(lbl))
        np.testing.assert_allclose(float(np.asarray(acc._data)), 0.5)

    def test_ema_apply_restore(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(2, 2)
        ema = paddle.static.ExponentialMovingAverage(decay=0.5)
        w0 = np.array(lin.weight._data)
        ema.update(lin.parameters())
        lin.weight._data = lin.weight._data + 1.0
        ema.update()
        with ema.apply():
            applied = np.array(lin.weight._data)
        restored = np.array(lin.weight._data)
        np.testing.assert_allclose(restored, w0 + 1.0, rtol=1e-5)
        assert not np.allclose(applied, restored)

    def test_program_guard_and_executor(self):
        prog = paddle.static.Program()
        with paddle.static.program_guard(prog):
            assert paddle.static.default_main_program() is prog
        exe = paddle.static.Executor()
        out = exe.run(lambda a: a + 1,
                      feed={"x": np.zeros((2,), np.float32)})
        np.testing.assert_array_equal(out[0], np.ones((2,), np.float32))

    def test_append_backward_raises(self):
        with pytest.raises(RuntimeError):
            paddle.static.append_backward(None)


class TestDevice:
    def test_device_api(self):
        dev = paddle.device.get_device()
        assert ":" in dev
        assert paddle.device.cuda.device_count() == 0
        assert paddle.device.is_compiled_with_cuda() is False
        assert paddle.device.get_cudnn_version() is None
        types = paddle.device.get_all_device_type()
        assert "cpu" in types

    def test_callbacks_module(self):
        assert paddle.callbacks.EarlyStopping is not None
        assert paddle.callbacks.ModelCheckpoint is not None


# ---------------------------------------------------------------------------
# text: viterbi + datasets
# ---------------------------------------------------------------------------

def _viterbi_oracle(pot, trans, lengths, bos_eos):
    B, L, N = pot.shape
    scores, paths = [], []
    for b in range(B):
        ln = int(lengths[b])
        best, arg = -1e30, None
        for path in itertools.product(range(N), repeat=ln):
            s = pot[b, 0, path[0]]
            if bos_eos:
                s += trans[-1, path[0]]
            for t in range(1, ln):
                s += trans[path[t - 1], path[t]] + pot[b, t, path[t]]
            if bos_eos:
                s += trans[path[ln - 1], -2]
            if s > best:
                best, arg = s, path
        scores.append(best)
        paths.append(list(arg) + [0] * (int(lengths.max()) - ln))
    return np.array(scores, np.float32), np.array(paths)


class TestViterbi:
    @pytest.mark.parametrize("bos_eos", [False, True])
    def test_matches_bruteforce(self, bos_eos):
        rng = np.random.RandomState(5)
        B, L, N = 3, 5, 4
        pot = rng.standard_normal((B, L, N)).astype(np.float32)
        trans = rng.standard_normal((N, N)).astype(np.float32)
        lengths = np.array([5, 3, 1])
        scores, path = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            paddle.to_tensor(lengths), include_bos_eos_tag=bos_eos)
        ref_s, ref_p = _viterbi_oracle(pot, trans, lengths, bos_eos)
        np.testing.assert_allclose(np.asarray(scores._data), ref_s, rtol=1e-4)
        np.testing.assert_array_equal(np.asarray(path._data), ref_p)

    def test_decoder_layer(self):
        rng = np.random.RandomState(6)
        pot = rng.standard_normal((2, 4, 3)).astype(np.float32)
        trans = rng.standard_normal((3, 3)).astype(np.float32)
        dec = paddle.text.ViterbiDecoder(paddle.to_tensor(trans),
                                         include_bos_eos_tag=False)
        scores, path = dec(paddle.to_tensor(pot),
                           paddle.to_tensor(np.array([4, 4])))
        assert np.asarray(path._data).shape == (2, 4)


class TestTextDatasets:
    def test_uci_housing(self, tmp_path):
        rng = np.random.RandomState(0)
        rows = rng.rand(50, 14).astype(np.float32)
        f = tmp_path / "housing.data"
        with open(f, "w") as fh:
            for r in rows:
                fh.write(" ".join(f"{v:.6f}" for v in r) + "\n")
        train = paddle.text.UCIHousing(data_file=str(f), mode="train")
        test = paddle.text.UCIHousing(data_file=str(f), mode="test")
        assert len(train) == 40 and len(test) == 10
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_imikolov_ngram(self, tmp_path):
        f = tmp_path / "ptb.train.txt"
        f.write_text("the cat sat on the mat\nthe dog sat on the log\n")
        ds = paddle.text.Imikolov(data_file=str(f), data_type="NGRAM",
                                  window_size=3, mode="train",
                                  min_word_freq=1)
        assert len(ds) > 0
        assert ds[0].shape == (3,)

    def test_missing_file_raises(self):
        with pytest.raises(ValueError, match="data_file"):
            paddle.text.Imdb(data_file=None)


# ---------------------------------------------------------------------------
# incubate: LookAhead / ModelAverage / auto_checkpoint; L1Decay
# ---------------------------------------------------------------------------

class TestIncubate:
    def test_lookahead_sync_every_k(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(2, 2)
        sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        la = paddle.incubate.LookAhead(sgd, alpha=0.5, k=2)
        w0 = np.array(lin.weight._data)
        x = paddle.to_tensor(np.ones((4, 2), np.float32))
        for i in range(2):
            loss = (lin(x) ** 2).mean()
            loss.backward()
            la.step()
            la.clear_grad()
        # after k=2 steps, weights = slow + 0.5*(fast - slow): strictly
        # between the initial (slow) and what plain SGD would give (fast)
        w2 = np.array(lin.weight._data)
        assert not np.allclose(w2, w0)

    def test_model_average(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(2, 2)
        ma = paddle.incubate.ModelAverage(0.5, parameters=lin.parameters(),
                                          min_average_window=100)
        vals = []
        for i in range(3):
            lin.weight._data = lin.weight._data + 1.0
            ma.step()
            vals.append(np.array(lin.weight._data))
        cur = np.array(lin.weight._data)
        with ma.apply():
            avg = np.array(lin.weight._data)
        np.testing.assert_allclose(avg, np.mean(vals, axis=0), rtol=1e-5)
        np.testing.assert_allclose(np.array(lin.weight._data), cur)

    def test_model_average_across_window_restart(self):
        """After a window restart the average must stay the true mean of the
        folded samples (round-2 review: old total was double-counted)."""
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(1, 1, bias_attr=False)
        ma = paddle.incubate.ModelAverage(1.0, parameters=lin.parameters(),
                                          min_average_window=3,
                                          max_average_window=3)
        seen = []
        for v in [1.0, 2.0, 3.0, 4.0, 5.0]:
            lin.weight._data = np.full((1, 1), v, np.float32) * 0 + v
            ma.step()
            seen.append(v)
        # window restarted after the 3rd step; average covers the last
        # old-window (1,2,3) plus the live window (4,5) single-counted
        with ma.apply():
            avg = float(np.asarray(lin.weight._data).ravel()[0])
        np.testing.assert_allclose(avg, np.mean(seen), rtol=1e-5)

    def test_lookahead_inherited_entry_points(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(2, 2)
        sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        la = paddle.incubate.LookAhead(sgd, alpha=0.5, k=2)
        la.set_lr(0.05)
        assert la.get_lr() == pytest.approx(0.05)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        loss = (lin(x) ** 2).mean()
        loss.backward()
        la.minimize_step()  # the class alias must dispatch to LookAhead.step
        assert la._k_count == 1

    def test_auto_checkpoint_resume(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import train_epoch_range
        state = {"v": 0}
        saved = {}

        def save_fn(path):
            saved["v"] = state["v"]
            with open(path, "w") as f:
                f.write(str(state["v"]))

        def load_fn(path):
            state["v"] = int(open(path).read())

        ran = []
        for e in train_epoch_range(3, save_fn=save_fn, load_fn=load_fn,
                                   checkpoint_dir=str(tmp_path),
                                   save_checkpoint_inter=0):
            state["v"] = e
            ran.append(e)
        assert ran == [0, 1, 2]
        ran2 = []
        for e in train_epoch_range(5, save_fn=save_fn, load_fn=load_fn,
                                   checkpoint_dir=str(tmp_path),
                                   save_checkpoint_inter=0):
            ran2.append(e)
        assert ran2 == [3, 4]  # resumed past completed epochs
        assert state["v"] == 2  # restored from snapshot


class TestL1Decay:
    def test_l1_is_sign_gradient(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(2, 2, bias_attr=False)
        w0 = np.array(lin.weight._data)
        opt = paddle.optimizer.SGD(
            learning_rate=0.1, parameters=lin.parameters(),
            weight_decay=paddle.regularizer.L1Decay(0.5))
        # zero data gradient: update must be pure L1 shrink = lr*coeff*sign(w)
        lin.weight._grad = np.zeros_like(w0)
        import jax.numpy as jnp
        lin.weight._grad = jnp.zeros_like(lin.weight._data)
        opt.step()
        np.testing.assert_allclose(np.array(lin.weight._data),
                                   w0 - 0.1 * 0.5 * np.sign(w0), rtol=1e-5)


class TestSparseNativeOps:
    def test_sparse_add_stays_sparse(self):
        import jax
        ind1 = np.array([[0, 1], [0, 1]])
        ind2 = np.array([[0, 2], [0, 2]])
        a = paddle.sparse.sparse_coo_tensor(ind1, np.array([1., 2.], dtype=np.float32), [3, 3])
        b = paddle.sparse.sparse_coo_tensor(ind2, np.array([10., 20.], dtype=np.float32), [3, 3])
        out = paddle.sparse.add(a, b)
        assert paddle.sparse.is_sparse(out)
        ref = np.asarray(a.to_dense()._data) + np.asarray(b.to_dense()._data)
        np.testing.assert_array_equal(np.asarray(out.to_dense()._data), ref)
        # jit-safe: static nse bound
        f = jax.jit(lambda: paddle.sparse.add(a, b).to_dense()._data)
        np.testing.assert_array_equal(np.asarray(f()), ref)

    def test_executor_feed_by_name(self):
        exe = paddle.static.Executor()
        out = exe.run(lambda x, y: x - y,
                      feed={"y": np.ones(2, np.float32),
                            "x": np.full(2, 3.0, np.float32)})
        np.testing.assert_array_equal(out[0], np.full(2, 2.0, np.float32))

    def test_conll_mode_split(self, tmp_path):
        f = tmp_path / "words.txt"
        blocks = []
        for i in range(10):
            blocks.append(f"word{i}\nother{i}\n")
        f.write_text("\n".join(blocks) + "\n")
        tr = paddle.text.Conll05st(data_file=str(tmp_path), mode="train")
        te = paddle.text.Conll05st(data_file=str(tmp_path), mode="test")
        assert len(tr) == 8 and len(te) == 2


class TestTopLevelParity:
    def test_new_namespace_modules(self, tmp_path):
        assert paddle.compat.to_text(b"abc") == "abc"
        assert paddle.compat.to_bytes("abc") == b"abc"
        assert os.path.isdir(paddle.sysconfig.get_include())
        # hub over a local hubconf
        (tmp_path / "hubconf.py").write_text(
            "def tiny(width=4):\n"
            "    'a tiny model'\n"
            "    import paddle_tpu.nn as nn\n"
            "    return nn.Linear(width, 2)\n")
        assert paddle.hub.list(str(tmp_path)) == ["tiny"]
        assert "tiny model" in paddle.hub.help(str(tmp_path), "tiny")
        layer = paddle.hub.load(str(tmp_path), "tiny", width=3)
        assert layer.weight.shape[0] == 3
        with pytest.raises(ValueError, match="zero-egress"):
            paddle.hub.list("whatever", source="github")

    def test_batch_and_reader_decorators(self):
        r = lambda: iter(range(10))
        batches = list(paddle.batch(r, 3)())
        assert batches[0] == [0, 1, 2] and len(batches) == 4
        batches = list(paddle.batch(r, 3, drop_last=True)())
        assert len(batches) == 3
        buf = list(paddle.reader.buffered(r, 2)())
        assert buf == list(range(10))
        comp = list(paddle.reader.chain(r, r)())
        assert len(comp) == 20
        mapped = list(paddle.reader.xmap_readers(lambda x: x * 2, r, 2, 4,
                                                 order=True)())
        assert mapped == [2 * i for i in range(10)]

    def test_places_and_legacy_aliases(self):
        assert paddle.CPUPlace().is_cpu_place()
        with pytest.raises(RuntimeError):
            paddle.CUDAPlace(0)
        assert paddle.Model is not None
        assert paddle.ParamAttr is not None
        assert paddle.VarBase is paddle.Tensor
        assert paddle.in_dygraph_mode() is True
        assert paddle.get_cuda_rng_state() == []
        paddle.disable_signal_handler()
        t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        assert paddle.tolist(t) == [1.0, 2.0]
        assert tuple(paddle.t(paddle.to_tensor(
            np.zeros((2, 3), np.float32))).shape) == (3, 2)
        assert float(np.asarray(paddle.add_n(
            [t, t])._data)[0]) == 2.0


class TestInplaceOpsAutograd:
    def test_inplace_ops_keep_gradients(self):
        """round-2 review: *_ ops must _adopt so the tape's out_refs follow
        the mutated tensor (direct _data/_node assignment orphaned them)."""
        import paddle_tpu.tensor as T
        w = paddle.to_tensor(np.array([[2.0, 3.0]], np.float32),
                             stop_gradient=False)
        y = w * 4.0                       # recorded node
        T.squeeze_(y)                     # in-place on a non-leaf
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(w.grad._data), [[4.0, 4.0]])

        x = paddle.to_tensor(np.array([0.5], np.float32), stop_gradient=False)
        z = x * 2.0
        T.tanh_(z)
        z.backward()
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   2.0 * (1 - np.tanh(1.0) ** 2), rtol=1e-6)

    def test_sci_mode_forces_scientific(self):
        import paddle_tpu.tensor as T
        T.set_printoptions(sci_mode=True, precision=2)
        try:
            s = repr(np.array([1.5, 20.0]))
            assert "e+" in s or "e-" in s, s
        finally:
            np.set_printoptions(suppress=False, formatter=None, precision=8)


class TestNamespaceParityTail:
    def test_vision_top_level_exports(self):
        import paddle_tpu.vision as v
        for n in ("MNIST", "Cifar10", "Compose", "Normalize", "Flowers",
                  "DatasetFolder", "ImageFolder", "resnet50"):
            assert hasattr(v, n), n
        assert v.get_image_backend() == "numpy"

    def test_dataset_folder(self, tmp_path):
        import paddle_tpu.vision as v
        for cls_name, val in [("cat", 1.0), ("dog", 2.0)]:
            d = tmp_path / cls_name
            d.mkdir()
            for i in range(3):
                np.save(d / f"s{i}.npy", np.full((4, 4), val, np.float32))
        ds = v.DatasetFolder(str(tmp_path))
        assert len(ds) == 6 and ds.classes == ["cat", "dog"]
        sample, target = ds[0]
        assert target == 0 and sample[0, 0] == 1.0
        flat = v.ImageFolder(str(tmp_path))
        assert len(flat) == 6

    def test_jit_legacy_surface(self):
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn
        jit.set_verbosity(3)
        jit.set_code_level(5)
        calls = {"eager": 0}

        @jit.declarative
        def f(x):
            calls["eager"] += 1
            return x * 2

        t = paddle.to_tensor(np.ones(2, np.float32))
        f(t)
        jit.ProgramTranslator.get_instance().enable(False)
        try:
            f(t)  # runs the original callable eagerly
        finally:
            jit.ProgramTranslator.get_instance().enable(True)
        assert calls["eager"] >= 2  # traced once + eager once

        lin = nn.Linear(2, 2)
        out, traced = jit.TracedLayer.trace(lin, [t.reshape([1, 2])])
        out2 = traced(t.reshape([1, 2]))
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(out2._data), rtol=1e-6)

    def test_distributed_namespace_tail(self):
        import paddle_tpu.distributed as dist
        assert dist.InMemoryDataset is paddle.io.InMemoryDataset
        e = dist.ProbabilityEntry(0.5)
        assert "0.5" in e._to_attr()
        with pytest.raises(ValueError):
            dist.ProbabilityEntry(1.5)
        with pytest.raises(RuntimeError, match="BoxPS"):
            dist.BoxPSDataset()
        eps, rank = dist.cloud_utils.get_cluster_and_pod()
        assert isinstance(eps, list) and rank >= 0
        assert callable(dist.shard_tensor) and callable(dist.gloo_barrier)
