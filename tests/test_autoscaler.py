"""Elastic autoscaler (paddle_tpu/autoscaler.py, ISSUE 11): closed-loop
SLO-driven fleet scaling over the fake-clock simulation harness.

Every scenario runs a REAL ServingGateway + real SLOMonitor + real
ElasticAutoscaler against fake-timed SimEngines on one injected clock —
whole scale-up/scale-down trajectories are deterministic CPU tests: the
flash-crowd acceptance loop (SLO fires → spawn + warm + activate with
zero in-serve compiles → resolve → idle drains back to min), sustained-
idle scale-down, replica death mid-burst, diurnal load tracking, fleet
bounds, per-direction cooldowns, hysteresis no-flap at the idle
boundary, the expected-compiles grid registration on spawned replicas,
and the GET /autoscaler ops view.  Zero dropped requests is asserted
across every transition."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from paddle_tpu.autoscaler import DECISIONS, ElasticAutoscaler
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                   TrafficSim, diurnal, flash_crowd,
                                   sim_tokens, steady)
from paddle_tpu.telemetry_slo import Objective, SLOMonitor


def _slo(clock, tracer=None, ttft_target=2.0):
    return SLOMonitor([
        Objective.latency("ttft_p99", "ttft_s", ttft_target,
                          compliance=0.9, windows=(30.0, 10.0),
                          burn_threshold=1.0, for_s=2.0, clear_s=10.0),
        Objective.ratio("shed_rate", "shed", "submitted", 0.05,
                        windows=(30.0, 10.0), burn_threshold=1.0,
                        for_s=2.0, clear_s=10.0),
    ], clock=clock, resolution_s=1.0, tracer=tracer)


class _Fleet:
    """One wired-up closed loop: gateway + SLO + autoscaler + the list of
    every factory-spawned engine (for post-hoc compile accounting)."""

    def __init__(self, clock, *, replicas=1, with_slo=True,
                 stall_threshold_s=30.0, max_queue_depth=64,
                 warmup_unsupported=False, **asc_kw):
        self.clock = clock
        self.tracer = SimTracer(clock, capacity=16384)
        self.gw = ServingGateway(clock=clock, tracer=self.tracer,
                                 stall_threshold_s=stall_threshold_s,
                                 max_queue_depth=max_queue_depth)
        self.spawned = []

        def factory():
            eng = SimEngine(max_slots=4, tracer=SimTracer(clock),
                            warmup_unsupported=warmup_unsupported)
            self.spawned.append(eng)
            return eng

        self.factory = factory
        for i in range(replicas):
            eng = SimEngine(max_slots=4, tracer=SimTracer(clock))
            eng.warmup()
            self.gw.add_replica(eng, f"r{i}")
        self.slo = _slo(clock, tracer=self.tracer) if with_slo else None
        if self.slo is not None:
            self.gw.set_slo(self.slo)
        kw = dict(min_replicas=1, max_replicas=4,
                  scale_up_cooldown_s=5.0, scale_down_cooldown_s=15.0,
                  idle_utilization=0.2, idle_dwell_s=20.0,
                  tracer=self.tracer, clock=clock)
        kw.update(asc_kw)
        self.asc = ElasticAutoscaler(self.gw, factory, slo=self.slo,
                                     **kw)


class TestFlashCrowdAcceptance:
    def test_closed_loop_end_to_end(self):
        """The acceptance scenario: TTFT SLO fires → replica spawned +
        AOT-warmed + activated (ZERO in-serve compiles on every spawned
        replica) → alert resolves → sustained idle drains the fleet back
        to min size — zero dropped requests, fleet bounds respected on
        every timeline sample, and the full decision timeline visible
        via GET /autoscaler and tracer ``autoscale`` events."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1)
        sim = TrafficSim(fl.gw, clk,
                         flash_crowd(0.02, 8.0, 20.0, 30.0),
                         dt=0.25, seed=0, autoscaler=fl.asc)
        rep = sim.run(240.0)

        # --- zero drops across every transition, nothing unaccounted
        assert rep["dropped"] == []
        assert sum(rep["outcomes"].values()) == rep["offered"]
        finished = [h for h in sim.handles if h.status == "finished"]
        for h in finished:
            assert h.tokens == sim_tokens(h.prompt, h.max_new_tokens)

        # --- the loop actually closed: fired → scaled up → resolved
        actions = [d["action"] for d in rep["decisions"]]
        assert "scale_up" in actions and "activate" in actions
        assert "scale_down" in actions and "removed" in actions
        ups = [d for d in rep["decisions"] if d["action"] == "scale_up"]
        assert all(d["reason"].startswith("slo:") for d in ups)
        whats = [t["what"] for t in fl.slo.snapshot()["transitions"]
                 if t["objective"] == "ttft_p99"]
        assert "firing" in whats and "resolved" in whats
        assert whats.index("firing") < whats.index("resolved")

        # --- spawned replicas were warmed BEFORE activation: zero
        # in-serve compiles on every one of them
        assert len(fl.spawned) >= 1
        for eng in fl.spawned:
            assert eng.warmed
            assert eng.in_serve_compiles == 0, eng.metrics()

        # --- bounds respected at every sample; back to min at the end
        assert all(1 <= s["active"] + s["draining"] <= 4
                   for s in rep["timeline"])
        assert max(s["active"] for s in rep["timeline"]) >= 2
        assert rep["fleet"]["active"] == 1          # drained back to min
        assert rep["fleet"]["pending_spawns"] == 0

        # --- decision timeline rides the tracer...
        ev = fl.tracer.events("autoscale")
        assert [e["what"] for e in ev] == actions
        assert all("fleet_active" in e for e in ev)

        # --- ...and GET /autoscaler serves it live
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer()
        srv.attach(fl.asc, "asc")
        url = srv.start()
        try:
            snap = json.loads(urllib.request.urlopen(
                url + "/autoscaler", timeout=10).read())
            assert [d["action"] for d in snap["decisions"]] == actions
            assert snap["fleet"]["active"] == 1
            assert snap["policy"]["min_replicas"] == 1
            assert snap["policy"]["max_replicas"] == 4
            txt = urllib.request.urlopen(url + "/metrics",
                                         timeout=10).read().decode()
            assert "paddle_tpu_autoscaler_fleet_size 1" in txt
            assert "paddle_tpu_autoscaler_scale_ups" in txt
        finally:
            srv.stop()

    def test_fixed_fleet_same_load_is_worse(self):
        """The same offered load on a fixed single-replica fleet sheds
        and tails out — the A/B bench.py gpt_autoscale asserts; pinned
        here at test scale so the bench contract can't silently rot."""
        def run(autoscaled):
            clk = SimClock()
            fl = _Fleet(clk, replicas=1)
            sim = TrafficSim(fl.gw, clk, flash_crowd(0.5, 8.0, 10.0, 20.0),
                             dt=0.25, seed=1,
                             autoscaler=fl.asc if autoscaled else None)
            return sim.run(90.0)
        fixed, auto = run(False), run(True)
        assert fixed["offered"] == auto["offered"]
        assert fixed["shed_rate"] > auto["shed_rate"]
        assert auto["ttft_s"]["p99"] < fixed["ttft_s"]["p99"]
        assert fixed["dropped"] == auto["dropped"] == []


class TestScaleUpPolicy:
    def _firing_fleet(self):
        """A fleet whose TTFT objective is made to fire by direct sample
        injection — policy unit tests without a traffic sim."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1)
        for _ in range(50):
            fl.slo.observe("ttft_s", 10.0)      # way over the 2s target
        return clk, fl

    def test_one_spawn_per_decision_and_cooldown(self):
        clk, fl = self._firing_fleet()
        # dwell: pending → firing needs for_s=2 on the fake clock
        fl.asc.evaluate()
        clk.advance(3.0)
        made = fl.asc.evaluate()
        assert [d["action"] for d in made] == ["scale_up"]   # step limit
        made = fl.asc.evaluate()                 # same instant: cooldown
        assert [d["action"] for d in made] == ["activate"]
        clk.advance(2.0)                         # < 5s cooldown
        assert fl.asc.evaluate() == []
        clk.advance(4.0)                         # past cooldown
        made = fl.asc.evaluate()
        assert [d["action"] for d in made] == ["scale_up"]

    def test_max_bound_caps_fleet(self):
        clk, fl = self._firing_fleet()
        for _ in range(40):
            clk.advance(6.0)
            for _ in range(5):
                fl.slo.observe("ttft_s", 10.0)   # keep the alert burning
            fl.asc.evaluate()
        reps = fl.gw.replicas()
        assert sum(1 for r in reps if r.state == "active") == 4
        assert fl.asc.fleet_size() == 4
        ups = [d for d in fl.asc.decisions() if d["action"] == "scale_up"]
        assert len(ups) == 3                     # 1 seed + 3 spawned = max

    def test_spawn_failed_is_a_recorded_decision(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=1)

        def broken():
            raise RuntimeError("no capacity anywhere")
        asc = ElasticAutoscaler(fl.gw, broken, slo=fl.slo,
                                min_replicas=2, max_replicas=4,
                                clock=clk)
        made = asc.evaluate()                    # min-bound spawn attempt
        assert [d["action"] for d in made] == ["spawn_failed"]
        assert "no capacity" in made[0]["error"]
        assert asc.metrics()["spawn_failures"] == 1
        # the loop keeps running — further evaluates don't raise
        clk.advance(1.0)
        asc.evaluate()

    def test_spawn_failure_backoff_bounds_retries(self):
        """A persistently broken factory is retried once per
        scale_up_cooldown_s window, not once per evaluate() round — even
        on the otherwise cooldown-exempt min-bound path (the retry storm
        would otherwise flood the log and churn the decision history)."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1)

        def broken():
            raise RuntimeError("no capacity anywhere")
        asc = ElasticAutoscaler(fl.gw, broken, slo=fl.slo,
                                min_replicas=2, max_replicas=4,
                                scale_up_cooldown_s=30.0, clock=clk)
        assert [d["action"] for d in asc.evaluate()] == ["spawn_failed"]
        for _ in range(29):                      # inside the backoff
            clk.advance(1.0)
            assert asc.evaluate() == []
        clk.advance(2.0)                         # window elapsed → retry
        assert [d["action"] for d in asc.evaluate()] == ["spawn_failed"]
        assert asc.metrics()["spawn_failures"] == 2

    def test_factory_falls_back_to_gateway_registration(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=1)
        fl.gw.register_replica_factory(fl.factory)
        asc = ElasticAutoscaler(fl.gw, None, min_replicas=2,
                                max_replicas=4, clock=clk)
        made = asc.evaluate()
        assert [d["action"] for d in made] == ["scale_up"]
        assert made[0]["reason"] == "min_bound"
        with pytest.raises(TypeError):
            fl.gw.register_replica_factory("not callable")

    def test_warm_async_future_defers_activation(self):
        clk = SimClock()

        class SlowWarmFuture:
            def __init__(self):
                self.ready_at = clk() + 10.0

            def done(self):
                return clk() >= self.ready_at

            def result(self):
                return {"programs": 3, "wall_s": 10.0}

        class SlowWarmEngine(SimEngine):
            def warmup(self, cache_dir=None, max_workers=1, block=True):
                if block:
                    return super().warmup(cache_dir=cache_dir)
                super().warmup(cache_dir=cache_dir)
                return SlowWarmFuture()

        gw = ServingGateway(clock=clk, tracer=SimTracer(clk))
        seed = SimEngine(max_slots=4)
        seed.warmup()
        gw.add_replica(seed, "r0")
        asc = ElasticAutoscaler(gw, lambda: SlowWarmEngine(max_slots=4),
                                min_replicas=2, max_replicas=4,
                                warm_async=True, clock=clk)
        made = asc.evaluate()
        assert [d["action"] for d in made] == ["scale_up"]
        assert made[0]["pending"] is True
        assert asc.metrics()["pending_spawns"] == 1
        clk.advance(5.0)
        assert asc.evaluate() == []              # future not done yet
        assert len(gw.replicas()) == 1
        clk.advance(6.0)
        made = asc.evaluate()
        assert [d["action"] for d in made] == ["activate"]
        assert made[0]["spawn_wait_s"] == pytest.approx(11.0)
        assert len(gw.replicas()) == 2


class TestScaleDownPolicy:
    def _idle_fleet(self, replicas=3, **asc_kw):
        clk = SimClock()
        fl = _Fleet(clk, replicas=replicas, with_slo=False, **asc_kw)
        return clk, fl

    def test_sustained_idle_drains_to_min_never_below(self):
        clk, fl = self._idle_fleet(3)
        for _ in range(400):
            clk.advance(1.0)
            fl.gw.step()
            fl.asc.evaluate()
        downs = [d for d in fl.asc.decisions()
                 if d["action"] == "scale_down"]
        assert len(downs) == 2                   # 3 → 1, never below min
        reps = fl.gw.replicas()
        assert len(reps) == 1                    # stopped shells removed
        assert reps[0].state == "active"
        # spacing respects dwell + down-cooldown on the fake clock
        assert downs[1]["ts"] - downs[0]["ts"] >= 20.0

    def test_scale_down_picks_least_loaded_and_finishes_inflight(self):
        clk, fl = self._idle_fleet(3, idle_dwell_s=5.0,
                                   scale_down_cooldown_s=5.0)
        # one long request occupies r0: occupancy 1/12 < 0.2 is still
        # idle, but the victim must be an EMPTY replica, and the
        # in-flight request must finish untouched
        h = fl.gw.submit([1, 2, 3], 40)
        fl.gw.step()
        busy = h.replica
        for _ in range(70):
            clk.advance(1.0)
            fl.gw.step()
            fl.asc.evaluate()
        downs = [d for d in fl.asc.decisions()
                 if d["action"] == "scale_down"]
        assert downs and downs[0]["replica"] != busy
        assert h.status == "finished"
        assert h.tokens == sim_tokens([1, 2, 3], 40)

    def test_recent_scale_up_blocks_scale_down(self):
        """The never-tear-down-what-you-just-added rule: a fresh spawn
        re-arms the scale-down cooldown even under instant idle."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1, idle_dwell_s=2.0,
                    scale_down_cooldown_s=30.0, min_replicas=1)
        # force a min-bound spawn by starting a second autoscaler with
        # min_replicas=2, then reuse its clock state: simpler — drive a
        # spawn through firing SLO
        for _ in range(50):
            fl.slo.observe("ttft_s", 10.0)
        fl.asc.evaluate()
        clk.advance(3.0)
        fl.asc.evaluate()                        # scale_up at t=3
        fl.asc.evaluate()                        # activate
        # let the alert clear: samples age out of the 30s windows
        clk.advance(25.0)                        # t=28: up was at t=3
        for _ in range(10):
            clk.advance(1.0)
            fl.asc.evaluate()
        # t=38: idle dwell long satisfied, but 38 - 3 = 35 >= 30 only
        # now; before t=33 no scale_down may have happened
        downs = [d for d in fl.asc.decisions()
                 if d["action"] == "scale_down"]
        assert all(d["ts"] - 3.0 >= 30.0 for d in downs)

    def test_hysteresis_band_no_flapping(self):
        """Occupancy hovering at the idle threshold cannot flap: inside
        the band [thresh, thresh*resume) a running dwell keeps running
        but a new one never starts; only a clear bounce above the band
        resets — mirroring the SLO engine's resolve hysteresis."""
        clk, fl = self._idle_fleet(3, idle_dwell_s=10.0,
                                   scale_down_cooldown_s=5.0,
                                   idle_utilization=0.2,
                                   idle_resume_ratio=1.5)
        asc = fl.asc
        occ = {"v": 0.2}
        real_util = asc.utilization

        def fake_util():
            out = real_util()
            out["occupancy"] = occ["v"]
            return out
        asc.utilization = fake_util
        # AT the threshold: never starts a dwell, never decides
        for _ in range(30):
            clk.advance(1.0)
            assert asc.evaluate() == []
        assert asc._idle_since is None
        # below: dwell starts
        occ["v"] = 0.19
        asc.evaluate()
        started = asc._idle_since
        assert started is not None
        # bounce INTO the band (0.2 <= occ < 0.3): dwell keeps running
        occ["v"] = 0.29
        clk.advance(1.0)
        asc.evaluate()
        assert asc._idle_since == started        # not reset — no flap
        # clear bounce ABOVE the band: dwell resets
        occ["v"] = 0.31
        clk.advance(1.0)
        asc.evaluate()
        assert asc._idle_since is None
        # sustained below → exactly one decision after the dwell
        occ["v"] = 0.1
        made = []
        for _ in range(12):
            clk.advance(1.0)
            made.extend(asc.evaluate())
        acts = [d["action"] for d in made]
        assert acts.count("scale_down") == 1     # one decision, no flap
        assert set(acts) <= {"scale_down", "removed"}


class TestReplicaDeath:
    def test_death_mid_burst_is_replaced_and_recovers(self):
        """Replica death during a flash crowd: the gateway quarantines
        the stalled replica on the fake clock, its in-flight work
        replays elsewhere, and the autoscaler back-fills the lost
        capacity — zero drops, oracle streams, bounds held."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=2, stall_threshold_s=3.0,
                    min_replicas=2, max_replicas=4)
        sim = TrafficSim(fl.gw, clk, flash_crowd(0.05, 6.0, 10.0, 20.0),
                         dt=0.25, seed=4, autoscaler=fl.asc)
        sim.at(15.0, fl.gw.replica("r0").engine.kill, "kill r0")
        rep = sim.run(120.0)
        assert rep["injections_fired"] == ["kill r0"]
        # the quarantined shell was reaped: drained (no in-flight — the
        # quarantine already rerouted it) and removed, so a long-lived
        # elastic fleet doesn't grow one dead entry per death
        assert "r0" not in [r.name for r in fl.gw.replicas()]
        acts = [d["action"] for d in fl.asc.decisions()]
        assert "reap" in acts and "removed" in acts
        assert rep["dropped"] == []
        assert rep["outcomes"].get("finished", 0) > 0
        for h in sim.handles:
            if h.status == "finished":
                assert h.tokens == sim_tokens(h.prompt, h.max_new_tokens)
        # lost capacity was back-filled: active never ends below min
        assert rep["fleet"]["active"] >= 2
        assert all(s["active"] + s["draining"] <= 4
                   for s in rep["timeline"])

    def test_min_bound_replacement_ignores_cooldown(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=2, min_replicas=2, max_replicas=4,
                    scale_up_cooldown_s=1000.0, with_slo=False)
        fl.gw.submit([1], 4)                     # work → stall detectable
        fl.gw.step()
        assert fl.asc.evaluate() == []           # healthy: nothing to do
        fl.gw.quarantine("r0")
        made = fl.asc.evaluate()
        # one round: the benched shell is reaped (drain → remove) AND the
        # min-bound back-fill spawns, cooldown notwithstanding
        assert [d["action"] for d in made] == ["reap", "removed",
                                               "scale_up"]
        assert made[0]["replica"] == "r0"
        assert made[-1]["reason"] == "min_bound"
        assert "r0" not in [r.name for r in fl.gw.replicas()]

    def test_reap_disabled_keeps_shell_for_reinstate(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=2, min_replicas=1, max_replicas=4,
                    with_slo=False, reap_quarantined=False)
        fl.gw.quarantine("r0")
        assert fl.asc.evaluate() == []
        assert fl.gw.replica("r0").state == "quarantined"
        fl.gw.reinstate("r0")                    # operator path preserved
        assert fl.gw.replica("r0").state == "active"


class TestDiurnal:
    def test_fleet_tracks_the_sinusoid(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=1, idle_dwell_s=15.0,
                    scale_down_cooldown_s=10.0, scale_up_cooldown_s=4.0)
        sim = TrafficSim(fl.gw, clk, diurnal(0.05, 10.0, 120.0),
                         dt=0.25, seed=6, autoscaler=fl.asc,
                         sample_every_s=2.0)
        rep = sim.run(300.0)                     # 2.5 periods
        assert rep["dropped"] == []
        peak = max(s["active"] for s in rep["timeline"])
        assert peak >= 2                         # grew into the peak
        assert all(1 <= s["active"] + s["draining"] <= 4
                   for s in rep["timeline"])
        # shrank again after a peak (the trough between diurnal peaks is
        # short relative to resolve + dwell + cooldown, so full return
        # to min is the flash-crowd test's job — here the fleet must
        # demonstrably track DOWN as well as up)
        t_peak = next(s["t"] for s in rep["timeline"]
                      if s["active"] == peak)
        assert any(s["active"] < peak for s in rep["timeline"]
                   if s["t"] > t_peak)
        assert any(d["action"] == "scale_down"
                   for d in rep["decisions"])


class TestExpectedCompileWindow:
    def test_unwarmable_replica_grid_registered(self):
        """A spawned replica whose engine cannot warm (TP/mesh shape)
        still activates, and its warmup grid is registered on its tracer
        via a held-open expected_compiles window: first-dispatch misses
        are tagged expected and never arm the recompile-storm warning."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1, warmup_unsupported=True,
                    min_replicas=2, with_slo=False)
        fl.asc.evaluate()                        # min-bound spawn
        made = fl.asc.evaluate()
        assert [d["action"] for d in made] == ["activate"]
        assert made[0]["warmed"] is False
        eng = fl.spawned[0]
        eng.tracer.recompile_warn_threshold = 1  # hair trigger
        # serve through the new replica: route there by loading r0
        for _ in range(6):
            fl.gw.submit([1, 2], 3)
        for _ in range(20):
            clk.advance(0.25)
            fl.gw.step()
        misses = [e for e in eng.tracer.events("compile")
                  if not e["hit"]]
        assert misses, "the unwarmed replica must have compiled"
        assert all(e["expected"] for e in misses)
        assert not eng.tracer._warned_storm
        assert eng.in_serve_compiles > 0         # honest engine-side count

    def test_window_closes_on_drain_and_close(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=1, warmup_unsupported=True,
                    min_replicas=2, with_slo=False,
                    idle_dwell_s=5.0, scale_down_cooldown_s=5.0)
        fl.asc.evaluate()
        fl.asc.evaluate()                        # activate
        eng = fl.spawned[0]
        assert eng.tracer._warmup_depth == 1     # window held open
        fl.asc.min_replicas = 1                  # now it may drain
        for _ in range(30):
            clk.advance(1.0)
            fl.gw.step()
            fl.asc.evaluate()
        # one of the two replicas was drained; if it was the spawned one
        # its window is closed — force the other case through close()
        fl.asc.close()
        assert eng.tracer._warmup_depth == 0
        # close() is idempotent and detaches evaluate()
        fl.asc.close()
        assert fl.asc.evaluate() == []


class TestObservability:
    def test_snapshot_prometheus_and_ops_404(self):
        clk = SimClock()
        fl = _Fleet(clk, replicas=2, with_slo=True)
        snap = fl.asc.autoscaler_snapshot()
        assert snap["policy"]["min_replicas"] == 1
        assert snap["fleet"]["active"] == 2
        assert snap["signals"]["firing"] == []
        assert snap["signals"]["utilization"]["total_slots"] == 8
        assert snap["last_decision"] == "none"
        prom = fl.asc.prometheus_text()
        assert "paddle_tpu_autoscaler_fleet_size 2" in prom
        assert "paddle_tpu_autoscaler_pending_spawns 0" in prom
        assert "paddle_tpu_autoscaler_last_decision 0" in prom
        assert DECISIONS[0] == "none"
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer()
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/autoscaler", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_scrape_races_decision_churn_guard_clean(self, lock_sanitizer):
        """Regression for the unlocked ``_pending``/``_decisions``/
        ``_firing`` reads: ``decisions()`` used to iterate the deque bare
        while ``_record`` appended from the evaluate path (a RuntimeError
        on a real ops thread), and ``metrics()`` read ``len(_firing)``
        outside the lock the class itself documents.  The sanitizer
        harvests the ``# guarded-by:`` declarations straight from the
        source, so EVERY access — scrape thread or evaluate path — must
        now hold the declared lock or this test fails at teardown."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1, scale_up_cooldown_s=0.0)
        asc = fl.asc
        wired = lock_sanitizer.instrument_guards(asc)
        assert ("_pending", "_state_lock") in wired
        assert ("_decisions", "_state_lock") in wired
        assert lock_sanitizer.guard(asc, "_firing", "_firing_lock")
        errors, stop = [], threading.Event()

        def scrape():
            try:
                while not stop.is_set():
                    asc.decisions()
                    asc.metrics()
                    asc.autoscaler_snapshot()
                    asc.prometheus_text()
                    asc.firing()
                    asc.fleet_size()
            except Exception as e:  # noqa: BLE001 — repro harness
                errors.append(e)

        threads = [threading.Thread(target=scrape, name=f"scrape{i}")
                   for i in range(3)]
        for t in threads:
            t.start()
        try:
            sim = TrafficSim(fl.gw, clk, flash_crowd(2.0, 30.0, 2.0, 10.0),
                             autoscaler=asc)
            sim.run(30.0)                 # spawn + activate churn
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors
        assert asc.decisions()            # churn actually happened

    def test_slo_subscription_seeds_from_firing_state(self):
        """An autoscaler attached mid-incident sees the already-firing
        alert (alert_states seeding) and unsubscribes on close()."""
        clk = SimClock()
        slo = _slo(clk)
        for _ in range(50):
            slo.observe("ttft_s", 10.0)
        slo.evaluate()
        clk.advance(3.0)
        slo.evaluate()
        assert slo.alert_states()["ttft_p99"] == "firing"
        gw = ServingGateway(clock=clk)
        eng = SimEngine()
        eng.warmup()
        gw.add_replica(eng, "r0")
        asc = ElasticAutoscaler(gw, lambda: SimEngine(), slo=slo,
                                clock=clk)
        assert asc.firing() == ["ttft_p99"]
        asc.close()
        assert slo.unsubscribe(asc._on_slo_transition) is False

    def test_watched_objectives_filter(self):
        clk = SimClock()
        slo = _slo(clk)
        gw = ServingGateway(clock=clk)
        eng = SimEngine()
        eng.warmup()
        gw.add_replica(eng, "r0")
        asc = ElasticAutoscaler(gw, lambda: SimEngine(), slo=slo,
                                objectives=("shed_rate",), clock=clk)
        for _ in range(50):
            slo.observe("ttft_s", 10.0)          # fires ttft_p99 only
        asc.evaluate()
        clk.advance(3.0)
        made = asc.evaluate()
        assert asc.firing() == []                # unwatched: no signal
        assert all(d["action"] != "scale_up" for d in made)


class TestGatewayPrimitives:
    def test_remove_replica_contract(self):
        clk = SimClock()
        gw = ServingGateway(clock=clk)
        eng = SimEngine()
        eng.warmup()
        gw.add_replica(eng, "a")
        with pytest.raises(ValueError):
            gw.remove_replica("a")               # active: refuse
        gw.drain("a")
        assert gw.is_drained("a")
        gw.remove_replica("a")
        with pytest.raises(KeyError):
            gw.replica("a")
        assert gw.metrics()["replicas_removed"] == 1
        # the name is reusable after removal
        eng2 = SimEngine()
        eng2.warmup()
        gw.add_replica(eng2, "a")
        assert gw.replica("a").state == "active"

    def test_firing_set_safe_under_cross_thread_transition_churn(self):
        """SLO transitions arrive on whatever thread drives
        slo.evaluate() — ops-server HTTP scrape threads included — so
        the subscriber callback must never tear the control loop's
        firing() read (an unlocked set raises 'Set changed size during
        iteration' out of evaluate() and kills the serving loop)."""
        clk = SimClock()
        fl = _Fleet(clk, replicas=1)
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                fl.asc._on_slo_transition(
                    {"objective": f"o{i % 50}",
                     "what": "firing" if i % 2 == 0 else "resolved"})
                i += 1

        t = threading.Thread(target=churn)
        t.start()
        try:
            for _ in range(2000):
                fl.asc.firing()              # must never raise
        finally:
            stop.set()
            t.join()

    def test_slo_subscribe_hook_contract(self):
        """SLOMonitor.subscribe delivers every transition; a raising
        subscriber is isolated; unsubscribe stops delivery."""
        clk = SimClock()
        slo = _slo(clk)
        seen = []

        def boom(ev):
            raise RuntimeError("subscriber bug")
        slo.subscribe(boom)
        slo.subscribe(seen.append)
        with pytest.raises(TypeError):
            slo.subscribe("nope")
        for _ in range(50):
            slo.observe("ttft_s", 10.0)
        slo.evaluate()                           # pending (boom isolated)
        clk.advance(3.0)
        slo.evaluate()                           # firing
        whats = [e["what"] for e in seen]
        assert whats == ["pending", "firing"]
        assert all(e["objective"] == "ttft_p99" for e in seen)
        assert slo.unsubscribe(seen.append) is True
        assert slo.unsubscribe(seen.append) is False
