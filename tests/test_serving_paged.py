"""Paged (block-table) KV cache engine (paddle_tpu/serving_paged.py):
parity with the contiguous engine's oracle contract (every request's tokens
equal solo model.generate), plus the allocator properties the paging exists
for — lazy growth, immediate release, deferred admission, preemption under
a dry pool, bounded compiled-program count, and HBM accounting.

No reference counterpart (the reference serves static batches only); the
oracle is the framework's own single-request generation path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine)


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo_greedy(model, params, prompt, n, **kw):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         greedy=True, **kw)
    return [int(t) for t in np.asarray(out)[0]]


PROMPTS = [[5, 17, 3], [40, 2], [9, 9, 9, 9, 9, 1], [61], [8, 30, 12, 4],
           [77, 13, 2, 5, 6, 7, 8]]


class TestPagedParity:
    @pytest.mark.parametrize("k", [1, 4])
    def test_interleaved_matches_solo_generate(self, model_and_params, k):
        """The contiguous engine's core schedule (six ragged requests
        through 3 slots with retirement/re-admission) on the paged cache:
        token-for-token solo parity for per-token and chunked sync."""
        model, params = model_and_params
        budgets = [10, 4, 7, 12, 3, 8]
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=3, max_len=32, block_size=4,
            prompt_buckets=[8, 16], ticks_per_sync=k)
        rids = [eng.add_request(p, n) for p, n in zip(PROMPTS, budgets)]
        got = eng.run_to_completion(max_ticks=200)
        assert sorted(got) == sorted(rids)
        for rid, p, n in zip(rids, PROMPTS, budgets):
            assert got[rid] == _solo_greedy(model, params, p, n), \
                f"request {rid} diverged (k={k})"
        assert eng.blocks_in_use == 0          # everything released

    def test_chunked_prefill_with_penalty(self, model_and_params):
        """Chunked admission + repetition penalty on the paged cache —
        the trash-block parking must keep filling prompts intact while
        another slot decodes (the contiguous engine's corruption scenario
        re-run against block tables)."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=64, block_size=4,
            prompt_buckets=[4, 16], ticks_per_sync=1, prefill_chunk=4,
            repetition_penalty=5.0)
        finished = {}
        r0 = eng.add_request(PROMPTS[1], 30)
        r1 = eng.add_request([61], 2)
        while r1 not in finished:
            eng.step()
            finished.update(eng.pop_finished())
        r2 = eng.add_request(list(range(20, 31)), 20)   # chunked, reused slot
        for _ in range(300):
            eng.step()
            finished.update(eng.pop_finished())
            if not eng.pending():
                break
        for rid, p, n in [(r0, PROMPTS[1], 30), (r1, [61], 2),
                          (r2, list(range(20, 31)), 20)]:
            assert finished[rid] == _solo_greedy(
                model, params, p, n, repetition_penalty=5.0), \
                f"request {rid} diverged"

    def test_int8_kv_paged(self):
        """int8 cache pairs (value plane + scale plane) ride the same
        gather/scatter: engine output equals solo generate on the SAME
        int8-cached model."""
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype="int8")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=8,
            prompt_buckets=[8], ticks_per_sync=2)
        budgets = [9, 5, 7]
        rids = [eng.add_request(p, n)
                for p, n in zip(PROMPTS[:3], budgets)]
        got = eng.run_to_completion(max_ticks=200)
        for rid, p, n in zip(rids, PROMPTS[:3], budgets):
            assert got[rid] == _solo_greedy(model, params, p, n), \
                f"int8 request {rid} diverged"


class TestPagedAllocator:
    def test_lazy_allocation_scales_with_emitted_tokens(self,
                                                        model_and_params):
        """Admission takes ceil(P/bs) blocks regardless of
        max_new_tokens — the contiguous engine would reserve max_len.
        Growth happens per decode sync; retirement releases everything."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=64, block_size=4,
            prompt_buckets=[8])
        eng.add_request(PROMPTS[0], 40)        # huge budget, short prompt
        eng.step()                             # admit + first decode sync
        # bucket 8 -> 2 blocks + the first decode sync's growth (1 block)
        assert eng.blocks_in_use == 3, eng.blocks_in_use
        eng.run_to_completion(max_ticks=100)
        assert eng.blocks_in_use == 0
        # high water = blocks for 8 + 40 positions, nowhere near max_len
        assert eng.blocks_high_water == -(-(8 + 40) // 4)

    def test_small_pool_preempts_and_stays_exact(self, model_and_params):
        """Two long requests cannot both fit the pool: the younger is
        preempted (blocks freed, rerun from scratch), outputs stay
        greedy-exact, and the pool high-water respects the cap."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            num_blocks=8, prompt_buckets=[8])
        r0 = eng.add_request(PROMPTS[0], 20)   # needs ceil(28/4) = 7 blocks
        r1 = eng.add_request(PROMPTS[1], 20)
        got = eng.run_to_completion(max_ticks=300)
        assert eng.preemptions >= 1
        assert eng.blocks_high_water <= 8
        assert got[r0] == _solo_greedy(model, params, PROMPTS[0], 20)
        assert got[r1] == _solo_greedy(model, params, PROMPTS[1], 20)

    def test_admission_defers_until_blocks_free(self, model_and_params):
        """A dry pool defers admission (FIFO kept) instead of failing;
        no preemption is needed when the waiting request was never
        admitted."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            num_blocks=3, prompt_buckets=[8])
        r0 = eng.add_request(PROMPTS[0], 4)    # needs ceil(11/4) = 3 blocks
        r1 = eng.add_request(PROMPTS[1], 4)
        eng.step()
        assert not eng._active[1]              # r1 deferred: pool can't fit
        got = eng.run_to_completion(max_ticks=200)
        assert eng.preemptions == 0
        assert got[r0] == _solo_greedy(model, params, PROMPTS[0], 4)
        assert got[r1] == _solo_greedy(model, params, PROMPTS[1], 4)

    def test_bucketed_view_protects_deep_filler_cache(self,
                                                      model_and_params):
        """Length-bucketed decode gathers only C table columns covering
        the deepest ACTIVE clock.  A chunk-filling slot's blocks can
        extend beyond C while a shallow request decodes next door; the
        filler's parked-clock scatter-back must hit trash, not the
        clamped last view column (which aliases a REAL prompt block).
        Checked against model.prefill's reference cache, block by
        block."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=64, block_size=4,
            prompt_buckets=[4, 16], ticks_per_sync=2, prefill_chunk=4)
        # admitted TOGETHER: r0 starts at t=4, so during the filler's
        # second segment the view bucket is still C=2 (covers t+k=8)
        # while the filler's table col 1 is already a REAL block — the
        # clamped parked-clock scatter-back aliases it unless gated
        r0 = eng.add_request([40, 2], 20)      # bucket 4: shallow decoder
        long_prompt = list(range(3, 19))       # bucket 16, pad 0: 4 blocks
        eng.add_request(long_prompt, 6)
        eng.step()
        assert eng._active[0] and eng._filling
        slot = next(iter(eng._filling))
        while slot in eng._filling:
            eng.step()
        ref = model.prefill(params, jnp.asarray([long_prompt], jnp.int32),
                            16)[1][0]
        ref = np.asarray(ref[:, 0, :16])       # (L, 16, nh, hd)
        tab = eng._table[slot, :4]
        got = np.asarray(eng.caches[0][:, tab])            # (L, 4, bs, ...)
        got = got.reshape(ref.shape)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5,
                                   err_msg="stale scatter-back corrupted "
                                           "the filler's prompt blocks")
        got_all = eng.run_to_completion(max_ticks=200)
        assert len(got_all) == 2

    def test_wedged_fillers_preempt_and_recover(self, model_and_params):
        """Review repro: two chunked fillers jointly exhaust the pool with
        NO active decoder — nothing will ever free blocks, so the stalled
        fillers would spin forever.  The engine must evict the younger
        filler (rerun later) and finish both, oracle-exact."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            num_blocks=5, prompt_buckets=[16], prefill_chunk=4)
        p0, p1 = list(range(3, 15)), list(range(50, 62))
        r0 = eng.add_request(p0, 3)            # each needs 4 blocks to fill;
        r1 = eng.add_request(p1, 3)            # pool of 5 wedges them both
        got = eng.run_to_completion(max_ticks=300)
        assert eng.preemptions >= 1
        assert got[r0] == _solo_greedy(model, params, p0, 3)
        assert got[r1] == _solo_greedy(model, params, p1, 3)

    def test_module_imports_directly(self):
        """The defining module must be importable on its own (review
        found the bottom-of-serving re-export made
        `import paddle_tpu.serving_paged` raise a circular ImportError)."""
        import subprocess
        import sys
        r = subprocess.run(
            [sys.executable, "-c",
             "import paddle_tpu.serving_paged as sp; "
             "assert sp.PagedContinuousBatchingEngine"],
            capture_output=True, text=True,
            env={"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu",
                 "PATH": "/usr/bin:/bin"})
        assert r.returncode == 0, r.stderr
        import paddle_tpu.serving_paged as sp
        assert sp.PagedContinuousBatchingEngine is \
            PagedContinuousBatchingEngine

    def test_oversized_request_rejected(self, model_and_params):
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            num_blocks=4, prompt_buckets=[8])
        with pytest.raises(ValueError, match="blocks"):
            eng.add_request(PROMPTS[0], 20)    # 7 blocks > pool of 4

    def test_block_size_validation(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="divide"):
            PagedContinuousBatchingEngine(model, params, max_slots=2,
                                          max_len=30, block_size=4)
        with pytest.raises(ValueError, match="bucket"):
            PagedContinuousBatchingEngine(model, params, max_slots=2,
                                          max_len=32, block_size=4,
                                          prompt_buckets=[6])

    def test_pool_capacity_below_contiguous(self, model_and_params):
        """The memory-accounting claim: a paged pool sized for the real
        workload allocates strictly less persistent cache HBM than the
        contiguous engine's max_slots x max_len reservation."""
        model, params = model_and_params

        def cache_bytes(caches):
            return sum(x.nbytes for x in jax.tree.leaves(caches))

        contiguous = ContinuousBatchingEngine(model, params, max_slots=4,
                                              max_len=64, prompt_buckets=[8])
        paged = PagedContinuousBatchingEngine(
            model, params, max_slots=4, max_len=64, block_size=8,
            num_blocks=12, prompt_buckets=[8])   # 96 positions vs 256
        ratio = cache_bytes(paged.caches) / cache_bytes(contiguous.caches)
        # 12+1 blocks of 8 positions = 104 vs 256 contiguous positions
        assert ratio == pytest.approx(104 / 256)
        # and it still serves a 4-slot workload of short requests
        rids = [paged.add_request(p, 4) for p in PROMPTS[:4]]
        got = paged.run_to_completion(max_ticks=200)
        for rid, p in zip(rids, PROMPTS[:4]):
            assert got[rid] == _solo_greedy(model, params, p, 4)

    def test_compiled_program_count_is_bounded(self, model_and_params):
        """Block tables are traced operands: allocation patterns,
        preemptions, and fresh engine instances never add programs — one
        decode program per length bucket + one prefill program per
        prompt bucket."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)

        def make(nb):
            return PagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                num_blocks=nb, prompt_buckets=[8, 16])

        eng = make(16)
        rids = [eng.add_request(p, n)
                for p, n in zip(PROMPTS[:4], [6, 3, 5, 4])]
        eng.run_to_completion(max_ticks=200)
        n_progs = len(model._serving_programs)
        # same shapes again, tighter pool (data, not shape): no new programs
        eng2 = make(16)
        eng2.add_request(PROMPTS[4], 5)
        eng2.add_request(PROMPTS[5], 8)
        eng2.run_to_completion(max_ticks=200)
        assert len(model._serving_programs) == n_progs
        assert rids is not None


class TestPagedFuzz:
    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_scenarios_match_solo(self, seed):
        """Randomized composition stress for the allocator: random
        prompts/budgets/admission times under randomly drawn engine
        configs INCLUDING tight pools (deferral + preemption), block
        sizes, chunked prefill, penalty, eos, and int8 — every request's
        tokens must equal solo generate() with the same knobs.  CANCELS
        are interleaved at random points (ISSUE 9): a cancelled request's
        delivered stream must be a prefix of its solo oracle and end with
        the terminal ``(None, True)`` signal, and at quiescence the
        allocator must balance exactly — ``blocks_allocated ==
        blocks_released`` with zero blocks in use (cancel leaks nothing,
        whatever lifecycle stage it hit).  TIER demotion/promotion rides
        along (ISSUE 14): most draws attach a TieredKVStore and
        interleave random ``flush_prefix()`` calls — pages bounce
        HBM -> DRAM -> HBM mid-traffic, and the same oracle/stream/
        balance contracts must hold through every restore."""
        import paddle_tpu as _paddle
        from paddle_tpu.kv_store import TieredKVStore
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        rng = np.random.RandomState(seed)
        kv = "int8" if rng.rand() < 0.5 else None
        _paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype=kv)
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}

        ticks = int(rng.choice([1, 2, 4]))
        chunk = int(rng.choice([0, 4, 8]))
        penalty = float(rng.choice([1.0, 4.0]))
        eos = int(rng.randint(0, 97)) if rng.rand() < 0.5 else None
        bs = int(rng.choice([2, 4, 8]))
        tiered = bool(rng.rand() < 0.7)
        store = TieredKVStore() if tiered else None
        # worst single request: bucket 16 + chunk-rounded budget of 11
        worst = -(-(16 + -(-(11 - 1) // ticks) * ticks) // bs)
        nb = int(rng.randint(worst, worst * 3))
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=int(rng.randint(1, 4)), max_len=48,
            block_size=bs, num_blocks=nb, prompt_buckets=[8, 16],
            ticks_per_sync=ticks, prefill_chunk=chunk or None,
            repetition_penalty=penalty, eos_token_id=eos,
            enable_prefix_cache=tiered, kv_store=store)

        streams = {}
        closed = set()

        def on_tok(rid, t, d):
            if t is None and not d:
                streams.get(rid, []).clear()    # preemption replay: reset
            elif t is not None:
                streams.setdefault(rid, []).append(int(t))
            if d:
                closed.add(rid)

        reqs = []
        for _ in range(int(rng.randint(4, 9))):
            p = [int(t) for t in rng.randint(1, 97, rng.randint(1, 15))]
            n = int(rng.randint(1, 12))
            reqs.append((eng.add_request(p, n, on_token=on_tok), p, n))
            for _ in range(int(rng.randint(0, 3))):
                eng.step()
        to_cancel = [rid for rid, _, _ in reqs if rng.rand() < 0.35]
        cancelled = set()
        steps = 0
        while eng.pending():
            eng.step()
            if to_cancel and rng.rand() < 0.5:
                rid = to_cancel.pop()
                if eng.cancel(rid):          # False: already finished
                    cancelled.add(rid)
            if store is not None and rng.rand() < 0.15:
                # mid-traffic demotion: unpinned cached pages leave HBM
                # for the DRAM tier; later admissions restore them
                eng.flush_prefix()
            steps += 1
            assert steps < 800, "not done after 800 ticks"
        got = eng.pop_finished()

        for rid, p, n in reqs:
            want = _solo_greedy(model, params, p, n,
                                repetition_penalty=penalty)
            if eos is not None and eos in want:
                want = want[:want.index(eos) + 1]
            ctx = (f"seed={seed} ticks={ticks} chunk={chunk} bs={bs} "
                   f"nb={nb} penalty={penalty} eos={eos} kv={kv} "
                   f"tiered={tiered} preempt={eng.preemptions} "
                   f"cancelled={cancelled}")
            if rid in cancelled:
                assert rid not in got, ctx
                assert rid in closed, ctx     # terminal (None, True) seen
                delivered = streams.get(rid, [])
                assert delivered == want[:len(delivered)], (ctx, delivered)
            else:
                assert got[rid] == want, ctx
                assert streams[rid] == want, ctx
        if store is not None:
            # cached pages linger in HBM by design; a final demotion
            # sweep must leave the pool completely empty
            eng.flush_prefix()
        assert eng.blocks_in_use == 0
        assert int(eng._stats.value("blocks_allocated")) == \
            int(eng._stats.value("blocks_released")), \
            "cancel leaked (or double-freed) pool blocks"


class TestCancel:
    """Engine.cancel(rid) — ISSUE 9: exact resource release at every
    lifecycle stage, the terminal ``(None, True)`` stream signal, and a
    slot that is immediately reusable."""

    def test_cancel_all_stages_releases_blocks(self, model_and_params):
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            prompt_buckets=[8])
        sig = []
        r0 = eng.add_request([5, 17, 3], 20,
                             on_token=lambda r, t, d: sig.append((r, t, d)))
        r1 = eng.add_request([40, 2], 6)
        r2 = eng.add_request([61], 4)              # queued: 2 slots only
        for _ in range(3):
            eng.step()
        assert eng.cancel(r0)                      # active mid-decode
        assert sig[-1] == (r0, None, True)         # clean end-of-stream
        assert eng.cancel(r2)                      # still queued
        assert not eng.cancel(999)                 # unknown rid
        got = eng.run_to_completion(max_ticks=100)
        assert sorted(got) == [r1]                 # cancelled never appear
        assert not eng.cancel(r1)                  # already finished
        m = eng.metrics()
        assert m["requests_cancelled"] == 2
        assert eng.blocks_in_use == 0
        assert m["blocks_allocated"] == m["blocks_released"]
        # the freed slots admit fresh requests with oracle-exact output
        r3 = eng.add_request([8, 30, 12, 4], 5)
        got = eng.run_to_completion(max_ticks=100)
        assert got[r3] == _solo_greedy(model, params, [8, 30, 12, 4], 5)

    def test_cancel_mid_chunked_prefill(self, model_and_params):
        """A filling slot (chunked admission in progress) cancels clean:
        its partially-grown table releases every block."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=1, max_len=48, block_size=4,
            prompt_buckets=[4, 16], prefill_chunk=4)
        rid = eng.add_request(list(range(20, 33)), 4)   # 16-bucket, 4 segs
        eng.step()                                 # one segment in
        assert eng._filling, "expected a mid-prefill filling slot"
        assert eng.cancel(rid)
        assert not eng._filling and not eng.pending()
        assert eng.blocks_in_use == 0
        m = eng.metrics()
        assert m["blocks_allocated"] == m["blocks_released"]

    def test_cancel_releases_prefix_pins(self, model_and_params):
        """Cancel under prefix caching: pinned chain blocks drop their
        refcount (stay cached, evictable) instead of leaking pins."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=1, max_len=32, block_size=4,
            prompt_buckets=[8], enable_prefix_cache=True)
        sysp = [9, 9, 9, 9, 7, 7, 7, 7]
        r0 = eng.add_request(sysp, 3)
        eng.run_to_completion(max_ticks=50)
        assert len(eng._prefix_cache) > 0
        r1 = eng.add_request(sysp, 6)              # prefix hit re-pins
        eng.step()
        assert eng.prefix_hits >= 1
        assert eng.cancel(r1)
        assert not [b for b, c in eng._refs.items() if c != 0], \
            "cancel leaked prefix-cache pins"
        assert int(eng._stats.value("blocks_allocated")) == \
            int(eng._stats.value("blocks_released"))

    def test_cancel_ragged_engine(self):
        """The ragged engine (admission flows through packed steps — the
        _filling path is the norm) cancels clean at both stages."""
        from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            prompt_buckets=[8, 16], token_budget=8)
        r0 = eng.add_request(list(range(1, 14)), 6)    # spans >1 step
        r1 = eng.add_request([3, 4], 8)
        eng.step()                                 # r0 still filling
        assert eng.cancel(r0)
        got = eng.run_to_completion(max_ticks=100)
        assert sorted(got) == [r1]
        assert got[r1] == _solo_greedy(model, params, [3, 4], 8)
        assert eng.blocks_in_use == 0
        m = eng.metrics()
        assert m["blocks_allocated"] == m["blocks_released"]

    def test_cancel_from_on_token_callback(self, model_and_params):
        """A consumer cancelling its own request from inside ``on_token``
        (the reentrant case) must not desync the scheduler."""
        model, params = model_and_params
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            prompt_buckets=[8])
        seen = []

        def cb(rid, tok, done):
            seen.append((tok, done))
            if tok is not None and len(seen) == 2:
                eng.cancel(rid)
        rid = eng.add_request([5, 17, 3], 20, on_token=cb)
        other = eng.add_request([40, 2], 6)
        got = eng.run_to_completion(max_ticks=100)
        assert rid not in got and other in got
        assert (None, True) in seen
        assert eng.blocks_in_use == 0


class TestLongContextServing:
    def test_large_max_len_compiles_only_small_programs(self):
        """The long-context serving story: an engine provisioned for
        max_len=2048 serving SHORT requests compiles only the small
        length-bucket decode programs (C covering actual clocks, not
        MB=128) and the pool holds only resident blocks — provisioned
        capacity costs neither compile time nor per-sync transients."""
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=2048,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        model.__dict__.pop("_serving_programs", None)
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=3, max_len=2048, block_size=16,
            num_blocks=64, prompt_buckets=[16])
        rids = [eng.add_request(p, 12) for p in PROMPTS[:3]]
        got = eng.run_to_completion(max_ticks=100)
        for rid, p in zip(rids, PROMPTS[:3]):
            assert got[rid] == _solo_greedy(model, params, p, 12)
        cols = [k[1] for k in model._serving_programs if k[0] == "decode"]
        # 16-token bucket + 12 tokens needs 2 blocks of 16: C=2, never 128
        assert cols and max(cols) <= 2, cols
        assert eng.blocks_high_water <= 3 * 2
        assert eng.blocks_in_use == 0


class TestComposedStress:
    @pytest.mark.slow
    def test_forty_request_composition_quiesces_clean(self):
        """40 mixed requests (shared system prompt + random, random
        budgets/penalties, staggered admission) through the FULLY composed
        engine — paged + chunked prefill + prefix cache + per-request
        planes, 8 slots, tight-ish pool.  Every output equals its solo
        oracle, and at quiescence the allocator is provably clean: zero
        leaked references, blocks_in_use consists exactly of evictable
        cached prompt blocks, and the prefix registry is bijective."""
        paddle.seed(99)
        cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=3,
                        num_attention_heads=4,
                        max_position_embeddings=256,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        rng = np.random.RandomState(7)
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=8, max_len=128, block_size=8,
            num_blocks=64, prompt_buckets=[16, 32], ticks_per_sync=4,
            prefill_chunk=8, enable_prefix_cache=True,
            per_request_sampling=True)
        sysp = [int(t) for t in rng.randint(1, 211, 24)]
        reqs = []
        for _ in range(40):
            p = (sysp + [int(t) for t in
                         rng.randint(1, 211, rng.randint(1, 8))]
                 if rng.rand() < 0.5 else
                 [int(t) for t in rng.randint(1, 211, rng.randint(1, 30))])
            n = int(rng.randint(1, 24))
            kw = ({"repetition_penalty": float(rng.choice([2.0, 5.0]))}
                  if rng.rand() < 0.3 else {})
            reqs.append((eng.add_request(p, n, **kw), p, n, kw))
            for _ in range(int(rng.randint(0, 2))):
                eng.step()
        got = eng.run_to_completion(max_ticks=5000)
        # oracle-check a deterministic SAMPLE (full per-request oracle
        # coverage lives in the smaller parity/fuzz tests; this test's
        # unique value is the at-scale allocator invariants below)
        for idx in range(0, 40, 3):
            rid, p, n, kw = reqs[idx]
            solo = model.generate(params, jnp.asarray([p], jnp.int32), n,
                                  greedy=True, **kw)
            assert got[rid] == [int(t) for t in np.asarray(solo)[0]], rid
        assert eng.prefix_hits > 0
        referenced = [b for b, c in eng._refs.items() if c != 0]
        cached = sum(1 for b in eng._prefix_cache.values()
                     if eng._refs.get(b, 0) == 0)
        assert not referenced                    # zero leaked references
        assert eng.blocks_in_use == cached       # in-use == evictable
        assert len(eng._prefix_cache) == len(eng._key_of) == cached
