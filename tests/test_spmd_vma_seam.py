"""Pin the JAX-internals seam behind the pipeline engine (VERDICT r3 weak
#4): ensure_varying leans on jax._src.core.get_aval and lax.pcast/pvary,
which have moved across JAX releases.  These tests fail LOUDLY on an
incompatible JAX instead of letting the varying-cast degrade to a no-op
(which would break shard_map pipelines silently)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 top-level export
    from jax import shard_map
except ImportError:  # older jax: experimental home
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # no shard_map at all: skip, don't break collection
        shard_map = None

# spmd itself imports shard_map unconditionally (its seam adapter), so on
# a JAX with no shard_map this import must SKIP too, not error collection
spmd = pytest.importorskip("paddle_tpu.distributed.spmd")

pytestmark = pytest.mark.skipif(
    shard_map is None, reason="this JAX exposes no shard_map")


class TestVMASeam:
    def test_seam_resolved_consistently(self):
        """If this JAX tracks varying-manual-axes, a cast primitive MUST have
        been found at import (spmd would have raised ImportError otherwise —
        asserting here keeps the contract visible and versions honest)."""
        if spmd.VMA_AVALS:
            assert spmd._cast_varying is not None
            # and the aval accessor must expose .vma on real values
            assert isinstance(spmd._get_aval(jnp.ones(3)).vma, frozenset)

    def test_vma_avals_matches_this_jax(self):
        # the module-level probe must agree with a from-scratch probe
        assert spmd.VMA_AVALS == hasattr(
            jax.core.ShapedArray((), np.dtype(np.float32)), "vma")

    @pytest.mark.skipif(not hasattr(jax.core.ShapedArray((), np.dtype("f4")),
                                    "vma"),
                        reason="pre-VMA JAX: nothing to mark")
    def test_ensure_varying_marks_replicated_carry(self):
        """Inside shard_map, a replicated value must come back actually
        varying (axis in aval.vma) — the exact property the pipeline's scan
        carry needs; and an already-varying value must pass through (pcast
        rejects varying->varying, so a blind cast would raise)."""
        mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
        seen = {}

        def body(x):
            rep = jnp.float32(1.0)  # replicated: vma == frozenset()
            v = spmd.ensure_varying(rep, "pipe")
            seen["was"] = spmd._get_aval(rep).vma
            seen["now"] = spmd._get_aval(v).vma
            v2 = spmd.ensure_varying(v, "pipe")  # idempotent on varying
            return x + v + v2

        out = shard_map(body, mesh=mesh, in_specs=P("pipe"),
                        out_specs=P("pipe"))(jnp.zeros(2))
        np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
        assert "pipe" not in seen["was"]
        assert "pipe" in seen["now"]

    def test_get_aval_raises_on_garbage_not_swallowed(self):
        """The old code wrapped get_aval in `except Exception: vma = None`,
        turning real incompatibilities into silent no-ops.  The seam must
        now propagate failures."""
        if not spmd.VMA_AVALS:
            pytest.skip("pre-VMA JAX")
        with pytest.raises(Exception):
            spmd.ensure_varying(object(), "pipe")  # not a JAX value: loud
