"""Tiered KV store + disaggregated prefill/decode (ISSUE 14).

Three layers under test:

- ``paddle_tpu/kv_store.py`` in isolation: the DRAM-over-disk page
  store (LRU demotion/promotion, byte capacities, bit-exact
  serialization, corruption-degrades-to-miss) and the byte-budgeted
  :class:`PageMigration` schedule;
- the paged engines' tiering surface: a prefix lookup that misses HBM
  but hits a lower tier restores pages device-side and produces
  TOKEN-IDENTICAL streams vs the cold-recompute oracle (the acceptance
  pin), eviction demotes instead of dropping, allocator balance holds,
  and the public ``prefix_index``/``prefix_match`` API replaces the
  gateway's old private-dict reach-in;
- the gateway's disaggregated pipeline, on real engines (end-to-end
  migration, zero in-serve compiles on warmed engines) and on the
  fake-clock simulation (byte-budget pacing, quarantine-mid-migration
  falling back to recompute with zero drops, tier-aware routing,
  the autoscaler's decode-pool signal, ``GET /kvstore``).
"""

import json
import tempfile
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.kv_store import (KVPage, PageMigration, TieredKVStore,
                                 chain_hex)
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (PagedContinuousBatchingEngine,
                                RaggedPagedContinuousBatchingEngine)
from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                   sim_chain_keys, sim_tokens)
from paddle_tpu.telemetry import Tracer


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo(model, params, prompt, n):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         greedy=True)
    return [int(t) for t in np.asarray(out)[0]]


def _page(i, meta=None, nbytes=2048):
    meta = meta if meta is not None else ["t", 1]
    arr = np.full(nbytes // 4, i, np.float32)
    return KVPage(bytes([i]) * 32, (arr,), meta)


# ---------------------------------------------------------------------------
# the store in isolation
# ---------------------------------------------------------------------------

class TestTieredKVStore:
    def test_put_lookup_lru_and_meta_mismatch(self):
        st = TieredKVStore(dram_capacity_bytes=1 << 20)
        pages = [_page(i) for i in range(3)]
        for p in pages:
            st.put(p)
        got = st.lookup(pages[0].chain, meta=["t", 1])
        assert got is not None
        assert np.array_equal(got.payload[0], pages[0].payload[0])
        assert st.lookup(b"zz" * 16) is None                   # miss
        assert st.lookup(pages[1].chain, meta=["other"]) is None
        c = st.counters()
        assert c["hits_dram"] == 1 and c["misses"] == 1
        assert c["meta_mismatches"] == 1
        assert st.tier_of(pages[2].chain) == "dram"
        assert set(st.index().values()) == {"dram"}

    def test_dram_demotes_to_disk_and_promotes_back(self):
        st = TieredKVStore(dram_capacity_bytes=2 * 2048 + 100,
                           disk_dir=tempfile.mkdtemp())
        pages = [_page(i) for i in range(4)]
        for p in pages:
            st.put(p)
        snap = st.snapshot()
        assert snap["dram"]["pages"] == 2 and snap["disk"]["pages"] == 2
        # the two OLDEST pages demoted (LRU)
        assert st.tier_of(pages[0].chain) == "disk"
        assert st.tier_of(pages[3].chain) == "dram"
        # disk hit promotes back to DRAM, bit-exact
        got = st.lookup(pages[0].chain)
        assert np.array_equal(got.payload[0], pages[0].payload[0])
        assert st.tier_of(pages[0].chain) == "dram"
        assert st.counters()["promotions"] == 1
        txt = st.prometheus_text()
        assert "paddle_tpu_kvstore_dram_pages" in txt

    def test_without_disk_eviction_drops(self):
        st = TieredKVStore(dram_capacity_bytes=2048 + 100)
        st.put(_page(0))
        st.put(_page(1))                      # evicts page 0, no disk
        assert st.tier_of(_page(0).chain) is None
        assert st.counters()["evictions_dram"] == 1

    def test_disk_capacity_evicts_oldest(self):
        st = TieredKVStore(dram_capacity_bytes=2048 + 100,
                           disk_dir=tempfile.mkdtemp(),
                           disk_capacity_bytes=3 * 2048)
        for i in range(5):
            st.put(_page(i))
        snap = st.snapshot()
        assert snap["disk"]["bytes"] <= 3 * 2048
        assert st.counters()["evictions_disk"] >= 1

    def test_corrupt_disk_page_is_a_miss_not_a_wrong_page(self):
        st = TieredKVStore(dram_capacity_bytes=2048 + 100,
                           disk_dir=tempfile.mkdtemp())
        p0 = _page(0)
        st.put(p0)
        st.put(_page(1))                      # p0 -> disk
        path = st._disk[p0.chain][0]
        with open(path, "wb") as f:
            f.write(b"garbage")
        assert st.lookup(p0.chain) is None
        assert st.counters()["corrupt_pages"] == 1
        assert st.tier_of(p0.chain) is None   # dropped, not retried

    def test_page_serialization_roundtrip(self):
        meta = ["kv1", 8, [["int8", [2, 8, 4, 8]], ["float32", [2, 8, 4]]]]
        page = KVPage(b"\x01" * 32,
                      (np.arange(64, dtype=np.int8).reshape(2, 8, 4)[..., None]
                       .repeat(8, -1),
                       np.linspace(0, 1, 64, dtype=np.float32)
                       .reshape(2, 8, 4)),
                      meta)
        back = KVPage.from_bytes(page.to_bytes())
        assert back.chain == page.chain and back.meta == page.meta
        for a, b in zip(page.payload, back.payload):
            assert a.dtype == b.dtype and np.array_equal(a, b)
        # bytes payloads (the sim engines' pages) round-trip too
        simpage = KVPage("sim:4:(1, 2, 3, 4)", b"\x07" * 100, ["sim", 4])
        back = KVPage.from_bytes(simpage.to_bytes())
        assert back.chain == simpage.chain
        assert back.payload == simpage.payload
        assert chain_hex(simpage.chain) == simpage.chain

    def test_extension_dtype_pages_survive_the_disk_tier(self):
        """bfloat16 — the TPU pool dtype — must round-trip the disk
        tier with its REAL dtype: np.savez returns raw void '|V2'
        arrays for ml_dtypes extension dtypes, which the meta check
        (dtype STRINGS) cannot catch and which would crash the engine
        mid-restore instead of missing."""
        import ml_dtypes
        arr = (np.arange(32, dtype=np.float32) / 7.0) \
            .astype(ml_dtypes.bfloat16).reshape(2, 16)
        meta = ["kv1", 8, [["bfloat16", [2, 16]]]]
        page = KVPage(b"\x02" * 32, (arr,), meta)
        back = KVPage.from_bytes(page.to_bytes())
        assert back.payload[0].dtype == arr.dtype
        assert np.array_equal(back.payload[0].view(np.uint16),
                              arr.view(np.uint16))       # bit-exact
        # and through a real disk tier: still the real dtype on lookup
        st = TieredKVStore(dram_capacity_bytes=80,
                           disk_dir=tempfile.mkdtemp())
        st.put(page)
        st.put(KVPage(b"\x03" * 32, (arr,), meta))   # page -> disk
        assert st.tier_of(page.chain) == "disk"
        got = st.lookup(page.chain, meta=meta)
        assert got is not None and got.payload[0].dtype == arr.dtype

    def test_long_string_chains_get_distinct_disk_files(self):
        """Sim chains share long leading text; disk file names are a
        fixed-length digest of the chain, so near-identical chains must
        land in distinct files (a truncated-name collision would let
        the integrity check destroy both pages as 'corrupt')."""
        st = TieredKVStore(dram_capacity_bytes=1,
                           disk_dir=tempfile.mkdtemp())
        long_a = "sim:4:" + repr(tuple(range(100)))
        long_b = "sim:4:" + repr(tuple(range(101)))
        pa = KVPage(long_a, b"\x01" * 64, ["sim", 4])
        pb = KVPage(long_b, b"\x02" * 64, ["sim", 4])
        st.put(pa)                 # over the 1-byte DRAM cap -> disk
        st.put(pb)
        assert st.tier_of(long_a) == "disk"
        assert st.tier_of(long_b) == "disk"
        ga = st.lookup(long_a)
        assert ga is not None and ga.payload == pa.payload
        gb = st.lookup(long_b)
        assert gb is not None and gb.payload == pb.payload
        assert st.counters().get("corrupt_pages", 0) == 0

    def test_oversized_page_promotion_does_not_flush_dram(self):
        """A disk page wider than the whole DRAM budget is served
        disk-resident: promoting it would flush the entire warm DRAM
        tier before spilling it right back out."""
        st = TieredKVStore(dram_capacity_bytes=3000,
                           disk_dir=tempfile.mkdtemp())
        st.put(_page(0))                              # 2048 B, warm
        big = KVPage(b"\x09" * 32, (np.zeros(2000, np.float32),),
                     ["t", 1])                        # 8000 B > cap
        assert st.put(big) == "disk"
        got = st.lookup(big.chain)
        assert got is not None
        assert st.tier_of(big.chain) == "disk"        # stayed put
        assert st.tier_of(_page(0).chain) == "dram"   # tier untouched

    def test_migration_byte_budget_pacing_and_restart(self):
        pages = [_page(i) for i in range(4)]          # 4 x 2048 B
        m = PageMigration(pages, bytes_per_tick=2048)
        ticks = []
        while not m.done:
            ticks.append(len(m.advance()))
        assert ticks == [1, 1, 1, 1]                  # one page per tick
        assert m.transferred_bytes == m.total_bytes == 4 * 2048
        # a page wider than the budget spans ticks, delivery page-granular
        m2 = PageMigration(pages[:1], bytes_per_tick=1000)
        assert m2.advance() == [] and m2.advance() == []
        assert m2.advance() == [pages[0]] and m2.done
        m2.restart()
        assert m2.remaining_bytes == 2048 and not m2.done
        # unbounded: everything in one tick
        m3 = PageMigration(pages, bytes_per_tick=None)
        assert len(m3.advance()) == 4 and m3.done


# ---------------------------------------------------------------------------
# engine-side tiering (real paged engines)
# ---------------------------------------------------------------------------

PROMPT = list(range(1, 25))          # 24 tokens -> bucket 32 (bs 8)


def _engine(cls, model, params, store=None, tracer=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("prompt_buckets", [8, 32])
    return cls(model, params, enable_prefix_cache=True, kv_store=store,
               tracer=tracer, **kw)


class TestEngineTiering:
    @pytest.mark.parametrize("cls", [PagedContinuousBatchingEngine,
                                     RaggedPagedContinuousBatchingEngine])
    def test_lower_tier_restore_is_token_exact(self, model_and_params,
                                               cls):
        """THE pinned contract: prime -> flush out of HBM -> re-serve;
        the restored stream equals the cold-recompute oracle byte for
        byte, allocator balanced at quiescence, demote/restore events
        emitted."""
        model, params = model_and_params
        oracle = _solo(model, params, PROMPT, 6)
        tracer = Tracer()
        store = TieredKVStore()
        eng = _engine(cls, model, params, store=store, tracer=tracer)
        r1 = eng.add_request(PROMPT, 6)
        assert eng.run_to_completion(max_ticks=300)[r1] == oracle
        demoted = eng.flush_prefix()
        assert demoted > 0 and len(eng._prefix_cache) == 0
        assert store.snapshot()["dram"]["pages"] == demoted
        r2 = eng.add_request(PROMPT, 6)
        out = eng.run_to_completion(max_ticks=300)
        assert out[r2] == oracle, "lower-tier restore diverged"
        m = eng.metrics()
        assert m["kvstore_restored_blocks"] >= 1
        assert m["kvstore_demoted_blocks"] >= demoted
        # quiescence balance: every pin/alloc matched by a release
        # (cached refs-0 blocks count released at their 1->0)
        assert m["blocks_allocated"] == m["blocks_released"]
        kinds = {e["what"] for e in tracer.events("kvstore")}
        assert {"demote", "restore"} <= kinds
        # prefix API is PUBLIC (the gateway router's new read)
        pm = eng.prefix_match(PROMPT)
        assert pm["total"] >= 1 and set(pm["tiers"]) <= {"hbm", "dram",
                                                         "disk"}
        assert set(eng.prefix_index().values()) <= {"hbm", "dram", "disk"}

    def test_int8_pages_ride_the_disk_tier_token_exact(self):
        """int8 pools ship value + fp32 scale planes as page leaves; a
        tiny DRAM cap forces the DISK tier (serialization) into the
        restore path — still token-exact."""
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype="int8")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        oracle = _solo(model, params, PROMPT, 6)
        store = TieredKVStore(dram_capacity_bytes=2048,
                              disk_dir=tempfile.mkdtemp())
        eng = _engine(RaggedPagedContinuousBatchingEngine, model, params,
                      store=store)
        r1 = eng.add_request(PROMPT, 6)
        assert eng.run_to_completion(max_ticks=300)[r1] == oracle
        eng.flush_prefix()
        assert store.snapshot()["disk"]["pages"] >= 1
        r2 = eng.add_request(PROMPT, 6)
        assert eng.run_to_completion(max_ticks=300)[r2] == oracle

    def test_eviction_demotes_instead_of_dropping(self, model_and_params):
        """A pool sized so serving a second prompt EVICTS the first
        one's cached pages: they land in the store, and re-serving
        prompt 1 restores instead of recomputing."""
        model, params = model_and_params
        p1, p2 = PROMPT, [int(t) for t in range(40, 64)]
        o1 = _solo(model, params, p1, 4)
        store = TieredKVStore()
        # 7 blocks: p2 shares p1's all-pad first block (a real prefix
        # hit), takes 3 fresh for its admission, and its decode growth
        # then finds the free list EMPTY — eviction must demote one of
        # p1's cached pages instead of dropping it
        eng = _engine(RaggedPagedContinuousBatchingEngine, model, params,
                      store=store, num_blocks=7)
        r1 = eng.add_request(p1, 4)
        eng.run_to_completion(max_ticks=300)
        r2 = eng.add_request(p2, 4)
        eng.run_to_completion(max_ticks=300)
        assert eng.metrics()["kvstore_demoted_blocks"] >= 1
        r3 = eng.add_request(p1, 4)
        out = eng.run_to_completion(max_ticks=300)
        assert out[r3] == o1
        assert eng.metrics()["kvstore_restored_blocks"] >= 1

    def test_kvio_in_warmup_grid_and_zero_in_serve_compiles(
            self, model_and_params):
        """A warmed engine restores lower-tier pages with ZERO in-serve
        compiles — the page gather/scatter programs are part of the
        warmup grid (the ``kvio`` task)."""
        model, params = model_and_params
        store = TieredKVStore()
        eng = _engine(RaggedPagedContinuousBatchingEngine, model, params,
                      store=store)
        assert "kvio" in eng.compile_grid()
        # store-LESS prefix engines gather pages at EXPORT time
        # (prefill-role replicas) — kvio rides their grid too
        plain = _engine(RaggedPagedContinuousBatchingEngine, model,
                        params)
        assert "kvio" in plain.compile_grid()
        eng.warmup(max_workers=1)
        misses = eng._compile_misses
        r1 = eng.add_request(PROMPT, 4)
        eng.run_to_completion(max_ticks=300)
        eng.flush_prefix()
        r2 = eng.add_request(PROMPT, 4)
        eng.run_to_completion(max_ticks=300)
        assert eng.metrics()["kvstore_restored_blocks"] >= 1
        assert eng._compile_misses == misses, "in-serve compiles"

    def test_kv_store_requires_prefix_cache(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="enable_prefix_cache"):
            RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=64, block_size=8,
                prompt_buckets=[8, 32], kv_store=TieredKVStore())


# ---------------------------------------------------------------------------
# disaggregated prefill/decode — real engines
# ---------------------------------------------------------------------------

class TestDisaggRealEngines:
    def test_migration_end_to_end_token_exact_zero_compiles(
            self, model_and_params):
        """The acceptance pin: a prefill-role replica produces the
        pages, the gateway migrates them into the decode replica's
        store, the request completes there token-for-token equal to the
        solo oracle — and neither warmed engine compiles anything on the
        serving path (zero extra program families)."""
        model, params = model_and_params
        oracle = _solo(model, params, PROMPT, 6)
        tracer = Tracer()
        gw = ServingGateway(tracer=tracer, migration_bytes_per_tick=4096)
        prefill = _engine(RaggedPagedContinuousBatchingEngine, model,
                          params, tracer=Tracer())
        decode = _engine(RaggedPagedContinuousBatchingEngine, model,
                         params, store=TieredKVStore(), tracer=Tracer())
        prefill.warmup(max_workers=1)
        decode.warmup(max_workers=1)
        misses0 = prefill._compile_misses + decode._compile_misses
        gw.add_replica(prefill, "pf", role="prefill")
        gw.add_replica(decode, "dc", role="decode")
        toks = []
        req = gw.submit(PROMPT, 6, on_token=lambda g, t, d:
                        toks.append(t) if t is not None else None)
        n = 0
        while gw.pending():
            gw.step()
            n += 1
            assert n < 500
        out = gw.pop_finished()
        assert req.status == "finished" and out[req.gid] == oracle
        assert toks == oracle                    # single-sourced stream
        assert req.replica == "dc"
        snap = gw.kvstore_snapshot()
        assert snap["counters"]["migrations_completed"] == 1
        assert snap["counters"]["migrated_bytes"] > 0
        assert decode.metrics()["kvstore_restored_blocks"] >= 1
        assert prefill._compile_misses + decode._compile_misses \
            == misses0, "migration added in-serve compiles"
        kinds = [e["what"] for e in tracer.events("kvstore")]
        for want in ("prefill_start", "migrate_start", "migrate_done"):
            assert want in kinds, kinds
        # fleet index: both replicas now hold the prompt's pages
        idx = gw.prefix_index(PROMPT)
        assert idx["dc"]["total"] >= 1
        assert "paddle_tpu_kvstore" in gw.prometheus_text()

    def test_mismatched_bucket_ladders_fall_back_cleanly(
            self, model_and_params):
        """Chain digests are seeded with the bucket-dependent pad: a
        destination with a DIFFERENT prompt-bucket ladder could never
        restore the migrated pages.  The page meta carries the ladder,
        so the dest-picker rejects it and the pipeline degrades to
        recompute — counted, never a silent always-miss migration."""
        model, params = model_and_params
        oracle = _solo(model, params, PROMPT, 4)
        gw = ServingGateway(migration_bytes_per_tick=None)
        prefill = _engine(RaggedPagedContinuousBatchingEngine, model,
                          params)                    # buckets [8, 32]
        decode = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=64, block_size=8,
            prompt_buckets=[16, 32], enable_prefix_cache=True,
            kv_store=TieredKVStore())                # different ladder
        gw.add_replica(prefill, "pf", role="prefill")
        gw.add_replica(decode, "dc", role="decode")
        req = gw.submit(PROMPT, 4)
        gw.run_to_completion(max_ticks=500)
        assert req.status == "finished" and req.tokens == oracle
        c = gw.kvstore_snapshot()["counters"]
        assert c.get("migration_fallbacks", 0) == 1
        assert c.get("migrations_completed", 0) == 0

    def test_prefill_role_excluded_from_request_routing(
            self, model_and_params):
        """With no decode-capable replica a prefill-only fleet never
        admits (no silent half-service), and a narrow prompt skips the
        pipeline onto the unified path."""
        model, params = model_and_params
        gw = ServingGateway(migration_bytes_per_tick=None)
        prefill = _engine(RaggedPagedContinuousBatchingEngine, model,
                          params)
        unified = _engine(RaggedPagedContinuousBatchingEngine, model,
                          params)
        gw.add_replica(prefill, "pf", role="prefill")
        gw.add_replica(unified, "un")            # unified, no store
        # narrow prompt (< 2 blocks): not exportable -> plain dispatch
        req = gw.submit([5, 17, 3], 4)
        gw.run_to_completion(max_ticks=300)
        assert req.status == "finished" and req.replica == "un"
        assert gw.kvstore_snapshot()["counters"].get(
            "prefill_dispatches", 0) == 0


# ---------------------------------------------------------------------------
# disaggregated prefill/decode — fake-clock simulation
# ---------------------------------------------------------------------------

def _sim_fleet(clock, *, bytes_per_tick=1024, stall_threshold_s=30.0,
               page_bytes=1024, prefill_ticks_per_block=2,
               extra_unified=False):
    tr = SimTracer(clock, capacity=8192)
    gw = ServingGateway(clock=clock, tracer=tr,
                        stall_threshold_s=stall_threshold_s,
                        migration_bytes_per_tick=bytes_per_tick)
    pf = SimEngine(max_slots=2, tracer=SimTracer(clock),
                   prefix_caching=True, block_size=4,
                   page_bytes=page_bytes,
                   prefill_ticks_per_block=prefill_ticks_per_block)
    dc = SimEngine(max_slots=2, tracer=SimTracer(clock),
                   prefix_caching=True, block_size=4,
                   page_bytes=page_bytes,
                   prefill_ticks_per_block=prefill_ticks_per_block,
                   kv_store=TieredKVStore())
    gw.add_replica(pf, "pf", role="prefill")
    gw.add_replica(dc, "dc", role="decode")
    if extra_unified:
        gw.add_replica(SimEngine(max_slots=2, tracer=SimTracer(clock)),
                       "un")
    return gw, tr, pf, dc


def _drive(gw, clock, dt=0.25, limit=500):
    n = 0
    while gw.pending():
        gw.step()
        clock.advance(dt)
        n += 1
        assert n < limit, "sim did not drain"
    return n


class TestDisaggSim:
    def test_migration_byte_budget_pacing_and_stream(self):
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, bytes_per_tick=1024)
        prompt = list(range(1, 17))              # 4 blocks of 4
        h = gw.submit(prompt, 8)
        _drive(gw, clock)
        assert h.status == "finished"
        assert h.tokens == sim_tokens(prompt, 8)
        done = [e for e in tr.events("kvstore")
                if e["what"] == "migrate_done"][0]
        assert done["bytes"] == 4 * 1024
        assert done["ticks"] >= 4                 # 1 KiB budget, 4 pages
        assert dc.stats.value("kvstore_restored_blocks") >= 1
        assert tr.summary()["kvstore"]["migrated_bytes"] == 4096

    def test_slow_budgeted_migration_outlives_stall_threshold(self):
        """Delivery is liveness: a migration legitimately paced over
        many more ticks than ``stall_threshold_s`` by the byte budget
        must COMPLETE (the timeout bounds no-progress time, not total
        transfer time)."""
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, bytes_per_tick=64,
                                    stall_threshold_s=2.0,
                                    prefill_ticks_per_block=0)
        prompt = list(range(1, 17))      # 4 KiB at 64 B/tick: 64 ticks
        h = gw.submit(prompt, 4)         # = 16 sim-seconds >> 2.0
        _drive(gw, clock, limit=2000)
        assert h.status == "finished"
        assert h.tokens == sim_tokens(prompt, 4)
        c = gw.kvstore_snapshot()["counters"]
        assert c["migrations_completed"] == 1
        assert c.get("migration_fallbacks", 0) == 0

    def test_warm_destination_skips_repeat_migration(self):
        """A routable replica already covering the prompt makes the
        pipeline pure overhead: the repeat request dispatches straight
        to the warm replica — no second prefill, no re-migrated
        bytes."""
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, bytes_per_tick=None)
        prompt = list(range(1, 17))
        h1 = gw.submit(prompt, 4)
        _drive(gw, clock)
        assert gw.kvstore_snapshot()["counters"][
            "migrations_started"] == 1
        h2 = gw.submit(prompt, 4)
        _drive(gw, clock)
        assert h2.status == "finished"
        assert h2.tokens == sim_tokens(prompt, 4)
        assert h2.replica == "dc"            # affinity found the warmth
        c = gw.kvstore_snapshot()["counters"]
        assert c["migrations_started"] == 1  # no re-migration
        assert c["prefill_dispatches"] == 1

    def test_quarantine_mid_migration_falls_back_zero_drops(self):
        """The fake-clock chaos pin: the destination dies while pages
        are mid-flight — the pipeline degrades to recompute on another
        replica, the stream stays exact, nothing drops."""
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, bytes_per_tick=256,
                                    stall_threshold_s=1000.0,
                                    extra_unified=True)
        prompt = list(range(1, 17))
        h = gw.submit(prompt, 6)
        killed = False
        n = 0
        while gw.pending():
            gw.step()
            clock.advance(0.25)
            n += 1
            if not killed and any(
                    j["phase"] == "migrate" for j in
                    gw.kvstore_snapshot()["migrations_inflight"]):
                gw.quarantine("dc", "chaos")
                killed = True
            assert n < 500
        assert killed, "never caught the migrate phase"
        assert h.status == "finished"
        assert h.tokens == sim_tokens(prompt, 6)
        counters = gw.kvstore_snapshot()["counters"]
        assert counters.get("migration_fallbacks", 0) >= 1
        assert any(e["what"] == "fallback"
                   for e in tr.events("kvstore"))

    def test_prefill_replica_lost_falls_back(self):
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, prefill_ticks_per_block=50)
        prompt = list(range(1, 17))
        h = gw.submit(prompt, 6)
        gw.step()                    # prefill dispatched
        assert gw.kvstore_snapshot()["migrations_inflight"]
        gw.quarantine("pf", "chaos")
        _drive(gw, clock)
        assert h.status == "finished"
        assert h.tokens == sim_tokens(prompt, 6)
        assert gw.kvstore_snapshot()["counters"][
            "migration_fallbacks"] >= 1

    def test_cancel_and_deadline_mid_pipeline(self):
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, prefill_ticks_per_block=50)
        prompt = list(range(1, 17))
        # client cancel mid-prefill
        h1 = gw.submit(prompt, 6)
        gw.step()
        assert gw.kvstore_snapshot()["migrations_inflight"]
        assert gw.cancel(h1.gid) is True
        assert h1.status == "cancelled"
        assert not gw.kvstore_snapshot()["migrations_inflight"]
        # ttft deadline expires mid-pipeline: structured, zero leaks
        h2 = gw.submit(prompt, 6, ttft_deadline_s=1.0)
        n = 0
        while gw.pending():
            gw.step()
            clock.advance(0.5)
            n += 1
            assert n < 200
        assert h2.status == "expired"
        assert h2.error is not None and h2.error.kind == "ttft"

    def test_prefill_breaker_probe_resolves_and_releases(self):
        """The gateway-internal prefill attempt never reaches harvest/
        _finalize, so the HALF_OPEN probe it claims must resolve through
        the pipeline itself: a completed prefill CLOSES the breaker, a
        cancelled pipeline RELEASES the claim — a single breaker trip
        must not disable disaggregation forever."""
        from paddle_tpu.gateway import CircuitBreaker, ResiliencePolicy
        clock = SimClock()
        pol = ResiliencePolicy(retry_budget=1, retry_jitter=0.0,
                               breaker_failures=1, breaker_open_s=1.0,
                               hedge=False, brownout=False)
        gw = ServingGateway(clock=clock, tracer=SimTracer(clock),
                            resilience=pol, stall_threshold_s=1000.0,
                            migration_bytes_per_tick=None)
        pf = SimEngine(max_slots=2, tracer=SimTracer(clock),
                       prefix_caching=True, block_size=4,
                       prefill_ticks_per_block=5)
        dc = SimEngine(max_slots=2, tracer=SimTracer(clock),
                       prefix_caching=True, block_size=4,
                       kv_store=TieredKVStore())
        gw.add_replica(pf, "pf", role="prefill")
        gw.add_replica(dc, "dc", role="decode")
        gw.quarantine("pf", "chaos")     # breaker failure -> OPEN
        gw.reinstate("pf")
        assert gw._breakers["pf"].state == CircuitBreaker.OPEN
        clock.advance(1.5)               # past open_s
        prompt = list(range(1, 17))
        h = gw.submit(prompt, 4)
        _drive(gw, clock)
        assert h.status == "finished"
        assert h.tokens == sim_tokens(prompt, 4)
        assert gw._breakers["pf"].state == CircuitBreaker.CLOSED
        c = gw.kvstore_snapshot()["counters"]
        assert c["migrations_completed"] == 1
        # cancel mid-prefill: the claim is released, not leaked
        gw.quarantine("pf", "chaos2")
        gw.reinstate("pf")
        clock.advance(1.5)
        h2 = gw.submit([t + 50 for t in prompt], 4)
        gw.step()                        # prefill dispatched (slow)
        cb = gw._breakers["pf"]
        assert cb.state == CircuitBreaker.HALF_OPEN \
            and cb.probe_gid == h2.gid
        assert gw.cancel(h2.gid) is True
        assert cb.probe_gid is None      # claim freed for the next probe
        _drive(gw, clock)

    def test_scrape_threads_race_clean_during_migration(self,
                                                        lock_sanitizer):
        """Regression for the unlocked ``_disagg`` reads: scrape-surface
        calls (``decode_pool_pressure`` / ``has_kv_surface`` / the
        snapshots / prometheus) used to read the migration table bare
        while ``step()`` popped completed jobs — a torn iterate on the
        ops thread.  Hammer the whole scrape surface from three threads
        while migrations start and finish; every lock is sanitizer-
        instrumented, so an inversion or a raced iterate fails here."""
        import threading

        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, bytes_per_tick=64,
                                    prefill_ticks_per_block=0)
        lock_sanitizer.instrument(gw)
        errors, stop = [], threading.Event()

        def scrape():
            try:
                while not stop.is_set():
                    gw.decode_pool_pressure()
                    gw.has_kv_surface()
                    gw.gateway_snapshot()
                    gw.kvstore_snapshot()
                    gw.prometheus_text()
            except Exception as e:  # noqa: BLE001 — repro harness
                errors.append(e)

        threads = [threading.Thread(target=scrape, name=f"scrape{i}")
                   for i in range(3)]
        for t in threads:
            t.start()
        try:
            for i in range(6):
                prompt = [t + 20 * i for t in range(1, 17)]
                h = gw.submit(prompt, 4)
                _drive(gw, clock, limit=2000)
                assert h.status == "finished"
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors

    def test_tier_aware_routing_deep_dram_beats_shallow_hbm(self):
        """The fleet-index contract: a replica whose DRAM tier holds a
        DEEP prefix outranks one with a shallow HBM hit."""
        clock = SimClock()
        gw = ServingGateway(clock=clock)
        shallow = SimEngine(max_slots=2, prefix_caching=True,
                            block_size=4)
        deep = SimEngine(max_slots=2, prefix_caching=True, block_size=4,
                         kv_store=TieredKVStore())
        gw.add_replica(shallow, "shallow")
        gw.add_replica(deep, "deep", role="decode")
        prompt = list(range(1, 17))              # 4 blocks
        chains = sim_chain_keys(prompt, 4)
        shallow._register_chains(prompt[:4])     # 1 HBM block
        for chain in chains[:3]:                 # 3 DRAM blocks
            deep.kv_store.put(KVPage(chain, b"\0" * 16,
                                     deep.kv_page_meta()))
        assert gw.prefix_index(prompt)["deep"]["total"] == 3
        assert gw.prefix_index(prompt)["shallow"]["total"] == 1
        h = gw.submit(prompt, 4)
        _drive(gw, clock)
        assert h.replica == "deep"
        assert h.tokens == sim_tokens(prompt, 4)

    def test_autoscaler_decode_pool_signal(self):
        from paddle_tpu.autoscaler import ElasticAutoscaler
        clock = SimClock()
        gw = ServingGateway(clock=clock, max_queue_depth=256)
        dc = SimEngine(max_slots=1, prefix_caching=True, block_size=4,
                       kv_store=TieredKVStore())
        gw.add_replica(dc, "dc", role="decode")
        asc = ElasticAutoscaler(gw, factory=lambda: SimEngine(max_slots=1),
                                clock=clock, decode_pool_high=2.0,
                                min_replicas=1, max_replicas=3)
        for _ in range(6):
            gw.submit([1, 2, 3], 4)
        made = asc.evaluate()
        assert any(d["action"] == "scale_up"
                   and "decode_pool" in d.get("reason", "")
                   for d in made), made
        snap = asc.autoscaler_snapshot()
        assert snap["signals"]["decode_pool_pressure"] is not None
        assert snap["signals"]["decode_pool_high"] == 2.0

    def test_ops_kvstore_route_live_and_404(self):
        from paddle_tpu.ops_server import OpsServer
        clock = SimClock()
        gw, tr, pf, dc = _sim_fleet(clock, bytes_per_tick=None)
        h = gw.submit(list(range(1, 17)), 4)
        _drive(gw, clock)
        assert h.status == "finished"
        srv = OpsServer()
        srv.attach(gw, "gw")
        url = srv.start()
        try:
            live = json.loads(urllib.request.urlopen(
                url + "/kvstore", timeout=10).read())
            assert live["counters"]["migrations_completed"] == 1
            assert live["replicas"]["dc"]["store"] is not None
            assert live["prefix_index"]["dc"]["role"] == "decode"
            txt = urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode()
            assert "paddle_tpu_kvstore_migrated_bytes" in txt
        finally:
            srv.stop()
        # 404 without any KV surface
        srv2 = OpsServer()
        srv2.attach(ServingGateway(), "plain")
        url2 = srv2.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url2 + "/kvstore", timeout=10)
            assert err.value.code == 404
        finally:
            srv2.stop()

    def test_standalone_store_attach_serves_kvstore(self):
        from paddle_tpu.ops_server import OpsServer
        st = TieredKVStore()
        st.put(_page(1))
        srv = OpsServer()
        srv.attach(st, "solo")
        url = srv.start()
        try:
            live = json.loads(urllib.request.urlopen(
                url + "/kvstore", timeout=10).read())
            assert live["dram"]["pages"] == 1
        finally:
            srv.stop()
