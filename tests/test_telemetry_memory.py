"""Memory ledger (ISSUE 17): exhaustive byte attribution for device and
host pools, the way the goodput ledger attributes wall clock.

The tentpole contracts under test:

- **conservation** — one ``census()`` classifies every live array by
  identity; the residual lands in ``other``, so ``sum(pool device
  bytes) == census total`` holds BY CONSTRUCTION (over- and
  under-registration stay visible, never silently clipped);
- **watermarks** — per-pool and per-space peaks are monotone, and every
  crossing lands in the bounded ring (and the Tracer, when attached);
- **surfaces** — ``GET /memory`` beside ``/ledger``, pool gauges merged
  into ``/metrics``, engine ``metrics()`` conditional keys, chrome
  counter tracks, and the flight recorder's ``*-forensics.json`` OOM
  section under a faults.py-injected allocation failure;
- **zero-cost-off** — no active ledger by default, every seam is one
  ``is None`` check, and the compiled train step is byte-identical with
  and without an active ledger (the PR 2 telemetry-off parity pin).
"""

import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import telemetry_memory as tm
from paddle_tpu.faults import (Fault, FaultPlan, FaultyEngine,
                               InjectedAllocationError)
from paddle_tpu.jit.functional import make_train_step
from paddle_tpu.kv_store import KVPage, TieredKVStore
from paddle_tpu.optimizer import Momentum
from paddle_tpu.telemetry import Tracer, TrainMonitor
from paddle_tpu.telemetry_ledger import FlightRecorder
from paddle_tpu.telemetry_memory import (MemoryLedger, account_bytes,
                                         current_memory_ledger)


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def _tiny_step(monitor=None, seed=0):
    paddle.seed(seed)
    layer = nn.Linear(4, 3)
    step, state = make_train_step(layer, nn.MSELoss(),
                                  Momentum(learning_rate=0.1, momentum=0.9),
                                  donate=False, monitor=monitor)
    x = jnp.ones((8, 4))
    y = jnp.zeros((8, 3))
    return step, state, (jax.random.key(0), np.float32(0.1), [x], [y])


# ---------------------------------------------------------------------------
# the ledger in isolation
# ---------------------------------------------------------------------------

class TestConservation:
    def test_sum_of_pools_equals_census_total(self):
        """THE invariant: pool device bytes sum exactly to the census
        total, with unregistered arrays visible in ``other``."""
        ml = MemoryLedger()
        params = {"w": jnp.ones((64, 64)), "b": jnp.ones((64,))}
        stray = jnp.ones((32, 32))          # deliberately unregistered
        ml.register_tree("params", params)
        walk = ml.census()
        assert sum(walk["pools"].values()) == walk["total_bytes"]
        assert walk["pools"]["params"] >= 64 * 64 * 4
        # the stray array is live and classified, just not registered
        assert walk["pools"]["other"] >= stray.nbytes
        snap = ml.memory_snapshot()
        assert sum(p["device_bytes"] for p in snap["pools"].values()) \
            == snap["totals"]["device_bytes"]

    def test_reregistration_replaces_and_unregister_demotes(self):
        ml = MemoryLedger()
        a = jnp.ones((16, 16))
        ml.register_tree("params", {"a": a}, name="t")
        assert ml.census()["pools"]["params"] >= a.nbytes
        ml.unregister_tree("t")
        walk = ml.census()
        # same bytes, now honest residual
        assert walk["pools"]["params"] == 0
        assert walk["pools"]["other"] >= a.nbytes

    def test_register_train_state_key_table(self):
        ml = MemoryLedger()
        state = {"params": {"w": jnp.ones((8,))},
                 "opt": {"m": jnp.ones((8,))},
                 "comm_e": jnp.ones((4,))}
        ml.register_train_state(state, name="s")
        walk = ml.census()
        assert walk["pools"]["params"] >= 32
        assert walk["pools"]["optimizer_state"] >= 32
        assert walk["pools"]["grads_comm_buffers"] >= 16
        # a re-registered state WITHOUT comm_e drops the stale bucket
        ml.register_train_state({"params": state["params"],
                                 "opt": state["opt"]}, name="s")
        assert ml.census()["pools"]["grads_comm_buffers"] == 0

    def test_rejects_other_and_unknown_pools(self):
        ml = MemoryLedger()
        with pytest.raises(ValueError, match="unknown pool"):
            ml.register_tree("other", {})
        with pytest.raises(ValueError, match="unknown pool"):
            ml.account("nope", 1)
        with pytest.raises(ValueError, match="unknown kv tier"):
            ml.account("kv_pages", 1, tier="l2")


class TestWatermarks:
    def test_peaks_are_monotone_and_ring_records_crossings(self):
        ml = MemoryLedger()
        ml.account("executables", 1000)
        ml.account("executables", -600)
        ml.account("executables", 100)      # 500 live, below the 1000 peak
        snap = ml.memory_snapshot()
        assert snap["pools"]["executables"]["host_bytes"] == 500
        assert snap["pools"]["executables"]["host_peak_bytes"] == 1000
        ml.account("executables", 5000)     # new watermark
        snap2 = ml.memory_snapshot()
        assert snap2["pools"]["executables"]["host_peak_bytes"] == 5500
        # peaks never decrease across any sequence of accounts
        assert snap2["pools"]["executables"]["host_peak_bytes"] \
            >= snap["pools"]["executables"]["host_peak_bytes"]
        assert snap2["totals"]["host_peak_bytes"] >= \
            snap["totals"]["host_peak_bytes"]
        crossings = snap2["watermarks"]
        assert crossings, "watermark ring is empty"
        for ev in crossings:
            assert ev["bytes"] > ev["prev_bytes"]

    def test_release_below_zero_clamps_with_warning(self, caplog):
        ml = MemoryLedger()
        with caplog.at_level("WARNING"):
            ml.account("executables", -100)
        assert ml.memory_snapshot()["pools"]["executables"]["host_bytes"] \
            == 0
        assert any("below zero" in r.message for r in caplog.records)

    def test_watermark_emits_tracer_event(self):
        tr = Tracer()
        ml = MemoryLedger(tracer=tr)
        ml.account("kv_pages", 4096, tier="dram")
        kinds = [e["kind"] for e in tr.events()]
        assert "memory" in kinds
        ev = [e for e in tr.events() if e["kind"] == "memory"][0]
        assert ev["what"] == "watermark" and ev["pool"] == "kv_pages"


class TestTiersAndSeams:
    def test_set_bytes_tiers_sum_into_kv_pool(self):
        ml = MemoryLedger()
        ml.set_bytes("kv_pages", 1000, tier="dram")
        ml.set_bytes("kv_pages", 300, tier="disk")
        ml.set_bytes("kv_pages", 700, tier="dram")    # absolute resync down
        snap = ml.memory_snapshot()
        assert snap["kv_tiers"]["dram"]["bytes"] == 700
        assert snap["kv_tiers"]["dram"]["peak_bytes"] == 1000
        assert snap["kv_tiers"]["disk"]["bytes"] == 300
        assert snap["pools"]["kv_pages"]["host_bytes"] == 1000

    def test_kv_store_mutations_resync_tier_bytes(self):
        ml = MemoryLedger()
        with ml:
            st = TieredKVStore(dram_capacity_bytes=1 << 20)
            pg = KVPage(b"k" * 32, (np.ones((64,), np.float32),), ["m"])
            st.put(pg)
            assert ml.memory_snapshot()["kv_tiers"]["dram"]["bytes"] \
                == pg.nbytes
            st.drop(pg.chain)
            assert ml.memory_snapshot()["kv_tiers"]["dram"]["bytes"] == 0
            assert ml.memory_snapshot()["kv_tiers"]["dram"]["peak_bytes"] \
                == pg.nbytes

    def test_active_ledger_protocol(self):
        assert current_memory_ledger() is None
        ml = MemoryLedger()
        with ml:
            assert current_memory_ledger() is ml
            inner = MemoryLedger()
            with inner:
                assert current_memory_ledger() is inner
            assert current_memory_ledger() is ml
        assert current_memory_ledger() is None
        account_bytes("executables", 123)    # no active ledger: a no-op
        assert ml.memory_snapshot()["pools"]["executables"]["host_bytes"] \
            == 0


# ---------------------------------------------------------------------------
# exports: prometheus round-trip, chrome counters, /memory endpoint
# ---------------------------------------------------------------------------

class TestExports:
    def test_prometheus_round_trip_preserves_conservation(self):
        ml = MemoryLedger()
        ml.register_tree("params", {"w": jnp.ones((32, 32))})
        ml.census()
        ml.account("executables", 2048)
        text = ml.prometheus_text()
        gauges = {}
        for line in text.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, val = line.rsplit(" ", 1)
            gauges[name] = float(val)
        total = gauges["paddle_tpu_memory_total_device_bytes"]
        summed = sum(v for k, v in gauges.items()
                     if k.endswith("_device_bytes")
                     and not k.endswith("peak_bytes")
                     and "total" not in k and "kv_" not in k)
        assert summed == total
        assert gauges["paddle_tpu_memory_executables_host_bytes"] == 2048
        assert gauges["paddle_tpu_memory_watermark_events_total"] >= 1
        assert gauges["paddle_tpu_memory_census_runs_total"] == 1

    def test_chrome_counters_one_track_per_space(self):
        ml = MemoryLedger()
        ml.account("executables", 100)
        ml.register_tree("params", {"w": jnp.ones((8,))})
        ml.census()
        evs = ml.to_chrome_counters()
        counters = [e for e in evs if e.get("ph") == "C"]
        assert counters
        names = {e["name"] for e in counters}
        assert names <= {"device_memory_bytes", "host_memory_bytes"}
        assert all("args" in e and "ts" in e for e in counters)
        # the offline twin consumes dump_json output identically
        offline = tm.chrome_counters_from_memory_dump(ml.to_dict())
        assert [e for e in offline if e.get("ph") == "C"]

    def test_memory_endpoint_schema_and_metrics_merge(self):
        ml = MemoryLedger()
        ml.register_tree("params", {"w": jnp.ones((16, 16))})
        ml.census()
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer().attach(ml, name="mem")
        url = srv.start()
        try:
            status, body = _get_json(url + "/memory")
            assert status == 200
            for key in ("pools", "kv_tiers", "totals", "per_device",
                        "census", "counts", "watermarks"):
                assert key in body, key
            for pool, row in body["pools"].items():
                assert set(row) == {"device_bytes", "host_bytes",
                                    "device_peak_bytes", "host_peak_bytes"}
            assert sum(r["device_bytes"] for r in body["pools"].values()) \
                == body["totals"]["device_bytes"]
            with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
                text = r.read().decode()
            assert "paddle_tpu_memory_params_device_bytes" in text
        finally:
            srv.stop()

    def test_memory_endpoint_404_without_ledger(self):
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer().attach(Tracer(), name="t")
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/memory", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()


import urllib.error  # noqa: E402  (used by the 404 test above)


# ---------------------------------------------------------------------------
# trainer seam: builder registration + per-step re-registration
# ---------------------------------------------------------------------------

class TestTrainerSeam:
    def test_builder_registers_state_and_steps_reregister(self):
        ml = MemoryLedger()
        with ml:
            step, state, args = _tiny_step(monitor=TrainMonitor())
            walk = ml.census()
            assert walk["pools"]["params"] > 0
            assert walk["pools"]["optimizer_state"] > 0
            state, _ = step(state, *args)
            # the donated/rebuilt state re-registered: the fresh ids
            # classify, so a census right after a step stays attributed
            walk2 = ml.census()
            assert walk2["pools"]["params"] > 0
            assert walk2["pools"]["optimizer_state"] > 0

    def test_forensics_names_largest_arrays_with_paths(self):
        ml = MemoryLedger()
        big = {"w": jnp.ones((128, 128))}
        ml.register_tree("params", big, name="model")
        ml.census()
        f = ml.forensics()
        assert f["largest_arrays"], "no largest-array rows"
        top = f["largest_arrays"][0]
        assert top["pool"] in ("params", "other")
        named = [r for r in f["largest_arrays"] if r["pool"] == "params"]
        assert named and named[0]["path"]      # tree path survives
        assert f["top_pools"] and f["allocator"] is not None


# ---------------------------------------------------------------------------
# OOM forensics: injected allocation failure -> flight-recorder section
# ---------------------------------------------------------------------------

class _TickEngine:
    def step(self):
        return None

    def pending(self):
        return False


class TestOOMForensics:
    def test_alloc_fail_dump_carries_forensics_section(self, tmp_path):
        """The OOM post-mortem path end to end: a faults.py-injected
        allocation failure raises a real ``MemoryError``; the crash dump
        gains ``<name>-forensics.json`` with top pools, recent growth,
        and the largest arrays (with tree paths) — the evidence an OOM
        debug needs, captured at death."""
        ml = MemoryLedger()
        ml.register_tree("params", {"w": jnp.ones((64, 64))}, name="m")
        ml.census()
        ml.account("kv_pages", 1 << 19, tier="dram")   # growth to report:
        ml.account("kv_pages", 1 << 19, tier="dram")   # two samples delta
        fr = FlightRecorder(str(tmp_path / "crash"), sources=[ml])
        eng = FaultyEngine(_TickEngine(),
                           FaultPlan([Fault("alloc_fail", count=1)]),
                           clock=lambda: 1.0)
        with pytest.raises(MemoryError) as ei:
            eng.step()
        assert isinstance(ei.value, InjectedAllocationError)
        assert fr.dump(f"oom: {ei.value}") is not None
        dumps = list((tmp_path / "crash").glob("crash-*"))
        assert len(dumps) == 1
        payload = json.loads(
            (dumps[0] / "memoryledger0-forensics.json").read_text())
        assert payload["top_pools"]
        assert any(r["pool"] == "kv_pages" for r in payload["top_pools"])
        growth = {(g["space"], g["pool"]): g["delta_bytes"]
                  for g in payload["recent_growth"]}
        assert growth.get(("host", "kv_pages"), 0) >= 1 << 19
        assert payload["largest_arrays"]
        assert payload["watermarks"]
        # the full ledger payload rides beside it, kind-tagged
        full = json.loads((dumps[0] / "memoryledger0.json").read_text())
        assert full["kind"] == "memory" and full["series"]
        # the injected fault is honest about itself
        assert eng.injected()[0]["kind"] == "alloc_fail"
        # count=1: the next tick is healthy again
        assert eng.step() is None

    def test_alloc_fail_never_touches_inner_engine(self):
        calls = []

        class Probe(_TickEngine):
            def step(self):
                calls.append(1)

        eng = FaultyEngine(Probe(),
                           FaultPlan([Fault("alloc_fail", count=2)]),
                           clock=lambda: 0.0)
        for _ in range(2):
            with pytest.raises(InjectedAllocationError):
                eng.step()
        assert calls == []                  # the "allocation" failed first
        eng.step()
        assert calls == [1]


# ---------------------------------------------------------------------------
# zero-cost-off parity (the PR 2 pin, extended to the memory plane)
# ---------------------------------------------------------------------------

class TestOffPathParity:
    def test_no_active_ledger_by_default(self):
        assert current_memory_ledger() is None

    def test_identical_lowering_with_and_without_ledger(self):
        """THE parity pin: an active memory ledger changes nothing inside
        the jit boundary — the compiled program text is byte-identical."""
        step_off, st, rest = _tiny_step(seed=3)
        off = step_off.lower(st, *rest).as_text()
        with MemoryLedger():
            step_on, st2, rest2 = _tiny_step(seed=3)
            on = step_on.lower(st2, *rest2).as_text()
        assert off == on

    def test_engine_metrics_memory_keys_are_conditional(self):
        """The ``memory_*`` metrics appear ONLY after attach_memory —
        the off path is one attribute check on a None field."""
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import ContinuousBatchingEngine
        paddle.seed(5)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=64,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32)
        assert eng._memory is None
        m = eng.metrics()
        assert "memory_device_bytes" not in m
        ml = MemoryLedger()
        eng.attach_memory(ml)
        ml.census()
        m2 = eng.metrics()
        assert m2["memory_device_bytes"] > 0     # params registered
        assert "memory_host_bytes" in m2
        assert "memory_device_bytes" in eng.prometheus_text()

    def test_kv_store_without_ledger_accounts_nothing(self):
        assert current_memory_ledger() is None
        st = TieredKVStore(dram_capacity_bytes=1 << 20)
        st.put(KVPage(b"q" * 32, (np.ones((8,), np.float32),), ["m"]))
        assert st.lookup(b"q" * 32, meta=["m"]) is not None
