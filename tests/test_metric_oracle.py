"""Metric numerics vs hand-computed confusion-matrix / rank statistics
(≙ reference test_metrics.py, test_auc_op.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall


def test_accuracy_topk():
    m = Accuracy(topk=(1, 2))
    preds = paddle.to_tensor(np.array([[0.1, 0.7, 0.2],
                                       [0.5, 0.3, 0.2],
                                       [0.2, 0.3, 0.5],
                                       [0.6, 0.3, 0.1]], "float32"))
    labels = paddle.to_tensor(np.array([1, 1, 2, 2], "int64"))
    m.update(m.compute(preds, labels))
    top1, top2 = m.accumulate()
    assert abs(top1 - 2 / 4) < 1e-6       # rows 0,2 correct at top-1
    assert abs(top2 - 3 / 4) < 1e-6       # row 1 recovered at top-2

def test_precision_recall_against_confusion_matrix():
    preds = np.array([1, 1, 0, 1, 0, 1, 0, 0], "float32")
    labels = np.array([1, 0, 0, 1, 1, 1, 0, 1], "int64")
    tp = int(((preds == 1) & (labels == 1)).sum())   # 3
    fp = int(((preds == 1) & (labels == 0)).sum())   # 1
    fn = int(((preds == 0) & (labels == 1)).sum())   # 2
    p = Precision(); p.update(paddle.to_tensor(preds),
                              paddle.to_tensor(labels))
    r = Recall(); r.update(paddle.to_tensor(preds), paddle.to_tensor(labels))
    assert abs(p.accumulate() - tp / (tp + fp)) < 1e-9
    assert abs(r.accumulate() - tp / (tp + fn)) < 1e-9


def test_precision_incremental_accumulation():
    p = Precision()
    for s in range(3):
        pr = np.array([1, 0, 1, 1], "float32")
        la = np.array([1, 1, 0, s % 2], "int64")
        p.update(paddle.to_tensor(pr), paddle.to_tensor(la))
    # per batch (preds 1 at idx 0,2,3): s=0 labels[0,2,3]=1,0,0 -> tp1 fp2;
    # s=1 labels=1,0,1 -> tp2 fp1; s=2 = s=0 -> tp1 fp2.  Totals tp4 fp5.
    assert abs(p.accumulate() - 4 / 9) < 1e-9


def test_auc_matches_exact_rank_auc():
    rng = np.random.RandomState(0)
    scores = rng.rand(4000).astype("float32")
    labels = (rng.rand(4000) < scores).astype("int64")  # informative scores
    m = Auc()
    m.update(paddle.to_tensor(np.stack([1 - scores, scores], 1)),
             paddle.to_tensor(labels))
    got = m.accumulate()
    # exact rank-based AUC (Mann-Whitney U)
    order = np.argsort(scores)
    ranks = np.empty(len(scores)); ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels == 1
    n1, n0 = pos.sum(), (~pos).sum()
    exact = (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)
    assert abs(got - exact) < 2e-3, (got, exact)


def test_auc_incremental_equals_single_shot():
    rng = np.random.RandomState(1)
    scores = rng.rand(1000).astype("float32")
    labels = (rng.rand(1000) < 0.4).astype("int64")
    a1 = Auc()
    a1.update(paddle.to_tensor(scores), paddle.to_tensor(labels))
    a2 = Auc()
    for i in range(0, 1000, 100):
        a2.update(paddle.to_tensor(scores[i:i + 100]),
                  paddle.to_tensor(labels[i:i + 100]))
    assert abs(a1.accumulate() - a2.accumulate()) < 1e-12
