"""Fused LN+residual+dropout and fused AdamW kernels (ops/fused.py) vs their
plain-XLA oracles.  ≙ reference unittests/test_fused_layernorm_residual_
dropout_bias.py (oracle = unfused composition) and multi_tensor_adam checks.
Pallas runs in interpret mode on CPU (FLAGS_fused_ln_interpret)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.flags import set_flags
from paddle_tpu.ops.fused import (_dense_ln_residual_dropout,
                                  fused_adamw_flat,
                                  fused_ln_residual_dropout)


@pytest.fixture
def kernel_on():
    set_flags({"FLAGS_use_fused_ln": True, "FLAGS_fused_ln_interpret": True})
    yield
    set_flags({"FLAGS_use_fused_ln": False, "FLAGS_fused_ln_interpret": False})


def _inputs(dtype, B=2, L=32, H=64, seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(B, L, H), dtype)
    res = jnp.asarray(rs.randn(B, L, H), dtype)
    w = jnp.asarray(rs.rand(H) + 0.5, jnp.float32)
    b = jnp.asarray(rs.randn(H) * 0.1, jnp.float32)
    bias = jnp.asarray(rs.randn(H) * 0.1, jnp.float32)
    return x, res, w, b, bias


class TestFusedLN:
    def test_fwd_matches_dense_fp32(self, kernel_on):
        x, res, w, b, bias = _inputs(jnp.float32)
        out, rout = fused_ln_residual_dropout(x, res, w, b, bias=bias)
        oute, route = _dense_ln_residual_dropout(x, res, w, b, bias, 0, 0.0,
                                                 1e-5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oute),
                                   rtol=2e-6, atol=2e-6)
        np.testing.assert_allclose(np.asarray(rout), np.asarray(route),
                                   rtol=2e-6, atol=2e-6)

    def test_fwd_matches_dense_bf16(self, kernel_on):
        x, res, w, b, bias = _inputs(jnp.bfloat16)
        out, rout = fused_ln_residual_dropout(x, res, w, b, bias=bias)
        oute, _ = _dense_ln_residual_dropout(x, res, w, b, bias, 0, 0.0, 1e-5)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(oute, np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_grads_match_dense(self, kernel_on):
        x, res, w, b, bias = _inputs(jnp.float32)

        def loss_pallas(x, res, w, b, bias):
            out, rout = fused_ln_residual_dropout(x, res, w, b, bias=bias)
            return jnp.sum(out * out) + jnp.sum(jnp.sin(rout))

        def loss_dense(x, res, w, b, bias):
            out, rout = _dense_ln_residual_dropout(x, res, w, b, bias, 0, 0.0,
                                                   1e-5)
            return jnp.sum(out * out) + jnp.sum(jnp.sin(rout))

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2, 3, 4))(x, res, w, b, bias)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2, 3, 4))(x, res, w, b, bias)
        for a, e, name in zip(gp, gd, ("dx", "dres", "dw", "db", "dbias")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       rtol=2e-4, atol=2e-5, err_msg=name)

    def test_dropout_parity_and_grad_mask(self, kernel_on):
        """Same seed ⇒ Pallas and dense paths drop the same positions, and
        dropped positions get exactly zero dx."""
        x, res, w, b, _ = _inputs(jnp.float32)
        p, seed = 0.4, jnp.uint32(7)
        out, rout = fused_ln_residual_dropout(x, res, w, b, dropout_p=p,
                                              dropout_seed=seed)
        oute, route = _dense_ln_residual_dropout(x, res, w, b, None, seed, p,
                                                 1e-5)
        np.testing.assert_allclose(np.asarray(rout), np.asarray(route),
                                   rtol=2e-6, atol=2e-6)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oute),
                                   rtol=2e-5, atol=2e-5)
        # dropped positions: residual_out == residual exactly
        dropped = np.asarray(rout) == np.asarray(res)
        assert 0.2 < dropped.mean() < 0.6  # ~p of positions

        dx = jax.grad(lambda xx: jnp.sum(
            fused_ln_residual_dropout(xx, res, w, b, dropout_p=p,
                                      dropout_seed=seed)[0] ** 2))(x)
        assert np.all(np.asarray(dx)[dropped] == 0.0)
        assert np.any(np.asarray(dx)[~dropped] != 0.0)

    def test_dropout_requires_seed(self):
        x, res, w, b, _ = _inputs(jnp.float32)
        with pytest.raises(ValueError, match="dropout_seed"):
            fused_ln_residual_dropout(x, res, w, b, dropout_p=0.1)

    def test_fallback_without_flag(self):
        """Flag off ⇒ dense path (still correct, no pallas tracing)."""
        x, res, w, b, bias = _inputs(jnp.float32)
        out, _ = fused_ln_residual_dropout(x, res, w, b, bias=bias)
        oute, _ = _dense_ln_residual_dropout(x, res, w, b, bias, 0, 0.0, 1e-5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oute),
                                   rtol=1e-6, atol=1e-6)


class TestFusedAdamW:
    def test_matches_reference_formula(self):
        rs = np.random.RandomState(1)
        n = 10000  # not a multiple of the block: exercises padding
        p = jnp.asarray(rs.randn(n), jnp.float32)
        g = jnp.asarray(rs.randn(n), jnp.float32)
        m = jnp.asarray(rs.randn(n) * 0.1, jnp.float32)
        v = jnp.asarray(rs.rand(n) * 0.01, jnp.float32)
        lr, b1, b2, eps, wd, step = 1e-3, 0.9, 0.999, 1e-8, 0.01, 3

        po, mo, vo = fused_adamw_flat(p, g, m, v, step, lr, b1, b2, eps, wd,
                                      block=4096)

        me = b1 * np.asarray(m) + (1 - b1) * np.asarray(g)
        ve = b2 * np.asarray(v) + (1 - b2) * np.asarray(g) ** 2
        mhat = me / (1 - b1 ** step)
        vhat = ve / (1 - b2 ** step)
        pe = np.asarray(p) - lr * (mhat / (np.sqrt(vhat) + eps)
                                   + wd * np.asarray(p))
        # fp32 rounding-order differences only (kernel keeps fp32 throughout)
        np.testing.assert_allclose(np.asarray(po), pe, rtol=3e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(mo), me, rtol=3e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(vo), ve, rtol=3e-5, atol=1e-6)

    def test_jit_and_odd_sizes(self):
        for n in (1, 127, 4096):
            p = jnp.ones((n,), jnp.float32)
            g = jnp.full((n,), 0.5, jnp.float32)
            m = jnp.zeros((n,), jnp.float32)
            v = jnp.zeros((n,), jnp.float32)
            po, mo, vo = jax.jit(
                lambda p, g, m, v: fused_adamw_flat(p, g, m, v, 1, 0.1))(
                    p, g, m, v)
            assert po.shape == (n,)
            assert np.all(np.asarray(po) < 1.0)


class TestModelWiring:
    def test_bert_block_fused_matches_plain(self, kernel_on):
        """BERT encode with FLAGS_use_fused_ln on (interpret Pallas) matches
        the plain _ln path — the wiring is numerics-preserving."""
        from paddle_tpu.models.bert import BertConfig, BertModel

        paddle.seed(11)
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=32, compute_dtype="float32",
                         use_flash_attention=False)
        model = BertModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))

        fused = model.encode(params, ids)
        set_flags({"FLAGS_use_fused_ln": False})
        plain = model.encode(params, ids)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(plain),
                                   rtol=2e-5, atol=2e-5)
