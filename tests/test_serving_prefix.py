"""Prefix caching / shared-prompt KV reuse in the paged serving engine
(VERDICT r4 #7): admission detects a cached prompt-block chain and maps the
shared blocks directly into the slot's table, computing only the suffix.
Sharing is lossless — every output must equal the non-cached engine / solo
generate — because prompt blocks are immutable once written (buckets are
block-aligned, decode growth starts in a fresh block) and the chain key
includes the pad length (left-padding shifts logical positions, so equal
token blocks at different pads have different k/v).

Beyond-reference capability (the reference has no serving scheduler at
all); oracle = the framework's own single-request generation."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import PagedContinuousBatchingEngine


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo(model, params, prompt, n, **kw):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         greedy=True, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _make(model, params, **kw):
    base = dict(max_slots=2, max_len=64, block_size=4, prompt_buckets=[16],
                enable_prefix_cache=True)
    base.update(kw)
    return PagedContinuousBatchingEngine(model, params, **base)


LONG = list(range(3, 17))                     # 14 tokens -> bucket 16, pad 2


class TestPrefixCache:
    def test_identical_prompt_hits_and_stays_lossless(self,
                                                      model_and_params):
        """Same prompt twice, sequentially through one engine: the second
        admission reuses the first's prompt blocks (counted), output
        identical to solo for both."""
        model, params = model_and_params
        eng = _make(model, params)
        r0 = eng.add_request(LONG, 6)
        got0 = eng.run_to_completion(max_ticks=100)
        r1 = eng.add_request(LONG, 6)
        got1 = eng.run_to_completion(max_ticks=100)
        want = _solo(model, params, LONG, 6)
        assert got0[r0] == want and got1[r1] == want
        assert eng.prefix_hits == 1
        # bucket 16 / bs 4 -> 4 prompt blocks, cap F <= 3
        assert eng.prefix_blocks_reused == 3

    def test_common_prefix_same_length_shares(self, model_and_params):
        """Two same-length prompts sharing their first 8 tokens (= 2 full
        blocks after padding alignment): the second reuses exactly the
        aligned shared blocks and both stay exact."""
        model, params = model_and_params
        a = [7] * 2 + list(range(20, 32))      # len 14, pad 2
        b = a[:8] + list(range(70, 76))        # same first 8, same length
        eng = _make(model, params)
        r0 = eng.add_request(a, 5)
        eng.run_to_completion(max_ticks=100)
        r1 = eng.add_request(b, 5)
        got = eng.run_to_completion(max_ticks=100)
        assert got[r1] == _solo(model, params, b, 5)
        assert eng.prefix_hits == 1
        # padded: [0,0,7,7,...]: blocks 0-1 match (pad+first 6 real), block
        # 2 diverges at token index 8 -> 2 blocks reused
        assert eng.prefix_blocks_reused == 2

    def test_different_pad_does_not_collide(self, model_and_params):
        """A prompt that equals another's padded TOKENS but at a different
        pad must not reuse its blocks (positions differ) — and output
        stays exact."""
        model, params = model_and_params
        a = list(range(5, 19))                 # len 14, pad 2
        b = list(range(5, 18))                 # len 13, pad 3: different pad
        eng = _make(model, params)
        ra = eng.add_request(a, 5)
        eng.run_to_completion(max_ticks=100)
        rb = eng.add_request(b, 5)
        got = eng.run_to_completion(max_ticks=100)
        assert got[rb] == _solo(model, params, b, 5)
        assert eng.prefix_hits == 0            # no false sharing

    def test_concurrent_sharing_and_refcounts(self, model_and_params):
        """Both slots decode with SHARED prompt blocks live: retirement of
        one must not free blocks the other still reads; cached blocks
        linger (evictable) after both finish."""
        model, params = model_and_params
        eng = _make(model, params)
        r0 = eng.add_request(LONG, 4)
        eng.step()                             # r0 admitted + decoding
        r1 = eng.add_request(LONG, 12)         # shares 3 blocks with r0
        got = eng.run_to_completion(max_ticks=200)
        want4 = _solo(model, params, LONG, 4)
        want12 = _solo(model, params, LONG, 12)
        assert got[r0] == want4 and got[r1] == want12
        assert eng.prefix_hits == 1
        m = eng.metrics()
        assert m["blocks_cached"] > 0          # prompt blocks linger
        # all still-cached blocks are unreferenced (requests done)
        assert all(eng._refs[b] == 0 for b in eng._prefix_cache.values())

    def test_eviction_under_pressure(self, model_and_params):
        """A tight pool evicts lingering cached blocks to serve new
        prompts; everything completes and stays exact."""
        model, params = model_and_params
        eng = _make(model, params, num_blocks=8)
        prompts = [list(range(i, i + 14)) for i in (3, 20, 40, 60)]
        outs = {}
        for p in prompts:                      # sequential distinct prompts
            rid = eng.add_request(p, 4)
            outs[rid] = p
        got = eng.run_to_completion(max_ticks=400)
        for rid, p in outs.items():
            assert got[rid] == _solo(model, params, p, 4), p
        # pool never exceeded, despite 4 x 4 prompt blocks being cached
        assert eng.blocks_high_water <= 8

    def test_preempted_request_rehits_its_own_prefix(self,
                                                     model_and_params):
        """Preemption keeps the victim's prompt blocks cached, so its
        rerun prefills only the suffix — and output stays exact."""
        model, params = model_and_params
        eng = _make(model, params, num_blocks=12, max_len=48)
        a, b = LONG, list(range(50, 64))
        r0 = eng.add_request(a, 24)            # grows to 16+24 pos: 10 blk
        r1 = eng.add_request(b, 24)
        got = eng.run_to_completion(max_ticks=400)
        assert got[r0] == _solo(model, params, a, 24)
        assert got[r1] == _solo(model, params, b, 24)
        assert eng.preemptions >= 1
        assert eng.prefix_hits >= 1            # the rerun hit its prefix

    def test_int8_prefix_sharing(self):
        """Shared int8 block pairs (values + scales) stay lossless."""
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype="int8")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        eng = _make(model, params)
        r0 = eng.add_request(LONG, 6)
        eng.run_to_completion(max_ticks=100)
        r1 = eng.add_request(LONG, 6)
        got = eng.run_to_completion(max_ticks=100)
        assert eng.prefix_hits == 1
        assert got[r1] == _solo(model, params, LONG, 6)

    def test_cached_prefill_beats_chunked_admission_rounds(
            self, model_and_params):
        """The TTFT mechanism: a chunked engine needs P/chunk scheduler
        rounds to admit a long prompt; a prefix-cache hit collapses that
        to ONE round (the suffix fits one chunk) — measured in scheduler
        rounds, the CPU-deterministic proxy for TTFT."""
        model, params = model_and_params
        eng = _make(model, params, prefill_chunk=4)

        def rounds_to_first_token(prompt):
            rid = eng.add_request(prompt, 3)
            n = 0
            while True:
                eng.step()
                n += 1
                done = eng.pop_finished()
                if any(self_r == rid for self_r in done) or \
                        any(r is not None and r.id == rid
                            for r in eng._slot_req):
                    return n
                assert n < 50

        first = rounds_to_first_token(LONG)    # cold: 4 fill rounds
        second = rounds_to_first_token(LONG)   # hit: one cached-prefill
        assert first >= 4                      # bucket 16 / chunk 4
        assert second == 1
        assert eng.prefix_hits == 1

    def test_program_count_bounded_with_cache(self, model_and_params):
        """Cached-prefill programs are keyed by (bucket, F): replaying
        mixed hit depths adds at most P/bs programs per bucket, and
        repeats add none."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)
        eng = _make(model, params)
        a = [7] * 2 + list(range(20, 32))
        b = a[:8] + list(range(70, 76))
        for p in (a, b, a, b, LONG, LONG):
            eng.add_request(p, 3)
            eng.run_to_completion(max_ticks=100)
        n = len(model._serving_programs)
        eng2 = _make(model, params)
        for p in (a, b, a, LONG):
            eng2.add_request(p, 3)
            eng2.run_to_completion(max_ticks=100)
        assert len(model._serving_programs) == n