"""nn.Layer system + layer forward/backward tests (reference: unittests
test_layers.py family)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_layer_registry():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 3)
            self.act = nn.ReLU()
            self.register_buffer("cnt", paddle.zeros([1]))

        def forward(self, x):
            return self.act(self.fc(x))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert names == ["fc.weight", "fc.bias"]
    assert "cnt" in net.state_dict()
    assert len(net.sublayers()) == 2
    net.eval()
    assert not net.fc.training
    net.train()
    assert net.fc.training


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(m1.state_dict())
    x = paddle.randn([3, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_linear_oracle():
    lin = nn.Linear(4, 3)
    x = np.random.randn(5, 4).astype("float32")
    out = lin(paddle.to_tensor(x))
    expect = x @ lin.weight.numpy() + lin.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5, atol=1e-5)


def test_conv2d_oracle():
    import torch
    import torch.nn.functional as tF
    x = np.random.randn(2, 3, 8, 8).astype("float32")
    w = np.random.randn(6, 3, 3, 3).astype("float32")
    b = np.random.randn(6).astype("float32")
    out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(b),
                   stride=2, padding=1)
    ref = tF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b), stride=2,
                    padding=1).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_conv_transpose_oracle():
    import torch
    import torch.nn.functional as tF
    x = np.random.randn(2, 4, 5, 5).astype("float32")
    w = np.random.randn(4, 3, 3, 3).astype("float32")  # (in, out, kh, kw)
    out = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w), stride=2,
                             padding=1)
    ref = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2,
                              padding=1).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = np.random.randn(4, 3, 5, 5).astype("float32") * 2 + 1
    out = bn(paddle.to_tensor(x))
    # normalized output ~ zero-mean unit-var per channel
    o = out.numpy()
    assert abs(o.mean()) < 1e-5
    assert abs(o.std() - 1) < 1e-2
    # running stats moved toward batch stats
    assert abs(bn._mean.numpy().mean() - 0.1 * x.mean(axis=(0, 2, 3)).mean()) < 0.05
    bn.eval()
    out2 = bn(paddle.to_tensor(x))
    assert not np.allclose(out2.numpy(), o)


def test_layernorm_oracle():
    import torch
    ln = nn.LayerNorm(8)
    x = np.random.randn(2, 5, 8).astype("float32")
    out = ln(paddle.to_tensor(x))
    ref = torch.nn.functional.layer_norm(torch.tensor(x), [8]).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_dropout_modes():
    x = paddle.ones([1000])
    d = nn.Dropout(0.5)
    out = d(x)
    kept = (out.numpy() != 0)
    assert 0.3 < kept.mean() < 0.7
    np.testing.assert_allclose(out.numpy()[kept], 2.0)  # upscale_in_train
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    out = emb(paddle.to_tensor(np.array([[0, 1], [2, 0]])))
    assert np.allclose(out.numpy()[0, 0], 0)
    assert np.allclose(out.numpy()[1, 1], 0)
    assert not np.allclose(out.numpy()[0, 1], 0)


def test_mha_against_manual():
    mha = nn.MultiHeadAttention(8, 2)
    x = paddle.randn([2, 4, 8])
    out = mha(x)
    assert out.shape == [2, 4, 8]
    q = x.numpy() @ mha.q_proj.weight.numpy() + mha.q_proj.bias.numpy()
    k = x.numpy() @ mha.k_proj.weight.numpy() + mha.k_proj.bias.numpy()
    v = x.numpy() @ mha.v_proj.weight.numpy() + mha.v_proj.bias.numpy()
    B, L, E, H, D = 2, 4, 8, 2, 4
    q = q.reshape(B, L, H, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, L, H, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, L, H, D).transpose(0, 2, 1, 3)
    s = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = (p @ v).transpose(0, 2, 1, 3).reshape(B, L, E)
    ref = o @ mha.out_proj.weight.numpy() + mha.out_proj.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-3)


def test_lstm_shapes_and_grad():
    lstm = nn.LSTM(5, 7, num_layers=2, direction="bidirect")
    x = paddle.randn([3, 6, 5])
    out, (h, c) = lstm(x)
    assert out.shape == [3, 6, 14]
    assert h.shape == [4, 3, 7]
    out.mean().backward()
    for p in lstm.parameters():
        assert p.grad is not None


def test_gru_matches_torch():
    import torch
    gru = nn.GRU(4, 6)
    tg = torch.nn.GRU(4, 6, batch_first=True)
    sd = gru.state_dict()
    with torch.no_grad():
        tg.weight_ih_l0.copy_(torch.tensor(sd["weight_ih_l0"].numpy()))
        tg.weight_hh_l0.copy_(torch.tensor(sd["weight_hh_l0"].numpy()))
        tg.bias_ih_l0.copy_(torch.tensor(sd["bias_ih_l0"].numpy()))
        tg.bias_hh_l0.copy_(torch.tensor(sd["bias_hh_l0"].numpy()))
    x = np.random.randn(2, 5, 4).astype("float32")
    out, h = gru(paddle.to_tensor(x))
    tout, th = tg(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), rtol=1e-4, atol=1e-4)


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    out = enc(x)
    assert out.shape == [2, 5, 16]
    out.mean().backward()
    grads = [p.grad is not None for p in enc.parameters()]
    assert all(grads)


def test_sequential_containers():
    seq = nn.Sequential(("a", nn.Linear(2, 3)), ("b", nn.ReLU()))
    assert isinstance(seq["a"], nn.Linear)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    ld = nn.LayerDict({"x": nn.Linear(2, 2)})
    assert "x" in ld


def test_pool_layers():
    x = paddle.randn([2, 3, 8, 8])
    assert nn.MaxPool2D(2)(x).shape == [2, 3, 4, 4]
    assert nn.AvgPool2D(2, stride=1)(x).shape == [2, 3, 7, 7]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 3, 1, 1]
    import torch
    a = np.random.randn(1, 2, 6, 6).astype("float32")
    out = F.avg_pool2d(paddle.to_tensor(a), 2)
    ref = torch.nn.functional.avg_pool2d(torch.tensor(a), 2).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_loss_oracles():
    import torch
    logits = np.random.randn(6, 5).astype("float32")
    labels = np.array([0, 1, 2, 3, 4, 0])
    out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
    ref = torch.nn.functional.cross_entropy(torch.tensor(logits),
                                            torch.tensor(labels)).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    p = np.random.rand(4, 3).astype("float32")
    y = np.random.rand(4, 3).astype("float32")
    np.testing.assert_allclose(
        F.mse_loss(paddle.to_tensor(p), paddle.to_tensor(y)).numpy(),
        ((p - y) ** 2).mean(), rtol=1e-5)
    z = np.random.randn(4, 3).astype("float32")
    yy = (np.random.rand(4, 3) > 0.5).astype("float32")
    ref_bce = torch.nn.functional.binary_cross_entropy_with_logits(
        torch.tensor(z), torch.tensor(yy)).numpy()
    np.testing.assert_allclose(
        F.binary_cross_entropy_with_logits(paddle.to_tensor(z),
                                           paddle.to_tensor(yy)).numpy(),
        ref_bce, rtol=1e-4, atol=1e-6)  # fp32 accumulation-order slack vs torch


def test_initializers():
    from paddle_tpu.nn import initializer as I
    w = I.XavierUniform()([100, 100], "float32")
    assert abs(np.asarray(w).mean()) < 0.01
    limit = np.sqrt(6 / 200)
    assert np.asarray(w).max() <= limit + 1e-6
    o = I.Orthogonal()([16, 16], "float32")
    np.testing.assert_allclose(np.asarray(o) @ np.asarray(o).T, np.eye(16), atol=1e-4)
    c = I.Constant(3.0)([4], "float32")
    np.testing.assert_allclose(np.asarray(c), 3.0)


def test_lstm_matches_torch():
    """LSTM numeric parity vs torch (same gate layout/state contract as the
    reference's cudnn LSTM)."""
    import torch
    lstm = nn.LSTM(4, 6)
    tl = torch.nn.LSTM(4, 6, batch_first=True)
    sd = lstm.state_dict()
    with torch.no_grad():
        tl.weight_ih_l0.copy_(torch.tensor(sd["weight_ih_l0"].numpy()))
        tl.weight_hh_l0.copy_(torch.tensor(sd["weight_hh_l0"].numpy()))
        tl.bias_ih_l0.copy_(torch.tensor(sd["bias_ih_l0"].numpy()))
        tl.bias_hh_l0.copy_(torch.tensor(sd["bias_hh_l0"].numpy()))
    x = np.random.randn(2, 5, 4).astype("float32")
    out, (h, c) = lstm(paddle.to_tensor(x))
    tout, (th, tc) = tl(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                               rtol=1e-4, atol=1e-4)


def test_initializer_statistics_and_properties():
    """Kaiming/TruncatedNormal/Dirac/calculate_gain oracles (≙ reference
    test_initializer.py)."""
    import torch
    from paddle_tpu.nn import initializer as I

    paddle.seed(0)
    # KaimingNormal for fan_in f: std = gain/sqrt(fan_in), relu gain sqrt(2)
    w = np.asarray(I.KaimingNormal()([400, 100], "float32"))
    assert abs(w.std() - np.sqrt(2.0 / 400)) < 0.005
    # TruncatedNormal: |x| <= 2*std and std shrinks vs plain normal
    t = np.asarray(I.TruncatedNormal(mean=0.0, std=1.0)([20000], "float32"))
    assert np.abs(t).max() <= 2.0 + 1e-5
    assert 0.7 < t.std() < 0.95
    # Dirac: conv with dirac weights is identity on matching channels
    d = np.asarray(I.Dirac()([4, 4, 3, 3], "float32"))
    x = np.random.RandomState(0).randn(1, 4, 8, 8).astype("float32")
    import paddle_tpu.nn.functional as F
    out = np.asarray(F.conv2d(paddle.to_tensor(x),
                              paddle.to_tensor(d), padding=1)._data)
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-6)
    # calculate_gain parity vs torch
    for nl, arg in [("relu", None), ("tanh", None), ("leaky_relu", 0.1),
                    ("sigmoid", None), ("linear", None)]:
        got = I.calculate_gain(nl, arg) if arg is not None else \
            I.calculate_gain(nl)
        want = torch.nn.init.calculate_gain(nl, arg) if arg is not None \
            else torch.nn.init.calculate_gain(nl)
        assert abs(got - want) < 1e-6, nl
