"""New vision model families (reference vision/models parity: densenet,
googlenet, inceptionv3, shufflenetv2, squeezenet)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models

def _x(size, B=2):
    return paddle.to_tensor(
        np.random.RandomState(0).standard_normal((B, 3, size, size))
        .astype(np.float32))


@pytest.mark.parametrize("ctor,size", [
    # the heavyweight families are slow-tier (60-78s eager forwards on the
    # CPU host); squeezenet stays in the quick loop as the representative
    pytest.param(lambda: models.densenet121(num_classes=10), 64,
                 marks=pytest.mark.slow),
    (lambda: models.squeezenet1_1(num_classes=10), 64),
    pytest.param(lambda: models.shufflenet_v2_x0_25(num_classes=10), 64,
                 marks=pytest.mark.slow),
    pytest.param(lambda: models.googlenet(num_classes=10), 64,
                 marks=pytest.mark.slow),
    pytest.param(lambda: models.inception_v3(num_classes=10), 80,
                 marks=pytest.mark.slow),
])
def test_forward_shapes(ctor, size):
    paddle.seed(0)
    m = ctor()
    m.eval()
    out = m(_x(size, B=1))
    assert tuple(out.shape) == (1, 10), out.shape


@pytest.mark.slow
def test_googlenet_train_mode_aux_heads():
    paddle.seed(0)
    m = models.googlenet(num_classes=10)
    m.train()
    main, a1, a2 = m(_x(64))
    assert tuple(main.shape) == (2, 10)
    assert tuple(a1.shape) == (2, 10) and tuple(a2.shape) == (2, 10)


@pytest.mark.slow
def test_shufflenet_trains():
    import paddle_tpu.nn as nn
    paddle.seed(0)
    m = models.shufflenet_v2_x0_25(num_classes=4)
    opt = paddle.optimizer.SGD(0.02, parameters=m.parameters())
    x = _x(32, B=4)
    y = paddle.to_tensor(np.array([0, 1, 2, 3]))
    losses = []
    for _ in range(10):
        loss = nn.functional.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert min(losses[3:]) < losses[0], losses


def test_pretrained_raises():
    with pytest.raises(ValueError, match="pretrained"):
        models.densenet121(pretrained=True)


def test_squeezenet_feature_extractor_modes():
    paddle.seed(0)
    feats = models.SqueezeNet("1.1", num_classes=0, with_pool=False)
    out = feats(_x(64))
    assert len(out.shape) == 4 and out.shape[1] == 512  # raw feature map
    pooled = models.SqueezeNet("1.1", num_classes=0, with_pool=True)
    out2 = pooled(_x(64))
    assert tuple(out2.shape)[:2] == (2, 512)


def test_shufflenet_int_scale_and_bad_scale():
    m = models.ShuffleNetV2(scale=1, num_classes=0)  # int normalizes to "1.0"
    assert m is not None
    with pytest.raises(ValueError, match="unsupported scale"):
        models.ShuffleNetV2(scale=0.7)


def test_resnet_nhwc_matches_nchw():
    """NHWC (TPU-preferred layout, bench path) is numerically identical to
    NCHW — same seed, transposed input (VERDICT r2 #3)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    m_nchw = resnet18(num_classes=10)
    paddle.seed(0)
    m_nhwc = resnet18(num_classes=10, data_format="NHWC")
    x = np.random.RandomState(0).randn(2, 3, 32, 32).astype("float32")
    m_nchw.eval(); m_nhwc.eval()
    o1 = np.asarray(m_nchw(paddle.to_tensor(x))._data)
    o2 = np.asarray(m_nhwc(paddle.to_tensor(
        np.transpose(x, (0, 2, 3, 1))))._data)
    np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_resnet_nhwc_train_step_parity():
    """Train-mode NHWC vs NCHW: full backward compared in float64, where
    layout equivalence is exact (worst observed diff ~2e-12).

    fp32 comparison is useless here: layouts change only the reduction
    order, but 4-sample BatchNorm amplifies that noise up to ~5% on deep
    conv grads, and a 1e-6 single-weight perturbation flips a 3-step loss
    trajectory entirely (both verified) — any fp32 tolerance either masks
    real bugs or fails on noise."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet18

    jax.config.update("jax_enable_x64", True)
    try:
        out = {}
        for df in ("NCHW", "NHWC"):
            paddle.seed(0)
            m = resnet18(num_classes=10, data_format=df)
            for _, p in m.named_parameters():
                p._data = p._data.astype(jnp.float64)
            for _, b in m.named_buffers():
                if jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._data = b._data.astype(jnp.float64)
            r = np.random.RandomState(0)
            x = r.randn(4, 3, 32, 32).astype("float64")
            y = paddle.to_tensor(np.array([0, 1, 2, 3], "int64"))
            xin = x if df == "NCHW" else np.transpose(x, (0, 2, 3, 1))
            m.train()
            loss = nn.functional.cross_entropy(m(paddle.to_tensor(xin)), y)
            loss.backward()
            out[df] = {n: np.asarray(p.grad._data)
                       for n, p in m.named_parameters()
                       if p.grad is not None}
        assert set(out["NCHW"]) == set(out["NHWC"])
        for n in out["NCHW"]:
            np.testing.assert_allclose(out["NCHW"][n], out["NHWC"][n],
                                       atol=1e-9, err_msg=n)
    finally:
        jax.config.update("jax_enable_x64", False)
