"""Model-family integration tests (reference: tests/book/ pattern — tiny
models end-to-end, assert loss decrease; SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.device import local_devices
from paddle_tpu.distributed import fleet
from paddle_tpu.optimizer import AdamW

needs8 = pytest.mark.skipif(len(local_devices()) < 8, reason="needs 8 devices")


def _fleet_hcg(**degrees):
    strategy = fleet.DistributedStrategy()
    hc = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1}
    hc.update(degrees)
    strategy.hybrid_configs = hc
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


class TestBert:
    def _cfg(self):
        from paddle_tpu.models.bert import BertConfig
        return BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                          num_attention_heads=4, max_position_embeddings=32,
                          compute_dtype="float32")

    def test_forward_shapes(self):
        from paddle_tpu.models.bert import BertModel
        paddle.seed(0)
        model = BertModel(self._cfg())
        x = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
        h, pooled = model(x)
        assert tuple(h.shape) == (2, 16, 32)
        assert tuple(pooled.shape) == (2, 32)

    def test_mlm_train_loss_decreases(self):
        from paddle_tpu.models.bert import BertModel, make_bert_train_step
        paddle.seed(0)
        model = BertModel(self._cfg())
        hcg = _fleet_hcg()
        step, state = make_bert_train_step(model, AdamW(1e-3), hcg, remat=False)
        r = np.random.RandomState(0)
        ids = jnp.asarray(r.randint(0, 128, (4, 16)))
        mlm = jnp.asarray(np.where(r.rand(4, 16) < 0.15,
                                   r.randint(0, 128, (4, 16)), -100))
        nsp = jnp.asarray(r.randint(0, 2, (4,)))
        first = None
        for _ in range(5):
            state, loss = step(state, np.float32(1e-3), ids, mlm, nsp)
            if first is None:
                first = float(loss)
        assert float(loss) < first

    @needs8
    def test_bert_dp_mp_parity(self):
        """2-dp × 2-mp loss matches serial (test_dist_base.py:1457 oracle)."""
        from paddle_tpu.models.bert import BertModel, make_bert_train_step
        losses = {}
        for key, degrees in (("serial", {}), ("dpmp", {"dp_degree": 2,
                                                       "mp_degree": 2})):
            paddle.seed(0)
            model = BertModel(self._cfg())
            hcg = _fleet_hcg(**degrees)
            step, state = make_bert_train_step(model, AdamW(1e-3), hcg,
                                               remat=False)
            r = np.random.RandomState(0)
            ids = jnp.asarray(r.randint(0, 128, (4, 16)))
            mlm = jnp.asarray(np.where(r.rand(4, 16) < 0.15,
                                       r.randint(0, 128, (4, 16)), -100))
            nsp = jnp.asarray(r.randint(0, 2, (4,)))
            for _ in range(3):
                state, loss = step(state, np.float32(1e-3), ids, mlm, nsp)
            losses[key] = float(loss)
        assert abs(losses["serial"] - losses["dpmp"]) < 1e-4, losses


class TestErnieMoe:
    def _cfg(self, **kw):
        from paddle_tpu.models.ernie_moe import ErnieMoeConfig
        d = dict(vocab_size=128, hidden_size=32, num_layers=2,
                 num_attention_heads=4, num_experts=4, top_k=2,
                 expert_hidden_size=64, max_position_embeddings=32,
                 compute_dtype="float32")
        d.update(kw)
        return ErnieMoeConfig(**d)

    def test_forward_and_loss(self):
        from paddle_tpu.models.ernie_moe import ErnieMoeModel
        paddle.seed(0)
        model = ErnieMoeModel(self._cfg())
        x = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
        logits = model(x)
        assert tuple(logits.shape) == (2, 16, 128)
        loss = model(x, labels=x)
        assert np.isfinite(float(loss))

    def test_train_loss_decreases(self):
        from paddle_tpu.models.ernie_moe import (ErnieMoeModel,
                                                 make_ernie_moe_train_step)
        paddle.seed(0)
        model = ErnieMoeModel(self._cfg())
        hcg = _fleet_hcg()
        step, state = make_ernie_moe_train_step(model, AdamW(1e-3), hcg,
                                                remat=False)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randint(0, 128, (4, 16)))
        first = None
        for _ in range(5):
            state, loss = step(state, np.float32(1e-3), x, x)
            if first is None:
                first = float(loss)
        assert float(loss) < first

    @needs8
    def test_expert_parallel_parity(self):
        """EP over dp axis (experts sharded over 'data') matches serial."""
        from paddle_tpu.models.ernie_moe import (ErnieMoeModel,
                                                 make_ernie_moe_train_step)
        losses = {}
        for key, degrees in (("serial", {}), ("ep", {"dp_degree": 4})):
            paddle.seed(0)
            model = ErnieMoeModel(self._cfg())
            hcg = _fleet_hcg(**degrees)
            step, state = make_ernie_moe_train_step(model, AdamW(1e-3), hcg,
                                                    remat=False)
            r = np.random.RandomState(0)
            x = jnp.asarray(r.randint(0, 128, (4, 16)))
            for _ in range(3):
                state, loss = step(state, np.float32(1e-3), x, x)
            losses[key] = float(loss)
        assert abs(losses["serial"] - losses["ep"]) < 1e-4, losses


class TestRematModes:
    def test_gpt_remat_modes_loss_parity(self):
        """remat=False / True / 'dots' (selective policy saving MXU outputs)
        must produce identical train losses — the policy changes WHAT is
        recomputed in the backward, never the math.  'dots' is the
        recommended large-batch mode and was previously untested."""
        from paddle_tpu.models.gpt import (GPTConfig, GPTModel,
                                           make_gpt_train_step)
        from paddle_tpu.optimizer import AdamW

        r = np.random.RandomState(3)
        ids = jnp.asarray(r.randint(0, 128, (2, 16)))
        labels = jnp.asarray(r.randint(0, 128, (2, 16)))

        def losses(remat):
            paddle.seed(5)
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_attention_heads=4,
                            max_position_embeddings=32,
                            compute_dtype="float32")
            model = GPTModel(cfg)
            hcg = _fleet_hcg()
            step, state = make_gpt_train_step(model, AdamW(1e-3), hcg,
                                              remat=remat)
            out = []
            for i in range(3):
                state, loss = step(state, jax.random.key(7),
                                   np.float32(1e-3), ids, labels)
                out.append(float(np.asarray(loss)))
            return out

        base = losses(False)
        np.testing.assert_allclose(losses(True), base, rtol=1e-6)
        np.testing.assert_allclose(losses("dots"), base, rtol=1e-6)
