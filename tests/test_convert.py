"""Torch/HF GPT-2 ↔ paddle_tpu GPT parity: the converted weights must give
the same logits, losses, and greedy generations as the torch implementation
— an external oracle over the ENTIRE transformer stack (embed, LN placement,
attention, gelu variant, head tying).  ≙ reference-style cross-framework
checkpoint compatibility (paddlenlp convert utilities)."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from paddle_tpu.models.convert import (gpt2_config_from_torch,
                                       gpt2_params_from_torch)
from paddle_tpu.models.gpt import GPTModel


@pytest.fixture(scope="module")
def pair():
    hf_cfg = transformers.GPT2Config(
        vocab_size=211, n_positions=64, n_embd=48, n_layer=3, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = gpt2_config_from_torch(hf_cfg, compute_dtype="float32")
    model = GPTModel(cfg)
    params = {k: jnp.asarray(v)
              for k, v in gpt2_params_from_torch(hf).items()}
    return hf, model, params


class TestGPT2Parity:
    def test_logits_match(self, pair):
        hf, model, params = pair
        ids = np.random.RandomState(0).randint(0, 211, (2, 17))
        with torch.no_grad():
            want = hf(torch.tensor(ids)).logits.numpy()
        h = model.scan_blocks(params, model.embed_fn(params, jnp.asarray(ids)),
                              remat=False)
        got = np.asarray(model.head_fn(params, h))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_loss_matches(self, pair):
        hf, model, params = pair
        rs = np.random.RandomState(1)
        ids = rs.randint(0, 211, (2, 12))
        labels = rs.randint(0, 211, (2, 12))
        with torch.no_grad():
            logits = hf(torch.tensor(ids)).logits
            want = torch.nn.functional.cross_entropy(
                logits.reshape(-1, 211), torch.tensor(labels).reshape(-1))
        h = model.scan_blocks(params, model.embed_fn(params, jnp.asarray(ids)),
                              remat=False)
        got = model.head_loss_fn(params, h, jnp.asarray(labels))
        np.testing.assert_allclose(float(got), float(want), rtol=1e-4)

    def test_greedy_generation_matches(self, pair):
        hf, model, params = pair
        prompt = np.random.RandomState(2).randint(0, 211, (1, 6))
        with torch.no_grad():
            want = hf.generate(
                torch.tensor(prompt), max_new_tokens=7, do_sample=False,
                pad_token_id=0).numpy()[:, 6:]
        got = np.asarray(model.generate(params, prompt, max_new_tokens=7))
        np.testing.assert_array_equal(got, want)

    def test_kv_cache_path_matches_torch(self, pair):
        """decode_step (our cache) vs torch full forward at the same
        position — cross-framework check of the incremental path."""
        hf, model, params = pair
        ids = np.random.RandomState(3).randint(0, 211, (2, 9))
        _, caches = model.prefill(params, jnp.asarray(ids[:, :8]), 16)
        dt = jnp.dtype(model.config.compute_dtype)
        tok = jnp.asarray(ids[:, 8])
        h = (jnp.take(params["wte"], tok[:, None], axis=0)
             + params["wpe"][8][None, None, :]).astype(dt)
        h, _ = model.decode_step(params, h, caches, jnp.asarray(8))
        got = np.asarray(model.head_fn(params, h))[:, 0]
        with torch.no_grad():
            want = hf(torch.tensor(ids)).logits.numpy()[:, -1]
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestBertParity:
    @pytest.fixture(scope="class")
    def bpair(self):
        from paddle_tpu.models.bert import BertModel
        from paddle_tpu.models.convert import (bert_config_from_torch,
                                               bert_params_from_torch)

        hf_cfg = transformers.BertConfig(
            vocab_size=199, hidden_size=48, num_hidden_layers=3,
            num_attention_heads=4, intermediate_size=96,
            max_position_embeddings=64, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0, hidden_act="gelu")
        torch.manual_seed(1)
        hf = transformers.BertModel(hf_cfg).eval()
        cfg = bert_config_from_torch(hf_cfg, compute_dtype="float32",
                                     use_flash_attention=False)
        model = BertModel(cfg)
        params = {k: jnp.asarray(v)
                  for k, v in bert_params_from_torch(hf).items()}
        return hf, model, params

    def test_hidden_states_and_pooler_match(self, bpair):
        hf, model, params = bpair
        rs = np.random.RandomState(4)
        ids = rs.randint(0, 199, (2, 13))
        tt = rs.randint(0, 2, (2, 13))
        with torch.no_grad():
            out = hf(torch.tensor(ids), token_type_ids=torch.tensor(tt))
        h = model.encode(params, jnp.asarray(ids), jnp.asarray(tt))
        np.testing.assert_allclose(np.asarray(h),
                                   out.last_hidden_state.numpy(),
                                   rtol=2e-4, atol=2e-4)
        pooled = model.pool_fn(params, h)
        np.testing.assert_allclose(np.asarray(pooled),
                                   out.pooler_output.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_exact_gelu_is_required_for_parity(self, bpair):
        """The tanh-approx gelu (round-2's unconditional choice) visibly
        diverges from torch's exact gelu — the hidden_act knob is
        load-bearing, not cosmetic."""
        from paddle_tpu.models.bert import BertModel
        from paddle_tpu.models.convert import bert_config_from_torch

        hf, model, params = bpair
        approx_cfg = bert_config_from_torch(
            hf.config, compute_dtype="float32", use_flash_attention=False,
            hidden_act="gelu_approx")
        approx_model = BertModel(approx_cfg)
        ids = np.random.RandomState(5).randint(0, 199, (2, 13))
        # tiny random weights keep activations where the two gelu forms
        # nearly agree; scale the FFN input weights so the nonlinearity is
        # actually exercised, then the knob must visibly change the output
        big = dict(params)
        big["blocks_fc1_w"] = params["blocks_fc1_w"] * 8.0
        h_exact = np.asarray(model.encode(big, jnp.asarray(ids)))
        h_approx = np.asarray(approx_model.encode(big, jnp.asarray(ids)))
        assert np.abs(h_exact - h_approx).max() > 1e-4


class TestBertMLMParity:
    def test_masked_lm_logits_match(self):
        """BertForMaskedLM ('bert.'-prefixed, no pooler, cls.predictions head)
        converts and its MLM logits match torch."""
        from paddle_tpu.models.bert import BertModel
        from paddle_tpu.models.convert import (bert_config_from_torch,
                                               bert_params_from_torch)

        hf_cfg = transformers.BertConfig(
            vocab_size=151, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=32, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0, hidden_act="gelu")
        torch.manual_seed(2)
        hf = transformers.BertForMaskedLM(hf_cfg).eval()
        cfg = bert_config_from_torch(hf_cfg, compute_dtype="float32",
                                     use_flash_attention=False)
        model = BertModel(cfg)
        params = {k: jnp.asarray(v)
                  for k, v in bert_params_from_torch(hf).items()}
        assert "pooler_w" not in params          # no pooling layer backbone
        assert "mlm_dense_w" in params

        ids = np.random.RandomState(6).randint(0, 151, (2, 11))
        with torch.no_grad():
            want = hf(torch.tensor(ids)).logits.numpy()
        h = model.encode(params, jnp.asarray(ids))
        got = np.asarray(model._mlm_logits(params, h))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestResNetParity:
    @pytest.mark.parametrize("data_format", ["NCHW", "NHWC"])
    def test_resnet18_logits_match_torch(self, data_format):
        """transformers ResNetForImageClassification (basic blocks, resnet18
        geometry) vs our torchvision-layout ResNet in BOTH layouts — an
        external oracle over the conv/BN/pool stack including running-stats
        eval semantics."""
        from paddle_tpu.models.convert import resnet_state_dict_from_torch
        from paddle_tpu.vision.models import resnet18

        hf_cfg = transformers.ResNetConfig(
            num_channels=3, embedding_size=64,
            hidden_sizes=[64, 128, 256, 512], depths=[2, 2, 2, 2],
            layer_type="basic", downsample_in_first_stage=False,
            num_labels=7)
        torch.manual_seed(3)
        hf = transformers.ResNetForImageClassification(hf_cfg)
        # random-but-nontrivial BN running stats (fresh init is mean 0/var 1,
        # which would mask running-stat mapping bugs): two train-mode passes
        hf.train()
        with torch.no_grad():
            for _ in range(2):
                hf(torch.randn(4, 3, 64, 64))
        hf.eval()

        import paddle_tpu as paddle
        paddle.seed(0)
        model = resnet18(num_classes=7, data_format=data_format)
        model.set_state_dict(resnet_state_dict_from_torch(hf))
        model.eval()

        x = np.random.RandomState(7).randn(2, 3, 64, 64).astype(np.float32)
        with torch.no_grad():
            want = hf(torch.tensor(x)).logits.numpy()
        xin = x.transpose(0, 2, 3, 1) if data_format == "NHWC" else x
        got = np.asarray(model(paddle.to_tensor(xin))._data)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
