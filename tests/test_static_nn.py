"""paddle.static.nn surface (reference static/nn/__init__.py — dense list;
sequence_* LoD ops are a declared non-goal)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(0)


class TestStaticNNDense:
    def test_fc_embedding_bilinear(self):
        x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
        assert tuple(snn.fc(x, 16, activation="relu").shape) == (4, 16)
        ids = paddle.to_tensor(np.array([1, 2, 3]))
        assert tuple(snn.embedding(ids, [10, 6]).shape) == (3, 6)
        y = paddle.to_tensor(np.random.rand(4, 5).astype("float32"))
        assert tuple(snn.bilinear_tensor_product(x, y, 3).shape) == (4, 3)

    def test_convs(self):
        img = paddle.to_tensor(np.random.rand(2, 3, 8, 8).astype("float32"))
        assert tuple(snn.conv2d(img, 6, 3, padding=1).shape) == (2, 6, 8, 8)
        assert snn.conv2d_transpose(img, 6, filter_size=3,
                                    stride=2).shape[1] == 6
        vol = paddle.to_tensor(np.random.rand(1, 2, 4, 4, 4).astype("float32"))
        assert snn.conv3d(vol, 3, 3, padding=1).shape[1] == 3
        off = paddle.to_tensor(np.zeros((2, 18, 6, 6), "float32"))
        assert tuple(snn.deform_conv2d(img, off, None, 4, 3).shape) == (2, 4, 6, 6)

    def test_norms(self):
        x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
        img = paddle.to_tensor(np.random.rand(2, 6, 8, 8).astype("float32"))
        assert tuple(snn.layer_norm(x).shape) == (4, 8)
        assert tuple(snn.group_norm(img, 3).shape) == (2, 6, 8, 8)
        assert tuple(snn.instance_norm(img).shape) == (2, 6, 8, 8)
        assert tuple(snn.batch_norm(img).shape) == (2, 6, 8, 8)
        out = np.asarray(snn.data_norm(x)._data)
        np.testing.assert_allclose(out.mean(0), 0.0, atol=1e-5)

    def test_spectral_norm_unit_sigma(self):
        w = paddle.to_tensor(np.random.rand(6, 4).astype("float32"))
        wn = np.asarray(snn.spectral_norm(w, power_iters=20)._data)
        assert abs(np.linalg.svd(wn, compute_uv=False)[0] - 1.0) < 1e-3

    def test_prelu_row_conv_nce(self):
        img = paddle.to_tensor(np.random.randn(2, 3, 4, 4).astype("float32"))
        assert tuple(snn.prelu(img, mode="channel").shape) == (2, 3, 4, 4)
        seq = paddle.to_tensor(np.random.rand(2, 6, 5).astype("float32"))
        assert tuple(snn.row_conv(seq, 2).shape) == (2, 6, 5)
        emb = paddle.to_tensor(np.random.rand(6, 8).astype("float32"))
        lab = paddle.to_tensor(np.array([1, 0, 3, 2, 1, 0]))
        loss = np.asarray(snn.nce(emb, lab, 20, num_neg_samples=4)._data)
        assert loss.shape == (6, 1) and np.all(loss > 0)


class TestStaticNNControlFlow:
    def test_cond_case_switch_while(self):
        x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
        c = snn.cond(paddle.to_tensor(True), lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(np.asarray(c._data),
                                   np.asarray(x._data) * 2, rtol=1e-6)
        sw = snn.switch_case(paddle.to_tensor(1),
                             [lambda: x * 1, lambda: x * 5, lambda: x * 9])
        np.testing.assert_allclose(np.asarray(sw._data),
                                   np.asarray(x._data) * 5, rtol=1e-6)
        sw_def = snn.switch_case(paddle.to_tensor(7),
                                 {1: lambda: x * 5}, default=lambda: x * 11)
        np.testing.assert_allclose(np.asarray(sw_def._data),
                                   np.asarray(x._data) * 11, rtol=1e-6)
        cse = snn.case([(paddle.to_tensor(False), lambda: x * 2)],
                       default=lambda: x * 7)
        np.testing.assert_allclose(np.asarray(cse._data),
                                   np.asarray(x._data) * 7, rtol=1e-6)
        i0 = paddle.to_tensor(np.int32(0))
        out = snn.while_loop(lambda i: i < 5, lambda i: (i + 1,), (i0,))
        assert int(np.asarray(out[0]._data)) == 5


class TestStaticNNDetection:
    def test_crf_decoding(self):
        em = paddle.to_tensor(np.random.rand(2, 7, 4).astype("float32"))
        path = snn.crf_decoding(em)
        assert tuple(path.shape) == (2, 7)

    def test_multi_box_head(self):
        feats = [paddle.to_tensor(np.random.rand(2, 8, 4, 4).astype("float32")),
                 paddle.to_tensor(np.random.rand(2, 8, 2, 2).astype("float32"))]
        image = paddle.to_tensor(np.random.rand(2, 3, 32, 32).astype("float32"))
        loc, conf, pb, pv = snn.multi_box_head(
            feats, image, 32, 5, [[2.0], [2.0]], min_ratio=20, max_ratio=90)
        n_priors = pb.shape[0]
        assert tuple(loc.shape) == (2, n_priors, 4)
        assert tuple(conf.shape) == (2, n_priors, 5)
        assert tuple(pv.shape) == (n_priors, 4)
        boxes = np.asarray(pb._data)
        assert boxes.min() >= 0.0 and boxes.max() <= 1.0

    def test_reference_all_resolves(self):
        import ast, os
        ref = "/root/reference/python/paddle/static/nn/__init__.py"
        if not os.path.exists(ref):
            pytest.skip("no reference checkout")
        tree = ast.parse(open(ref).read())
        names = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", "") == "__all__":
                        names += [e.value for e in node.value.elts
                                  if isinstance(e, ast.Constant)]
        non_goal = {n for n in names if n.startswith("sequence_")}
        missing = [n for n in names
                   if n not in non_goal and not hasattr(snn, n)]
        assert not missing, missing


class TestStaticNNEdgeCases:
    def test_prelu_element_mode(self):
        x = paddle.to_tensor(np.random.randn(2, 3, 4, 4).astype("float32"))
        out = snn.prelu(x, mode="element")
        assert tuple(out.shape) == (2, 3, 4, 4)

    def test_cond_none_branches(self):
        x = paddle.to_tensor(np.random.rand(2, 2).astype("float32"))
        assert snn.cond(paddle.to_tensor(True)) is None
        with pytest.raises(ValueError):
            snn.cond(paddle.to_tensor(True), lambda: x * 2)  # mismatch

    def test_switch_case_out_of_range_runs_last(self):
        x = paddle.to_tensor(np.random.rand(2, 2).astype("float32"))
        out = snn.switch_case(paddle.to_tensor(-3),
                              [lambda: x * 1, lambda: x * 5, lambda: x * 9])
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(x._data) * 9, rtol=1e-6)
        out = snn.switch_case(paddle.to_tensor(7),
                              {1: lambda: x * 5, 3: lambda: x * 8})
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(x._data) * 8, rtol=1e-6)

    def test_conv2d_transpose_output_size(self):
        img = paddle.to_tensor(np.random.rand(1, 2, 8, 8).astype("float32"))
        out = snn.conv2d_transpose(img, 3, output_size=[17, 17],
                                   filter_size=3, stride=2)
        assert tuple(out.shape)[-2:] == (17, 17)

    def test_multi_box_head_no_max_sizes(self):
        feats = [paddle.to_tensor(np.random.rand(1, 4, 4, 4).astype("float32"))]
        image = paddle.to_tensor(np.random.rand(1, 3, 16, 16).astype("float32"))
        loc, conf, pb, pv = snn.multi_box_head(feats, image, 16, 3, [[2.0]],
                                               min_sizes=[8.0])
        assert pb.shape[0] == loc.shape[1]

    def test_crf_decoding_with_label_returns_correctness(self):
        em = paddle.to_tensor(np.random.rand(2, 5, 4).astype("float32"))
        path = snn.crf_decoding(em)
        correct = snn.crf_decoding(em, label=path)
        arr = np.asarray(correct._data)
        assert set(np.unique(arr)).issubset({0, 1})
