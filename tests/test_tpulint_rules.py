"""Per-rule tpulint fixture tests: every rule must trip on its bad_*.py
fixture and stay silent on its clean_*.py near-miss, plus pragma
suppression semantics.  The fixture files under
paddle_tpu/analysis/fixtures/ are the single corpus shared by these tests
and the CI gate (they are linted in place and frozen in
tools/tpulint_baseline.json, so a rule silently going blind breaks the
ratchet too — see docs/STATIC_ANALYSIS.md)."""

import pathlib
import textwrap

import pytest

from paddle_tpu.analysis import RULES, lint_paths, lint_source

ROOT = pathlib.Path(__file__).parent.parent
FIXTURES = ROOT / "paddle_tpu" / "analysis" / "fixtures"

#: rule id → bad fixture (path-scoped rules live in subdirs)
BAD_FIXTURE = {
    "host-impurity-in-jit": "bad_host_impurity_in_jit.py",
    "donated-arg-reuse": "bad_donated_arg_reuse.py",
    "traced-python-branch": "bad_traced_python_branch.py",
    "unhashable-static-arg": "bad_unhashable_static_arg.py",
    "silent-except": "bad_silent_except.py",
    "unseeded-nondeterminism": "distributed/bad_unseeded_nondeterminism.py",
    "import-time-device-touch": "bad_import_time_device_touch.py",
    "no-print": "bad_no_print.py",
    "jit-in-hot-loop": "bad_jit_in_hot_loop.py",
    "blocking-fetch-in-loop": "bad_blocking_fetch_in_loop.py",
    "unbounded-retry": "bad_unbounded_retry.py",
    "raw-partition-spec": "bad_raw_partition_spec.py",
    "raw-memory-introspection": "bad_raw_memory_introspection.py",
}
CLEAN_FIXTURE = {rule: path.replace("bad_", "clean_")
                 for rule, path in BAD_FIXTURE.items()}


def _lint(path: pathlib.Path):
    return lint_paths([path], root=ROOT)


def test_fixture_map_covers_every_rule():
    assert set(BAD_FIXTURE) == set(RULES), (
        "every registered rule needs a bad/clean fixture pair — add one "
        "under paddle_tpu/analysis/fixtures/ and rebaseline")


@pytest.mark.parametrize("rule", sorted(BAD_FIXTURE))
def test_bad_fixture_trips_its_rule(rule):
    findings = _lint(FIXTURES / BAD_FIXTURE[rule])
    hits = [f for f in findings if f.rule == rule]
    assert hits, f"{BAD_FIXTURE[rule]} produced no {rule} finding"
    for f in hits:
        assert f.line > 0 and f.path.startswith("paddle_tpu/analysis/fixtures/")


@pytest.mark.parametrize("rule", sorted(CLEAN_FIXTURE))
def test_clean_fixture_is_silent(rule):
    findings = _lint(FIXTURES / CLEAN_FIXTURE[rule])
    assert findings == [], (
        f"{CLEAN_FIXTURE[rule]} should be finding-free, got: "
        f"{[f.render() for f in findings]}")


# ------------------------------------------------------------------- pragmas

def test_pragma_fixture_fully_suppressed():
    assert _lint(FIXTURES / "pragma_suppressed.py") == []


def test_pragma_without_reason_reports_and_does_not_suppress():
    findings = _lint(FIXTURES / "bad_pragma_missing_reason.py")
    rules = sorted(f.rule for f in findings)
    assert rules == ["bad-pragma", "silent-except"]


def test_pragma_wrong_rule_does_not_suppress():
    src = textwrap.dedent("""\
        def f(x):
            try:
                x()
            except Exception:  # tpulint: disable=no-print(wrong rule id)
                pass
    """)
    findings = lint_source("paddle_tpu/x.py", src)
    assert [f.rule for f in findings] == ["silent-except"]


def test_pragma_disable_all():
    src = textwrap.dedent("""\
        def f(x):
            try:
                x()
            except Exception:  # tpulint: disable=all(demo of the big hammer)
                pass
    """)
    assert lint_source("paddle_tpu/x.py", src) == []


def test_pragma_text_in_docstring_is_documentation_not_a_pragma():
    """Only COMMENT tokens carry pragmas: quoting the syntax in a docstring
    must neither suppress findings nor emit bad-pragma."""
    src = textwrap.dedent('''\
        """Docs: write # tpulint: disable=silent-except to suppress,
        never a bare # tpulint: disable=silent-except without a reason."""

        def f(x):
            try:
                x()
            except Exception:
                pass
    ''')
    findings = lint_source("paddle_tpu/x.py", src)
    assert [f.rule for f in findings] == ["silent-except"]


def test_main_guard_is_not_import_time():
    src = textwrap.dedent("""\
        import jax.numpy as jnp

        if __name__ == "__main__":
            jnp.zeros((3,))
    """)
    assert lint_source("tools/cli.py", src) == []


def test_syntax_error_is_a_finding_not_a_crash():
    findings = lint_source("paddle_tpu/broken.py", "def f(:\n")
    assert [f.rule for f in findings] == ["syntax-error"]


# ------------------------------------------------- rule-specific edge cases

def test_conditional_donate_argnums_is_not_guessed_at():
    """The tree's dominant idiom — donate_argnums=(0,) if donate else () —
    is opaque to the AST and must NOT produce findings."""
    src = textwrap.dedent("""\
        import functools
        import jax

        def make_step(donate):
            @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
            def step(state, batch):
                return state
            new = step({}, 1)
            return new, step
    """)
    assert lint_source("paddle_tpu/x.py", src) == []


def test_no_print_ignores_files_outside_package():
    assert lint_source("tools/some_cli.py", "print('usage: ...')\n",
                       rules=[RULES["no-print"]]) == []


def test_no_print_reports_stale_allowlist_entry():
    from paddle_tpu.analysis.rules import PRINT_ALLOWLIST
    rel = sorted(PRINT_ALLOWLIST)[0]
    findings = lint_source(f"paddle_tpu/{rel}", "x = 1\n",
                           rules=[RULES["no-print"]])
    assert len(findings) == 1 and "stale" in findings[0].message


def test_jit_in_hot_loop_flags_all_four_shapes():
    """The bad fixture carries one positive per detection shape: jit in a
    for loop, shard_map in a while loop, immediately-invoked jit inside a
    function, and a jit-decorated def inside a loop (decorator re-runs
    per iteration)."""
    findings = _lint(FIXTURES / "bad_jit_in_hot_loop.py")
    msgs = [f.message for f in findings if f.rule == "jit-in-hot-loop"]
    assert len(msgs) == 5, msgs
    assert sum("for loop" in m for m in msgs) == 3
    assert sum("while loop" in m for m in msgs) == 1
    assert sum("one expression" in m for m in msgs) == 1
    assert sum("@jit-decorated" in m for m in msgs) == 1


def test_raw_partition_spec_exempts_only_the_authority_file():
    """sharding_rules.py IS the sanctioned constructor site; the same
    source anywhere else in the tree must trip."""
    src = ("from jax.sharding import PartitionSpec as P\n"
           "def spec():\n"
           "    return P('data')\n")
    assert lint_source("paddle_tpu/distributed/sharding_rules.py", src,
                       rules=[RULES["raw-partition-spec"]]) == []
    findings = lint_source("paddle_tpu/distributed/spmd.py", src,
                           rules=[RULES["raw-partition-spec"]])
    assert [f.rule for f in findings] == ["raw-partition-spec"]


def test_jit_in_hot_loop_ignores_shard_map_invoked_inside_traced_body():
    """shard_map built-and-called inside a function is the models/gpt.py
    idiom (the body traces once under the outer jit) — only LOOP
    construction of shard_map is a hazard."""
    src = textwrap.dedent("""\
        import functools
        from paddle_tpu.distributed.spmd import shard_map

        def block(q, mesh, spec):
            return shard_map(functools.partial(sum), mesh=mesh,
                             in_specs=spec, out_specs=spec)(q)
    """)
    assert lint_source("paddle_tpu/x.py", src,
                       rules=[RULES["jit-in-hot-loop"]]) == []
