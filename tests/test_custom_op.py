"""Custom-op registration + C++ extension tests (SURVEY row 8 —
framework/custom_operator.cc + utils/cpp_extension analogs)."""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import custom_op, get_op, list_ops, register_op


class TestCustomOp:
    def test_autodiff_op_eager_and_tape(self):
        op = register_op("square_plus", lambda x, y: x * x + y)
        a = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                             stop_gradient=False)
        b = paddle.to_tensor(np.array([1.0, 1.0], np.float32),
                             stop_gradient=False)
        out = op(a, b)
        np.testing.assert_allclose(np.asarray(out._data), [5.0, 10.0])
        out.backward(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(a.grad._data), [4.0, 6.0])
        np.testing.assert_allclose(np.asarray(b.grad._data), [1.0, 1.0])
        assert "square_plus" in list_ops()
        assert get_op("square_plus") is op

    def test_custom_backward_overrides_autodiff(self):
        # forward x^2, but backward deliberately returns 10*dOut (not 2x*dOut)
        op = register_op("weird_sq", lambda x: x * x,
                         backward=lambda g, ins, outs: (g[0] * 10.0,))
        x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        out = op(x)
        out.backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), [10.0])

    def test_reference_grad_convention_sees_inputs_and_outputs(self):
        """backward receives (dOut, X, Out) — the GradOpMaker contract."""
        seen = {}

        def bwd(g, ins, outs):
            seen["in"] = np.asarray(ins[0])
            seen["out"] = np.asarray(outs[0])
            return (g[0] * outs[0],)  # d/dx exp(x) = exp(x) = Out

        op = register_op("myexp", jnp.exp, backward=bwd)
        x = paddle.to_tensor(np.array([0.5], np.float32), stop_gradient=False)
        out = op(x)
        out.backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), np.exp([0.5]),
                                   rtol=1e-6)
        np.testing.assert_allclose(seen["in"], [0.5])
        np.testing.assert_allclose(seen["out"], np.exp([0.5]), rtol=1e-6)

    def test_works_under_jit(self):
        op = register_op("triple", lambda x: 3.0 * x,
                         backward=lambda g, ins, outs: (3.0 * g[0],))
        f = jax.jit(jax.grad(lambda x: jnp.sum(op._raw(x) ** 2)))
        g = f(jnp.array([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(g), [18.0, 36.0])

    def test_decorator_and_pallas_kernel(self):
        """A Pallas kernel registered as a custom op (the reference's
        'compiled kernel' path, TPU-style)."""
        from jax.experimental import pallas as pl

        def scale_kernel(x_ref, o_ref, *, factor):
            o_ref[:] = x_ref[:] * factor

        @custom_op(name="pallas_scale")
        def pallas_scale(x):
            import functools
            return pl.pallas_call(
                functools.partial(scale_kernel, factor=2.5),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=jax.default_backend() != "tpu",
            )(x)

        x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(1, 8))
        out = pallas_scale(x)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.arange(8, dtype=np.float32)[None] * 2.5)

    def test_unknown_op_raises(self):
        with pytest.raises(KeyError, match="no custom op"):
            get_op("nope_never_registered")


class TestCppExtension:
    def test_load_compile_and_run(self, tmp_path):
        src = tmp_path / "myops.cpp"
        src.write_text(textwrap.dedent("""
            #include <cstdint>
            extern "C" void twice_plus_one(const float* x, float* out,
                                           int64_t n) {
                for (int64_t i = 0; i < n; ++i) out[i] = x[i] * 2.0f + 1.0f;
            }
        """))
        from paddle_tpu.utils import cpp_extension
        ext = cpp_extension.load("myops", [str(src)],
                                 build_directory=str(tmp_path / "build"))
        op = ext.wrap_elementwise("twice_plus_one")
        x = jnp.asarray(np.arange(6, dtype=np.float32))
        out = op(x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.arange(6, dtype=np.float32) * 2 + 1)
        # inside jit: lowered as a host callback
        jout = jax.jit(op)(x)
        np.testing.assert_allclose(np.asarray(jout), np.asarray(out))
        # and through the custom-op registry on Tensors
        reg = register_op("twice_plus_one", op)
        t = paddle.to_tensor(np.ones(3, np.float32))
        np.testing.assert_allclose(np.asarray(reg(t)._data), [3.0, 3.0, 3.0])

    def test_missing_symbol_raises(self, tmp_path):
        src = tmp_path / "empty.cpp"
        src.write_text('extern "C" void something(const float* a, float* b, '
                       'long long n) {}')
        from paddle_tpu.utils import cpp_extension
        ext = cpp_extension.load("empty", [str(src)],
                                 build_directory=str(tmp_path / "build"))
        with pytest.raises(cpp_extension.CppExtensionError, match="symbol"):
            ext.wrap_elementwise("not_there")

    def test_missing_source_raises(self):
        from paddle_tpu.utils import cpp_extension
        with pytest.raises(cpp_extension.CppExtensionError, match="not found"):
            cpp_extension.load("x", ["/does/not/exist.cpp"])
