"""Optimizer trajectory parity vs torch.optim / reference kernel formulas
(≙ reference unittests/test_{sgd,momentum,adam,adamw,...}_op.py — the update
rules cite operators/optimizers/*_op.h)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle

STEPS = 5


def _run_paddle(opt_cls, kwargs, grads):
    paddle.seed(0)
    p = paddle.to_tensor(P0.copy(), stop_gradient=False)
    opt = opt_cls(parameters=[p], **kwargs)
    for g in grads:
        p.grad = paddle.to_tensor(g)
        opt.step()
        opt.clear_grad()
    return np.asarray(p._data)


def _run_torch(opt_cls, kwargs, grads):
    tp = torch.tensor(P0.copy(), requires_grad=True)
    opt = opt_cls([tp], **kwargs)
    for g in grads:
        opt.zero_grad()
        tp.grad = torch.tensor(g)
        opt.step()
    return tp.detach().numpy()


RNG = np.random.RandomState(3)
P0 = RNG.randn(6, 4).astype("float32")
GRADS = [RNG.randn(6, 4).astype("float32") for _ in range(STEPS)]


TORCH_PAIRS = [
    ("sgd", paddle.optimizer.SGD, {"learning_rate": 0.1},
     torch.optim.SGD, {"lr": 0.1}, 1e-6),
    ("momentum", paddle.optimizer.Momentum,
     {"learning_rate": 0.1, "momentum": 0.9},
     torch.optim.SGD, {"lr": 0.1, "momentum": 0.9}, 1e-6),
    ("nesterov", paddle.optimizer.Momentum,
     {"learning_rate": 0.1, "momentum": 0.9, "use_nesterov": True},
     torch.optim.SGD, {"lr": 0.1, "momentum": 0.9, "nesterov": True}, 1e-6),
    ("adam", paddle.optimizer.Adam,
     {"learning_rate": 0.01, "beta1": 0.9, "beta2": 0.99, "epsilon": 1e-8},
     torch.optim.Adam, {"lr": 0.01, "betas": (0.9, 0.99), "eps": 1e-8}, 1e-5),
    ("adamw", paddle.optimizer.AdamW,
     {"learning_rate": 0.01, "weight_decay": 0.05},
     torch.optim.AdamW, {"lr": 0.01, "weight_decay": 0.05}, 1e-5),
    ("adamax", paddle.optimizer.Adamax,
     {"learning_rate": 0.01, "beta1": 0.9, "beta2": 0.999},
     torch.optim.Adamax, {"lr": 0.01, "betas": (0.9, 0.999)}, 1e-5),
    ("adagrad", paddle.optimizer.Adagrad,
     {"learning_rate": 0.05, "initial_accumulator_value": 0.1},
     torch.optim.Adagrad, {"lr": 0.05, "initial_accumulator_value": 0.1},
     1e-5),
    ("adadelta", paddle.optimizer.Adadelta,
     {"learning_rate": 1.0, "rho": 0.9, "epsilon": 1e-6},
     torch.optim.Adadelta, {"lr": 1.0, "rho": 0.9, "eps": 1e-6}, 1e-5),
]


@pytest.mark.parametrize("name,pcls,pkw,tcls,tkw,tol", TORCH_PAIRS,
                         ids=[c[0] for c in TORCH_PAIRS])
def test_trajectory_matches_torch(name, pcls, pkw, tcls, tkw, tol):
    got = _run_paddle(pcls, pkw, GRADS)
    want = _run_torch(tcls, tkw, GRADS)
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol, err_msg=name)


def test_rmsprop_matches_reference_formula():
    """rmsprop_op.h:72: ms = rho*ms + (1-rho)g^2;
    p -= lr * g / sqrt(ms + eps) — eps INSIDE the sqrt, unlike torch."""
    lr, rho, eps = 0.01, 0.95, 1e-6
    got = _run_paddle(paddle.optimizer.RMSProp,
                      {"learning_rate": lr, "rho": rho, "epsilon": eps}, GRADS)
    p = P0.copy()
    ms = np.zeros_like(p)
    for g in GRADS:
        ms = rho * ms + (1 - rho) * g * g
        p = p - lr * g / np.sqrt(ms + eps)
    np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-5)


def test_lars_matches_reference_formula():
    """lars_momentum_op: local_lr = lr * eta * ||p|| /
    (||g|| + lambda*||p||); v = mu*v + local_lr*(g + lambda*p); p -= v."""
    lr, mu, eta, lam = 0.1, 0.9, 0.001, 0.0005
    got = _run_paddle(paddle.optimizer.Lars,
                      {"learning_rate": lr, "momentum": mu,
                       "lars_coeff": eta, "lars_weight_decay": lam}, GRADS)
    p = P0.copy()
    v = np.zeros_like(p)
    for g in GRADS:
        pn, gn = np.linalg.norm(p), np.linalg.norm(g)
        local = lr * eta * pn / (gn + lam * pn)
        v = mu * v + local * (g + lam * p)
        p = p - v
    np.testing.assert_allclose(got, p, rtol=1e-4, atol=1e-5)


def test_lamb_matches_reference_formula():
    """lamb_op.h: adam moment update + trust ratio ||p||/||update||."""
    lr, b1, b2, eps, wd = 0.01, 0.9, 0.999, 1e-6, 0.01
    got = _run_paddle(paddle.optimizer.Lamb,
                      {"learning_rate": lr, "beta1": b1, "beta2": b2,
                       "epsilon": eps, "lamb_weight_decay": wd}, GRADS)
    p = P0.copy()
    m = np.zeros_like(p)
    vv = np.zeros_like(p)
    for t, g in enumerate(GRADS, start=1):
        m = b1 * m + (1 - b1) * g
        vv = b2 * vv + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        v_hat = vv / (1 - b2 ** t)
        r = m_hat / (np.sqrt(v_hat) + eps) + wd * p
        pn, rn = np.linalg.norm(p), np.linalg.norm(r)
        trust = np.where((pn > 0) & (rn > 0), pn / rn, 1.0)
        p = p - lr * trust * r
    np.testing.assert_allclose(got, p, rtol=1e-4, atol=1e-5)


class TestLRScheduleOracles:
    """Schedule-value parity vs torch.optim.lr_scheduler / paddle formulas
    (≙ reference test_lr_scheduler.py)."""

    def _paddle_lrs(self, sched, n=8):
        out = []
        for _ in range(n):
            out.append(float(sched()))
            sched.step()
        return out

    def _torch_lrs(self, cls, n=8, **kw):
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=kw.pop("base_lr"))
        s = cls(opt, **kw)
        out = []
        for _ in range(n):
            out.append(opt.param_groups[0]["lr"])
            opt.step()
            s.step()
        return out

    def test_exponential(self):
        from paddle_tpu.optimizer.lr import ExponentialDecay
        got = self._paddle_lrs(ExponentialDecay(0.1, gamma=0.8))
        want = self._torch_lrs(torch.optim.lr_scheduler.ExponentialLR,
                               base_lr=0.1, gamma=0.8)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_multistep(self):
        from paddle_tpu.optimizer.lr import MultiStepDecay
        got = self._paddle_lrs(MultiStepDecay(0.1, milestones=[2, 5],
                                              gamma=0.1))
        want = self._torch_lrs(torch.optim.lr_scheduler.MultiStepLR,
                               base_lr=0.1, milestones=[2, 5], gamma=0.1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_cosine_annealing(self):
        from paddle_tpu.optimizer.lr import CosineAnnealingDecay
        got = self._paddle_lrs(CosineAnnealingDecay(0.1, T_max=6))
        want = self._torch_lrs(torch.optim.lr_scheduler.CosineAnnealingLR,
                               base_lr=0.1, T_max=6)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_lambda_and_multiplicative(self):
        from paddle_tpu.optimizer.lr import LambdaDecay, MultiplicativeDecay
        got = self._paddle_lrs(LambdaDecay(0.1, lr_lambda=lambda e: 0.9 ** e))
        want = self._torch_lrs(torch.optim.lr_scheduler.LambdaLR,
                               base_lr=0.1, lr_lambda=lambda e: 0.9 ** e)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        gm = self._paddle_lrs(MultiplicativeDecay(0.1,
                                                  lr_lambda=lambda e: 0.95))
        wm = self._torch_lrs(torch.optim.lr_scheduler.MultiplicativeLR,
                             base_lr=0.1, lr_lambda=lambda e: 0.95)
        np.testing.assert_allclose(gm, wm, rtol=1e-6)

    def test_formula_schedules(self):
        from paddle_tpu.optimizer.lr import (InverseTimeDecay, NaturalExpDecay,
                                             NoamDecay, PiecewiseDecay,
                                             PolynomialDecay)
        base, gamma = 0.1, 0.5
        got = self._paddle_lrs(NaturalExpDecay(base, gamma=gamma), n=4)
        np.testing.assert_allclose(
            got, [base * np.exp(-gamma * e) for e in range(4)], rtol=1e-6)
        got = self._paddle_lrs(InverseTimeDecay(base, gamma=gamma), n=4)
        np.testing.assert_allclose(
            got, [base / (1 + gamma * e) for e in range(4)], rtol=1e-6)
        d2 = 64
        got = self._paddle_lrs(NoamDecay(d_model=d2, warmup_steps=3,
                                         learning_rate=1.0), n=5)
        want = [d2 ** -0.5 * min((e or 1) ** -0.5, (e or 1) * 3 ** -1.5)
                for e in range(5)]
        np.testing.assert_allclose(got, want, rtol=1e-5)
        got = self._paddle_lrs(PiecewiseDecay(boundaries=[2, 4],
                                              values=[1.0, 0.5, 0.1]), n=6)
        np.testing.assert_allclose(got, [1.0, 1.0, 0.5, 0.5, 0.1, 0.1],
                                   rtol=1e-6)
        got = self._paddle_lrs(PolynomialDecay(base, decay_steps=4,
                                               end_lr=0.01, power=2.0), n=6)
        want = [(base - 0.01) * (1 - min(e, 4) / 4) ** 2 + 0.01
                for e in range(6)]
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestGradClipOracles:
    """Grad-clip numerics vs torch equivalents (≙ reference
    test_gradient_clip.py)."""

    def _grads(self):
        r = np.random.RandomState(9)
        return [r.randn(4, 3).astype("float32") * 3,
                r.randn(7).astype("float32") * 0.1]

    def _clipped(self, clip, gs):
        ps = [paddle.to_tensor(np.zeros_like(g), stop_gradient=False)
              for g in gs]
        for p, g in zip(ps, gs):
            p.grad = paddle.to_tensor(g)
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=ps,
                                   grad_clip=clip)
        opt.step()
        # with lr=1 and zero init, new param = -clipped_grad
        return [-np.asarray(p._data) for p in ps]

    def test_global_norm_matches_torch(self):
        import torch
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm
        gs = self._grads()
        got = self._clipped(ClipGradByGlobalNorm(1.0), gs)
        tps = [torch.nn.Parameter(torch.zeros(g.shape)) for g in gs]
        for tp, g in zip(tps, gs):
            tp.grad = torch.tensor(g)
        torch.nn.utils.clip_grad_norm_(tps, 1.0)
        for a, tp in zip(got, tps):
            np.testing.assert_allclose(a, tp.grad.numpy(),
                                       rtol=1e-5, atol=1e-6)

    def test_by_norm_per_tensor(self):
        from paddle_tpu.nn.clip import ClipGradByNorm
        gs = self._grads()
        got = self._clipped(ClipGradByNorm(1.0), gs)
        for a, g in zip(got, gs):
            n = np.linalg.norm(g)
            want = g / max(n, 1.0)
            np.testing.assert_allclose(a, want, rtol=1e-5, atol=1e-6)

    def test_by_value(self):
        from paddle_tpu.nn.clip import ClipGradByValue
        gs = self._grads()
        got = self._clipped(ClipGradByValue(max=0.5, min=-0.25), gs)
        for a, g in zip(got, gs):
            np.testing.assert_allclose(a, np.clip(g, -0.25, 0.5),
                                       rtol=1e-6, atol=1e-7)
