"""Sharding-rules layer tests (distributed/sharding_rules.py +
distributed/update_sharding.py).

Three concerns:

1. Resolver semantics — first-match ordering, scalar exemption, unmatched
   and indivisible policies (with replication-fallback accounting in the
   stats registry), rank fitting, optimizer-state and KV-pool trees.
2. Digest stability — rule-content digests, spec-tree digests, and the
   process-global ``sharding_rules_digest`` that jit/aot.py folds into
   executable-cache environments.
3. Trainer parity pins — the five re-based trainers must lower exactly as
   before the move (where specs came from functions moved verbatim, the
   pin is ``is``-identity on the re-exported functions: the same function
   object computes the same specs), and the NEW weight-update-sharded DP
   trainer must be loss- and param-identical to the replicated GSPMD
   baseline over a 10-step run while holding ~R× less optimizer HBM.
"""

import re
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import sharding_rules as sr
from paddle_tpu.distributed.update_sharding import (
    make_dp_update_sharded_train_step, update_sharding_rules)
from paddle_tpu.utils.stats import get_all_stats


def _mesh(n, names=("data",), shape=None):
    devs = np.array(jax.devices()[:n])
    if shape is not None:
        devs = devs.reshape(shape)
    return Mesh(devs, names)


def _fallback_stats():
    s = get_all_stats()
    return (s.get("sharding_replicated_fallback_bytes", 0),
            s.get("sharding_replicated_fallback_leaves", 0))


# ==========================================================================
# 1. resolver semantics
# ==========================================================================

class TestResolver:
    def test_first_match_wins_in_rule_order(self):
        rules = sr.ShardingRules([
            (r"w", ("data",)),
            (r".*", None),
        ])
        assert rules.spec_for("w1", np.zeros((8,))) == P("data")
        assert rules.spec_for("b1", np.zeros((8,))) == P()
        # reversed order: the general rule shadows the specific one
        shadowed = sr.ShardingRules([(r".*", None), (r"w", ("data",))])
        assert shadowed.spec_for("w1", np.zeros((8,))) == P()

    def test_search_not_fullmatch(self):
        rules = sr.ShardingRules([(r"attn/", ("model",))],
                                 unmatched="raise")
        assert rules.spec_for("block0/attn/wq",
                              np.zeros((4, 4))) == P("model")

    def test_scalar_and_size1_leaves_are_always_replicated(self):
        rules = sr.ShardingRules([(r".*", ("data",))])
        assert rules.spec_for("step", np.zeros(())) == P()
        assert rules.spec_for("beta_pow", np.zeros((1,))) == P()
        # the same rule DOES shard a real vector
        assert rules.spec_for("v", np.zeros((8,))) == P("data")

    def test_unmatched_raise_names_the_path(self):
        rules = sr.ShardingRules([(r"^w$", ("data",))], name="strict")
        with pytest.raises(ValueError, match=r"no rule matches path 'b'"):
            rules.spec_for("b", np.zeros((8,)))

    def test_unmatched_replicate_warns_and_accounts_bytes(self):
        rules = sr.ShardingRules([(r"^w$", ("data",))],
                                 unmatched="replicate")
        b0, l0 = _fallback_stats()
        leaf = np.zeros((8,), np.float32)     # 32 bytes
        with pytest.warns(UserWarning, match="stays fully replicated"):
            assert rules.spec_for("b", leaf) == P()
        b1, l1 = _fallback_stats()
        assert b1 - b0 == leaf.nbytes
        assert l1 - l0 == 1

    def test_indivisible_replicate_drops_the_axis_with_accounting(self):
        mesh = _mesh(2)
        rules = sr.ShardingRules([(r".*", ("data",))], mesh=mesh)
        b0, _ = _fallback_stats()
        with pytest.warns(UserWarning, match="indivisible|replicated"):
            spec = rules.spec_for("odd", np.zeros((7, 4), np.float32))
        assert spec == P()                    # dropped entry, squeezed
        b1, _ = _fallback_stats()
        assert b1 - b0 == 7 * 4 * 4
        # divisible leaf under the same rules still shards
        assert rules.spec_for("even", np.zeros((8, 4))) == P("data")

    def test_indivisible_raise(self):
        rules = sr.ShardingRules([(r".*", ("data",))], mesh=_mesh(2),
                                 indivisible="raise")
        with pytest.raises(ValueError, match="does not divide dim 0"):
            rules.spec_for("odd", np.zeros((7,)))

    def test_rank_fit_trims_pads_and_squeezes(self):
        rules = sr.ShardingRules([(r".*", ("data", None))])
        # 1-D leaf: trailing entry trimmed
        assert rules.spec_for("v", np.zeros((8,))) == P("data")
        # 3-D leaf: padded with None then squeezed back
        assert rules.spec_for("t", np.zeros((8, 4, 2))) == P("data")
        # squeeze keeps equality rank-independent: P("data", None) never
        # leaks out of the resolver
        assert rules.spec_for("m", np.zeros((8, 4))) == P("data")

    def test_tuple_axis_entry_divisibility_uses_product_degree(self):
        mesh = _mesh(4, ("data", "model"), shape=(2, 2))
        rules = sr.ShardingRules([(r".*", (("data", "model"),))], mesh=mesh)
        assert rules.spec_for("v", np.zeros((8,))) == P(("data", "model"))
        with pytest.warns(UserWarning):
            assert rules.spec_for("odd", np.zeros((6,))) == P()

    def test_rule_spec_forms_are_equivalent(self):
        leaf = np.zeros((8, 4))
        for form in [P("data"), ("data",), ["data"]]:
            assert sr.ShardingRules([(r".*", form)]).spec_for(
                "x", leaf) == P("data")
        for form in [None, P(), ()]:
            assert sr.ShardingRules([(r".*", form)]).spec_for(
                "x", leaf) == P()

    def test_bad_policy_and_bad_spec_type_raise(self):
        with pytest.raises(ValueError, match="unmatched"):
            sr.ShardingRules([], unmatched="bogus")
        with pytest.raises(ValueError, match="indivisible"):
            sr.ShardingRules([], indivisible="bogus")
        with pytest.raises(TypeError, match="rule spec"):
            sr.ShardingRules([(r".*", 5)])

    def test_resolve_preserves_tree_structure(self):
        tree = {"a": {"w": np.zeros((8, 4)), "b": np.zeros((4,))},
                "n": [np.zeros((8,)), np.zeros(())]}
        specs = sr.ShardingRules([
            (r"/w$", ("data", None)),
            (r".*", None),
        ]).resolve(tree)
        assert specs == {"a": {"w": P("data"), "b": P()},
                         "n": [P(), P()]}

    def test_resolve_state_slots_inherit_their_params_rule(self):
        """Optimizer slots resolve under ``params/<pname>`` so ONE rule
        table covers params and their moments; scalar slot leaves (beta
        powers) stay exempt and opt/step is pinned replicated."""
        state = {
            "params": {"w": np.zeros((8, 4)), "b": np.zeros((4,))},
            "opt": {"step": np.zeros(()),
                    "slots": {"w": {"m": np.zeros((8, 4)),
                                    "beta1_pow": np.zeros((1,))},
                              "b": {"m": np.zeros((4,))}}},
            "buffers": {},
        }
        specs = sr.ShardingRules([
            (r"^params/w$", ("data", None)),
            (r".*", None),
        ]).resolve_state(state)
        assert specs["params"] == {"w": P("data"), "b": P()}
        assert specs["opt"]["step"] == P()
        assert specs["opt"]["slots"]["w"]["m"] == P("data")
        assert specs["opt"]["slots"]["w"]["beta1_pow"] == P()
        assert specs["opt"]["slots"]["b"]["m"] == P()

    def test_kv_pool_tree_resolves_like_any_pytree(self):
        """KV-cache pools are plain trees to the resolver: page pools
        shard their head dim on 'model', everything else replicates."""
        pool = {"layers": [{"k": np.zeros((16, 8, 4, 64)),
                            "v": np.zeros((16, 8, 4, 64))} for _ in range(2)],
                "page_table": np.zeros((32,), np.int32)}
        specs = sr.ShardingRules([
            (r"layers/\d+/[kv]$", (None, None, "model", None)),
            (r"page_table", None),
        ]).resolve(pool)
        assert specs["layers"][0]["k"] == P(None, None, "model")
        assert specs["layers"][1]["v"] == P(None, None, "model")
        assert specs["page_table"] == P()

    def test_shardings_builds_namedshardings_on_the_mesh(self):
        mesh = _mesh(2)
        tree = {"w": np.zeros((8, 4)), "s": np.zeros(())}
        sh = sr.ShardingRules([(r".*", ("data",))],
                              mesh=mesh).shardings(tree)
        assert sh["w"] == NamedSharding(mesh, P("data"))
        assert sh["s"] == NamedSharding(mesh, P())
        # unbound rules need an explicit mesh
        with pytest.raises(ValueError, match="needs a mesh"):
            sr.ShardingRules([(r".*", None)]).shardings(tree)

    def test_match_partition_rules_functional_shorthand(self):
        tree = {"w": np.zeros((8,)), "b": np.zeros((4,))}
        specs = sr.match_partition_rules(
            [(r"w", ("data",)), (r".*", None)], tree)
        assert specs == {"w": P("data"), "b": P()}
        with pytest.raises(ValueError, match="no rule matches"):
            sr.match_partition_rules([(r"w", ("data",))], tree)


class TestSpecConstructors:
    def test_make_and_replicated(self):
        assert sr.make_spec("data", None) == P("data", None)
        assert sr.replicated_spec() == P()

    def test_replica_stacked_spec_pads_to_leaf_rank(self):
        assert sr.replica_stacked_spec(np.zeros((4, 2, 3)),
                                       "data") == P("data", None, None)
        assert sr.replica_stacked_spec(np.zeros((4,)), "data") == P("data")

    def test_batch_spec_falls_back_when_axis_is_trivial(self):
        assert sr.batch_spec(_mesh(2)) == P("data")
        assert sr.batch_spec(_mesh(1)) == P()
        assert sr.batch_spec(_mesh(2), "model") == P()

    def test_activation_batch_spec_per_mesh_shape(self):
        assert sr.activation_batch_spec(_mesh(2)) == P("data", None, None)
        assert sr.activation_batch_spec(
            _mesh(2, ("data", "sep"), shape=(1, 2))) == P("data", "sep", None)
        assert sr.activation_batch_spec(_mesh(1)) is None

    def test_sep_activation_spec(self):
        assert sr.sep_activation_spec() == P(None, "sep", None, None)
        assert sr.sep_activation_spec(ndim=3) == P(None, "sep", None)

    def test_override_leading_axis(self):
        assert sr.override_leading_axis(P(None, "model"), 3,
                                        "pipe") == P("pipe", "model", None)
        assert sr.override_leading_axis(P(), 2, "pipe") == P("pipe", None)

    def test_resolve_flat_shard_spec_divisible(self):
        assert sr.resolve_flat_shard_spec("r", 8, _mesh(2),
                                          "data") == P("data")
        # trivial axis: replicated WITHOUT fallback noise (nothing lost)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert sr.resolve_flat_shard_spec("r", 8, _mesh(1),
                                              "data") == P()

    def test_resolve_flat_shard_spec_indivisible_accounts(self):
        b0, l0 = _fallback_stats()
        with pytest.warns(UserWarning, match="stays fully replicated"):
            spec = sr.resolve_flat_shard_spec("resid", 7, _mesh(2), "data")
        assert spec == P()
        b1, l1 = _fallback_stats()
        assert b1 - b0 == 7 * 4 and l1 - l0 == 1

    def test_replication_fallback_emits_tracer_event(self):
        events = []

        class Tracer:
            def emit(self, event, **kw):
                events.append((event, kw))

        with pytest.warns(UserWarning):
            sr.replication_fallback("unit-test", "x", 128, axis="data",
                                    degree=2, tracer=Tracer())
        assert events == [("sharding_fallback",
                           {"kind": "unit-test", "name": "x", "bytes": 128,
                            "axis": "data", "degree": 2})]


# ==========================================================================
# 2. digest stability
# ==========================================================================

class TestDigests:
    def test_rules_digest_is_content_not_name(self):
        a = sr.ShardingRules([(r"w", ("data",))], name="a")
        b = sr.ShardingRules([(r"w", ("data",))], name="b")
        assert a.digest() == b.digest()
        assert re.fullmatch(r"[0-9a-f]{32}", a.digest())

    def test_rules_digest_is_order_policy_and_spec_sensitive(self):
        base = sr.ShardingRules([(r"w", ("data",)), (r".*", None)])
        assert base.digest() != sr.ShardingRules(
            [(r".*", None), (r"w", ("data",))]).digest()
        assert base.digest() != sr.ShardingRules(
            [(r"w", ("model",)), (r".*", None)]).digest()
        assert base.digest() != sr.ShardingRules(
            [(r"w", ("data",)), (r".*", None)],
            unmatched="replicate").digest()

    def test_rules_digest_equates_spec_forms(self):
        assert sr.ShardingRules([(r"w", ("data", None))]).digest() == \
            sr.ShardingRules([(r"w", P("data", None))]).digest()
        assert sr.ShardingRules([(r"w", None)]).digest() == \
            sr.ShardingRules([(r"w", P())]).digest()

    def test_spec_tree_digest_stable_and_content_sensitive(self):
        t1 = {"a": P("data"), "b": {"c": None}}
        t2 = {"b": {"c": None}, "a": P("data")}       # key order irrelevant
        assert sr.spec_tree_digest(t1) == sr.spec_tree_digest(t2)
        assert sr.spec_tree_digest(t1) != sr.spec_tree_digest(
            {"a": P("model"), "b": {"c": None}})
        # None vs P() are DIFFERENT digest inputs (None means
        # "unconstrained", P() means "replicated")
        assert sr.spec_tree_digest({"a": None}) != \
            sr.spec_tree_digest({"a": P()})

    def test_global_digest_tracks_registration(self):
        d0 = sr.sharding_rules_digest()
        rules = sr.ShardingRules([(r".*", ("data",))], name="test_digest")
        try:
            sr.register_rules(rules)
            d1 = sr.sharding_rules_digest()
            assert d1 != d0
            # idempotent: re-registering identical content changes nothing
            sr.register_rules(rules)
            assert sr.sharding_rules_digest() == d1
        finally:
            sr.unregister_rules("test_digest")
        assert sr.sharding_rules_digest() == d0


# ==========================================================================
# 3. trainer parity pins
# ==========================================================================

class TestTrainerParityPins:
    """The five re-based trainers import their spec logic from
    sharding_rules.  Where the functions moved VERBATIM, ``is``-identity
    is the strongest possible parity pin: the trainer calls the same
    function object, so it computes byte-identical specs and lowers
    identically.  (Behavioral 10-step parity for the one NEW trainer is
    TestUpdateSharding below; the five existing trainers keep their own
    suites in test_distributed.py et al.)"""

    def test_spmd_rebased_on_sharding_rules(self):
        from paddle_tpu.distributed import spmd
        assert spmd.build_param_specs is sr.build_param_specs
        assert spmd.build_state_shardings is sr.build_state_shardings
        assert spmd.batch_spec is sr.batch_spec

    def test_zero_rebased_on_sharding_rules(self):
        from paddle_tpu.distributed import zero
        assert zero.build_param_specs is sr.build_param_specs
        assert zero.resolve_flat_shard_spec is sr.resolve_flat_shard_spec

    def test_localsgd_and_dgc_rebased_on_sharding_rules(self):
        from paddle_tpu.distributed import dgc, localsgd
        assert localsgd.replica_stacked_spec is sr.replica_stacked_spec
        assert dgc.replica_stacked_spec is sr.replica_stacked_spec

    def test_pipeline_engine_rebased_via_spmd_reexport(self):
        from paddle_tpu.distributed import pipeline_engine
        assert pipeline_engine.build_param_specs is sr.build_param_specs
        assert pipeline_engine.build_state_shardings is \
            sr.build_state_shardings

    def test_build_param_specs_tp_pp_zero_semantics(self):
        """The moved inference still honors _dims_mapping / _pipe_stacked /
        zero_stage — the catalog rows 'tp', 'pp', 'zero3'."""
        mesh = _mesh(4, ("data", "model"), shape=(2, 2))

        class Leaf(np.ndarray):
            pass

        w = np.zeros((8, 6), np.float32).view(Leaf)
        w._dims_mapping = {1: "model"}
        odd = np.zeros((8, 5), np.float32).view(Leaf)
        odd._dims_mapping = {1: "model"}      # 5 % 2 != 0 -> dropped
        specs = sr.build_param_specs({"w": w, "odd": odd, "b":
                                      np.zeros((4,), np.float32)}, mesh)
        assert specs["w"] == P(None, "model")
        assert specs["odd"] == P(None, None)
        assert specs["b"] == P(None)


class _MLP:
    """Tiny 2-layer MLP as a functional loss for the parity run."""

    @staticmethod
    def params(seed=0):
        rng = np.random.default_rng(seed)
        return {
            "w1": jnp.asarray(rng.normal(size=(8, 16)) * 0.1, jnp.float32),
            "b1": jnp.zeros((16,), jnp.float32),
            "w2": jnp.asarray(rng.normal(size=(16, 4)) * 0.1, jnp.float32),
            "b2": jnp.zeros((4,), jnp.float32),
        }

    @staticmethod
    def loss(params, x, y):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        p = h @ params["w2"] + params["b2"]
        return jnp.mean((p - y) ** 2)

    @staticmethod
    def batch(seed=1):
        rng = np.random.default_rng(seed)
        return (jnp.asarray(rng.normal(size=(4, 8)), jnp.float32),
                jnp.asarray(rng.normal(size=(4, 4)), jnp.float32))


class TestUpdateSharding:
    """arXiv:2004.13336 equivalence: reduce-scatter + shard update +
    all-gather must be step-for-step identical to all-reduce + replicated
    update, with the optimizer state held at 1/R per replica."""

    def test_rule_table_layout(self):
        tree = {"params": {"w": np.zeros((8,))},
                "opt": {"step": np.zeros(()),
                        "slots": {"flat": np.zeros((16,))}},
                "comm_e": np.zeros((2, 16))}
        specs = update_sharding_rules("data").resolve(tree)
        assert specs["params"]["w"] == P()
        assert specs["opt"]["step"] == P()
        assert specs["opt"]["slots"]["flat"] == P("data")
        assert specs["comm_e"] == P("data")

    def test_ten_step_parity_with_replicated_gspmd_baseline(self):
        from paddle_tpu.distributed.spmd import make_gspmd_step_from_loss
        from paddle_tpu.distributed.zero import per_device_state_bytes
        from paddle_tpu.optimizer import Adam

        mesh = _mesh(2)
        x, y = _MLP.batch()
        lr = np.float32(0.05)

        # fresh params per builder: both steps donate their state
        ref_step, ref_state = make_gspmd_step_from_loss(
            _MLP.loss, _MLP.params(), Adam(0.05), mesh)
        us_step, us_state = make_dp_update_sharded_train_step(
            _MLP.loss, _MLP.params(), Adam(0.05), mesh)

        ref_bytes = per_device_state_bytes(ref_state)
        us_bytes = per_device_state_bytes(us_state)
        n = sum(int(np.prod(v.shape)) for v in _MLP.params().values())
        assert ref_bytes == 2 * n * 4          # Adam m+v, fully replicated
        assert us_bytes == ref_bytes // 2      # the R=2 saving, exactly

        ref_losses, us_losses = [], []
        for _ in range(10):
            ref_state, rl = ref_step(ref_state, lr, x, y)
            us_state, ul = us_step(us_state, lr, x, y)
            ref_losses.append(float(rl))
            us_losses.append(float(ul))

        np.testing.assert_allclose(us_losses, ref_losses,
                                   rtol=1e-5, atol=1e-7)
        assert ref_losses[-1] < ref_losses[0]  # both actually trained
        for k in ref_state["params"]:
            np.testing.assert_allclose(
                np.asarray(us_state["params"][k]),
                np.asarray(ref_state["params"][k]),
                rtol=1e-4, atol=1e-6, err_msg=k)

    def test_int8_ef_policy_composes(self):
        """Under int8_ef the reduce-scatter seam quantizes and the error
        residual rides per-replica stacked state — the step still trains."""
        from paddle_tpu.optimizer import SGD

        mesh = _mesh(2)
        step, state = make_dp_update_sharded_train_step(
            _MLP.loss, _MLP.params(), SGD(0.05), mesh, grad_comm="int8_ef")
        assert state["comm_e"].shape[0] == 2   # one residual per replica
        x, y = _MLP.batch()
        losses = []
        for _ in range(5):
            state, loss = step(state, np.float32(0.05), x, y)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        # the residual actually carries quantization error
        assert float(jnp.abs(state["comm_e"]).max()) > 0

    def test_replicated_args_ride_whole(self):
        """An RNG key in the batch position marked replicated must reach
        every replica un-split."""
        from paddle_tpu.optimizer import SGD

        def loss_with_key(params, key, x, y):
            noise = jax.random.normal(key, x.shape) * 1e-3
            return _MLP.loss(params, x + noise, y)

        mesh = _mesh(2)
        step, state = make_dp_update_sharded_train_step(
            loss_with_key, _MLP.params(), SGD(0.05), mesh,
            replicated_args=(0,))
        x, y = _MLP.batch()
        state, loss = step(state, np.float32(0.05), jax.random.key(0), x, y)
        assert np.isfinite(float(loss))

    def test_guards_refuse_unsupported_optimizers_and_meshes(self):
        from paddle_tpu.nn import ClipGradByGlobalNorm
        from paddle_tpu.optimizer import Adam, Lamb

        mesh = _mesh(2)
        with pytest.raises(NotImplementedError, match="grad_clip"):
            make_dp_update_sharded_train_step(
                _MLP.loss, _MLP.params(),
                Adam(0.05, grad_clip=ClipGradByGlobalNorm(1.0)), mesh)
        with pytest.raises(NotImplementedError, match="multi_precision"):
            make_dp_update_sharded_train_step(
                _MLP.loss, _MLP.params(),
                Adam(0.05, multi_precision=True), mesh)
        with pytest.raises(NotImplementedError, match="per-param-identity"):
            make_dp_update_sharded_train_step(
                _MLP.loss, _MLP.params(), Lamb(0.05), mesh)
        hybrid = _mesh(4, ("data", "model"), shape=(2, 2))
        with pytest.raises(NotImplementedError, match="non-trivial axes"):
            make_dp_update_sharded_train_step(
                _MLP.loss, _MLP.params(), Adam(0.05), hybrid)
