"""TPU smoke suite — Mosaic-path (non-interpret) evidence on real hardware.

Every Pallas kernel runs in ``interpret=True`` on the CPU CI suite, so a
Mosaic miscompile is invisible there (VERDICT r2 missing #6; ≙ the
reference's device-gated CI, tools/ci_op_benchmark.sh).  This suite runs the
same kernels through the real Mosaic compiler and checks numerics against
the dense/XLA path on-device.

Run (TPU only — skipped wholesale elsewhere):
    PYTHONPATH=/root/repo:/root/.axon_site python -m pytest -m tpu -q \
        tests/test_tpu_smoke.py 2>&1 | tee TPU_SMOKE_r04.log

The committed log (TPU_SMOKE_r04.log) is the round's hardware evidence.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu


def _on_tpu():
    import os
    if os.environ.get("PADDLE_TPU_TEST_TPU") != "1":
        return False  # don't touch the backend from CPU CI (tunnel dial risk)
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


skip_unless_tpu = pytest.mark.skipif(not _on_tpu(),
                                     reason="requires real TPU backend")


def _sync(x):
    """Host-fetch sync: block_until_ready on the tunneled backend returns
    early (BENCH_NOTES.md); fetching the value is the reliable barrier."""
    return np.asarray(x)


@skip_unless_tpu
class TestFlashMosaic:
    def _qkv(self, B=2, H=8, L=512, D=64, dtype=jnp.bfloat16, seed=0):
        r = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(r.standard_normal((B, L, H, D)), dtype=dtype)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_fwd_matches_dense(self, causal):
        from paddle_tpu.ops.attention import dense_attention, flash_attention
        q, k, v = self._qkv()
        out_f = _sync(jax.jit(
            lambda a, b, c: flash_attention(a, b, c, causal=causal))(q, k, v))
        out_d = _sync(jax.jit(
            lambda a, b, c: dense_attention(a, b, c, causal=causal))(q, k, v))
        np.testing.assert_allclose(out_f.astype(np.float32),
                                   out_d.astype(np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_flash_bwd_matches_dense(self):
        from paddle_tpu.ops.attention import dense_attention, flash_attention
        q, k, v = self._qkv(L=256)

        def loss_flash(a, b, c):
            return flash_attention(a, b, c, causal=True).astype(
                jnp.float32).sum()

        def loss_dense(a, b, c):
            return dense_attention(a, b, c, causal=True).astype(
                jnp.float32).sum()

        gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(_sync(a).astype(np.float32),
                                       _sync(b).astype(np.float32),
                                       rtol=5e-2, atol=5e-2)

    def test_flash_long_sequence_runs(self):
        """L=8192 flash step executes on hardware (long-context proof)."""
        from paddle_tpu.ops.attention import flash_attention
        q, k, v = self._qkv(B=1, L=8192)
        out = _sync(jax.jit(
            lambda a, b, c: flash_attention(a, b, c, causal=True))(q, k, v))
        assert out.shape == (1, 8192, 8, 64)
        assert np.isfinite(out.astype(np.float32)).all()


@skip_unless_tpu
class TestFusedLossMosaic:
    def test_fused_ce_matches_xla(self):
        from paddle_tpu.ops.loss import softmax_cross_entropy_mean
        r = np.random.RandomState(0)
        logits = jnp.asarray(r.standard_normal((8, 128, 1024)), jnp.bfloat16)
        labels = jnp.asarray(r.randint(0, 1024, (8, 128)))

        fused = float(_sync(jax.jit(softmax_cross_entropy_mean)(
            logits, labels)))
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ref = float(_sync(-jnp.take_along_axis(
            lp, labels[..., None], axis=-1).mean()))
        assert abs(fused - ref) < 2e-2, (fused, ref)


@skip_unless_tpu
class TestTrainStepMosaic:
    def test_gpt_train_step_runs_and_descends(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import (GPTConfig, GPTModel,
                                           make_gpt_train_step)
        from paddle_tpu.optimizer import AdamW

        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=2048, hidden_size=256, num_layers=2,
                        num_attention_heads=8, max_position_embeddings=256,
                        compute_dtype="bfloat16")
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, AdamW(1e-3), hcg,
                                          remat=False)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randint(0, 2048, (4, 256)))
        y = jnp.asarray(r.randint(0, 2048, (4, 256)))
        losses = []
        for i in range(8):
            state, loss = step(state, jax.random.key(i), np.float32(1e-3),
                               x, y)
            losses.append(float(_sync(loss)))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses


@skip_unless_tpu
class TestPagedAttentionMosaic:
    def test_paged_decode_matches_gather_fallback(self):
        """The in-kernel block-table walk (scalar-prefetch index maps)
        through the REAL Mosaic compiler vs the XLA gather fallback —
        the serving engine's decode hot path."""
        from paddle_tpu.models._decode import PagedKV, cached_attention
        from paddle_tpu.ops.paged_attention import paged_decode_attention
        r = np.random.RandomState(0)
        S, nh, hd, NB1, bs, C = 8, 12, 64, 33, 32, 8
        pk = jnp.asarray(r.standard_normal((NB1, bs, nh, hd)), jnp.bfloat16)
        pv = jnp.asarray(r.standard_normal((NB1, bs, nh, hd)), jnp.bfloat16)
        table = jnp.asarray(r.randint(0, NB1, (S, C)), jnp.int32)
        t = jnp.asarray(r.randint(0, C * bs, S), jnp.int32)
        pad = jnp.minimum(jnp.asarray(r.randint(0, bs, S), jnp.int32), t)
        q = jnp.asarray(r.standard_normal((S, nh, hd)), jnp.bfloat16)
        got = _sync(jax.jit(lambda *a: paged_decode_attention(*a))(
            q, pk, pv, table, t, pad))
        ref = _sync(jax.jit(lambda q_, k_, v_, t_, p_: cached_attention(
            q_[:, None], PagedKV(k_, table), PagedKV(v_, table), t_,
            pad_lens=p_))(q, pk, pv, t, pad))[:, 0]
        np.testing.assert_allclose(got.astype(np.float32),
                                   ref.astype(np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_ragged_mixed_pack_matches_gather_fallback(self):
        """The ragged mixed prefill+decode kernel (per-ROW table walk)
        through the REAL Mosaic compiler vs the XLA gather fallback —
        the one-program serving step's hot path."""
        from paddle_tpu.ops.ragged_paged_attention import (
            ragged_attention_ref, ragged_paged_attention, ragged_rows)
        r = np.random.RandomState(0)
        S, nh, hd, NB1, bs, C, T = 8, 12, 64, 33, 32, 8, 64
        pk = jnp.asarray(r.standard_normal((NB1, bs, nh, hd)), jnp.bfloat16)
        pv = jnp.asarray(r.standard_normal((NB1, bs, nh, hd)), jnp.bfloat16)
        table = jnp.asarray(r.randint(0, NB1, (S, C)), jnp.int32)
        # mixed pack: prefill chunks + single decode rows + an idle seq
        q_lens = np.array([16, 1, 0, 8, 1, 1, 24, 4])
        cu = jnp.asarray(np.concatenate([[0], np.cumsum(q_lens)]), jnp.int32)
        kv = jnp.asarray([q + int(r.randint(0, C * bs - q + 1)) if q else 0
                          for q in q_lens], jnp.int32)
        pad = jnp.asarray([int(r.randint(0, 8)) for _ in range(S)],
                          jnp.int32)
        q = jnp.asarray(r.standard_normal((T, nh, hd)), jnp.bfloat16)
        got = _sync(jax.jit(lambda *a: ragged_paged_attention(*a))(
            q, pk, pv, table, cu, kv, pad))
        rs, rp = ragged_rows(cu, kv, T)
        ref = _sync(jax.jit(lambda *a: ragged_attention_ref(*a))(
            q, pk, pv, table, rs, rp, pad))
        n_real = int(q_lens.sum())
        np.testing.assert_allclose(got[:n_real].astype(np.float32),
                                   ref[:n_real].astype(np.float32),
                                   rtol=2e-2, atol=2e-2)
