"""tools/bench_diff.py: the BENCH trajectory is machine-readable — every
accepted file shape normalizes, error/skipped records classify as
non-comparable (exit 2, never a fake regression), direction follows the
unit, and the exit-code contract holds."""

import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "_bench_diff", os.path.join(ROOT, "tools", "bench_diff.py"))
bench_diff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_diff)


def _rec(metric, value, unit="tokens/s/chip"):
    return {"metric": metric, "value": value, "unit": unit,
            "vs_baseline": None, "loss": 0.0}


ERROR_REC = {"metric": "deadcfg", "value": None, "unit": "error",
             "error": {"attempts": 2, "detail": []}}
SKIPPED_REC = {"metric": "deadcfg", "value": None, "unit": "skipped",
               "skipped": {"reason": "backend unhealthy", "probe": []}}


class TestClassify:
    def test_records(self):
        assert bench_diff.classify(_rec("m", 1.0)) == "ok"
        assert bench_diff.classify(ERROR_REC) == "error"
        assert bench_diff.classify(SKIPPED_REC) == "skipped"
        assert bench_diff.classify({"metric": "m", "value": None,
                                    "unit": "x"}) == "invalid"
        assert bench_diff.classify({"no": "metric"}) == "invalid"


class TestLoadShapes:
    def test_driver_wrapper_with_parsed_dict(self, tmp_path):
        p = tmp_path / "a.json"
        p.write_text(json.dumps({"n": 5, "cmd": "x", "rc": 0, "tail": "",
                                 "parsed": _rec("m", 10.0)}))
        assert bench_diff.load_records(str(p)) == [_rec("m", 10.0)]

    def test_driver_wrapper_parsed_null_recovers_from_tail(self, tmp_path):
        tail = "noise\n" + json.dumps(_rec("m", 7.0)) + "\nmore noise\n"
        p = tmp_path / "a.json"
        p.write_text(json.dumps({"n": 1, "rc": 1, "tail": tail,
                                 "parsed": None}))
        assert bench_diff.load_records(str(p)) == [_rec("m", 7.0)]

    def test_jsonl_and_list_and_single(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text(json.dumps(_rec("a", 1.0)) + "\n"
                     + json.dumps(_rec("b", 2.0)) + "\n")
        assert len(bench_diff.load_records(str(p))) == 2
        p2 = tmp_path / "b.json"
        p2.write_text(json.dumps([_rec("a", 1.0), ERROR_REC]))
        assert len(bench_diff.load_records(str(p2))) == 2
        p3 = tmp_path / "c.json"
        p3.write_text(json.dumps(_rec("solo", 3.0)))
        assert bench_diff.load_records(str(p3)) == [_rec("solo", 3.0)]

    def test_real_repo_artifacts_parse(self):
        # the actual trajectory in this repo must at least normalize
        for name in sorted(os.listdir(ROOT)):
            if name.startswith("BENCH_r") and name.endswith(".json"):
                recs = bench_diff.load_records(os.path.join(ROOT, name))
                for r in recs:
                    assert bench_diff.classify(r) in ("ok", "error",
                                                      "skipped")


class TestCompare:
    def test_throughput_drop_is_regression(self):
        rows, n_reg, n_cmp = bench_diff.compare(
            [_rec("m", 100.0)], [_rec("m", 80.0)], threshold=0.1)
        assert n_cmp == 1 and n_reg == 1
        assert rows[0]["delta_frac"] == pytest.approx(-0.2)

    def test_latency_rise_is_regression_drop_is_not(self):
        _rows, n_reg, _ = bench_diff.compare(
            [_rec("lat", 5.0, unit="ms")], [_rec("lat", 9.0, unit="ms")],
            threshold=0.1)
        assert n_reg == 1
        _rows, n_reg, _ = bench_diff.compare(
            [_rec("lat", 9.0, unit="ms")], [_rec("lat", 5.0, unit="ms")],
            threshold=0.1)
        assert n_reg == 0

    def test_within_threshold_ok(self):
        _rows, n_reg, n_cmp = bench_diff.compare(
            [_rec("m", 100.0)], [_rec("m", 95.0)], threshold=0.1)
        assert n_cmp == 1 and n_reg == 0

    def test_error_skipped_never_compare(self):
        rows, n_reg, n_cmp = bench_diff.compare(
            [ERROR_REC], [SKIPPED_REC], threshold=0.1)
        assert n_cmp == 0 and n_reg == 0
        assert "not comparable" in rows[0]["status"]


class TestExitCodes:
    def _write(self, path, records):
        path.write_text("\n".join(json.dumps(r) for r in records))

    def test_zero_clean_one_regression_two_nodata(self, tmp_path, capsys):
        old, new = tmp_path / "old.json", tmp_path / "new.json"
        self._write(old, [_rec("m", 100.0), ERROR_REC])
        self._write(new, [_rec("m", 99.0), SKIPPED_REC])
        assert bench_diff.main([str(old), str(new)]) == 0
        self._write(new, [_rec("m", 50.0)])
        assert bench_diff.main([str(old), str(new)]) == 1
        self._write(new, [SKIPPED_REC])
        assert bench_diff.main([str(old), str(new)]) == 2
        capsys.readouterr()

    def test_scan_trajectory_steps_over_dead_rounds(self, tmp_path, capsys):
        # r1 ok, r2 skipped (infra-dead), r3 ok-but-regressed: the scan
        # compares r1 against r3, not against the dead round
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"n": 1, "rc": 0, "tail": "", "parsed": _rec("m", 100.0)}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(
            {"n": 2, "rc": 0, "tail": "", "parsed": SKIPPED_REC}))
        (tmp_path / "BENCH_r03.json").write_text(json.dumps(
            {"n": 3, "rc": 0, "tail": "", "parsed": _rec("m", 50.0)}))
        assert bench_diff.main(["--scan", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "100" in out and "50" in out

    def test_scan_all_dead_is_nodata(self, tmp_path, capsys):
        for i in (1, 2):
            (tmp_path / f"BENCH_r0{i}.json").write_text(json.dumps(
                {"n": i, "rc": 0, "tail": "", "parsed": ERROR_REC}))
        assert bench_diff.main(["--scan", str(tmp_path)]) == 2
        capsys.readouterr()

    def test_json_output_mode(self, tmp_path, capsys):
        old, new = tmp_path / "o.json", tmp_path / "n.json"
        self._write(old, [_rec("m", 100.0)])
        self._write(new, [_rec("m", 80.0)])
        assert bench_diff.main([str(old), str(new), "--json"]) == 1
        report = json.loads(capsys.readouterr().out)
        assert report["regressions"] == 1 and report["compared"] == 1
        assert report["rows"][0]["metric"] == "m"


class TestTelemetryExpansion:
    """Telemetry snapshot fields (TTFT p99, goodput, MFU…) participate in
    the diff as synthetic <metric>.telemetry.<field> rows with
    per-field direction — a latency rise OR a goodput/MFU drop flags even
    when the headline number held."""

    def _tel_rec(self, value, ttft_p99, goodput, mfu):
        rec = _rec("gpt_serving_tokens_per_sec", value)
        rec["telemetry"] = {"ttft_ms_p99": ttft_p99, "ticks": 40,
                            "goodput": {"goodput": goodput}, "mfu": mfu}
        return rec

    def test_expansion_whitelists_and_flattens(self):
        rows = bench_diff.expand_telemetry(
            [self._tel_rec(100.0, 12.0, 0.8, 0.4)])
        by = {r["metric"]: r for r in rows}
        assert "gpt_serving_tokens_per_sec.telemetry.ttft_ms_p99" in by
        assert "gpt_serving_tokens_per_sec.telemetry.goodput.goodput" \
            in by                                 # nested dicts flatten
        assert "gpt_serving_tokens_per_sec.telemetry.mfu" in by
        # un-whitelisted leaves (raw tick counts) stay out
        assert not any(m.endswith(".ticks") for m in by)
        assert by["gpt_serving_tokens_per_sec.telemetry.ttft_ms_p99"][
            "direction"] == "lower"
        # error rounds never expand
        assert bench_diff.expand_telemetry([ERROR_REC]) == [ERROR_REC]

    def test_latency_rise_regresses_throughput_held(self):
        old = bench_diff.expand_telemetry(
            [self._tel_rec(100.0, 10.0, 0.8, 0.4)])
        new = bench_diff.expand_telemetry(
            [self._tel_rec(100.0, 25.0, 0.8, 0.4)])
        rows, n_reg, n_cmp = bench_diff.compare(old, new, 0.1)
        bad = [r for r in rows if "REGRESSION" in r["status"]]
        assert n_reg == 1
        assert bad[0]["metric"].endswith("ttft_ms_p99")

    def test_goodput_and_mfu_drop_regress_higher_is_better(self):
        old = bench_diff.expand_telemetry(
            [self._tel_rec(100.0, 10.0, 0.8, 0.4)])
        new = bench_diff.expand_telemetry(
            [self._tel_rec(100.0, 10.0, 0.4, 0.1)])
        rows, n_reg, _ = bench_diff.compare(old, new, 0.1)
        assert n_reg == 2
        names = {r["metric"].split(".")[-1] for r in rows
                 if "REGRESSION" in r["status"]}
        assert names == {"goodput", "mfu"}
        # and an IMPROVEMENT in a lower-is-better field never flags
        rows, n_reg, _ = bench_diff.compare(
            bench_diff.expand_telemetry(
                [self._tel_rec(100.0, 25.0, 0.8, 0.4)]),
            bench_diff.expand_telemetry(
                [self._tel_rec(100.0, 10.0, 0.8, 0.4)]), 0.1)
        assert n_reg == 0

    def test_one_sided_telemetry_not_comparable(self):
        """Only rounds that BOTH carry the field compare — an old round
        without telemetry must not fabricate a regression."""
        old = bench_diff.expand_telemetry(
            [_rec("gpt_serving_tokens_per_sec", 100.0)])
        new = bench_diff.expand_telemetry(
            [self._tel_rec(100.0, 25.0, 0.8, 0.4)])
        rows, n_reg, n_cmp = bench_diff.compare(old, new, 0.1)
        assert n_reg == 0 and n_cmp == 1          # headline only
        tel_rows = [r for r in rows if ".telemetry." in r["metric"]]
        assert tel_rows and all("not comparable" in r["status"]
                                for r in tel_rows)

    def test_scan_trajectory_diffs_telemetry(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"n": 1, "rc": 0, "tail": "",
             "parsed": self._tel_rec(100.0, 10.0, 0.8, 0.4)}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(
            {"n": 2, "rc": 0, "tail": "",
             "parsed": self._tel_rec(100.0, 30.0, 0.8, 0.4)}))
        assert bench_diff.main(["--scan", str(tmp_path)]) == 1
        out = capsys.readouterr().err
        assert "1 regression" in out


class TestUpdateShardingExpansion:
    """The gpt_weight_update_sharding attachment (ISSUE 16) expands into
    per-arm synthetic rows: bytes/step-wall/wire regress when they RISE,
    the reduction factor and throughput when they DROP, and a growing
    loss_delta flags the parity pin eroding."""

    def _us_rec(self, value, sharded_bytes, reduction, sharded_ms,
                loss_delta=0.0):
        rec = _rec("gpt_weight_update_sharding_tokens_per_sec", value)
        rec["update_sharding"] = {
            "replicas": 2,
            "replicated": {"opt_bytes_per_replica": 3829760,
                           "step_ms": 850.0, "tokens_per_sec": 300.0,
                           "wire_bytes": 3829760, "loss": 5.62},
            "sharded": {"opt_bytes_per_replica": sharded_bytes,
                        "step_ms": sharded_ms, "tokens_per_sec": value,
                        "wire_bytes": 3829760, "loss": 5.62},
            "opt_bytes_reduction": reduction,
            "loss_delta": loss_delta,
        }
        return rec

    def test_expansion_covers_both_arms_with_directions(self):
        rows = bench_diff.expand_telemetry(
            [self._us_rec(6000.0, 1914880, 2.0, 42.0)])
        by = {r["metric"]: r for r in rows}
        pre = "gpt_weight_update_sharding_tokens_per_sec.update_sharding"
        assert f"{pre}.sharded.opt_bytes_per_replica" in by
        assert f"{pre}.replicated.opt_bytes_per_replica" in by
        assert f"{pre}.opt_bytes_reduction" in by
        assert f"{pre}.loss_delta" in by
        assert by[f"{pre}.sharded.opt_bytes_per_replica"][
            "direction"] == "lower"
        assert by[f"{pre}.opt_bytes_reduction"]["direction"] == "higher"
        # scenario context (replica count, absolute loss) stays out
        assert not any(m.endswith(".replicas") for m in by)
        assert not any(m.endswith(".loss") for m in by)

    def test_bytes_rise_and_reduction_drop_regress(self):
        old = bench_diff.expand_telemetry(
            [self._us_rec(6000.0, 1914880, 2.0, 42.0)])
        new = bench_diff.expand_telemetry(
            [self._us_rec(6000.0, 3829760, 1.0, 42.0)])
        rows, n_reg, _ = bench_diff.compare(old, new, 0.1)
        names = {r["metric"].split(".")[-1] for r in rows
                 if "REGRESSION" in r["status"]}
        # the sharded arm's bytes doubled AND the reduction factor halved
        assert "opt_bytes_per_replica" in names
        assert "opt_bytes_reduction" in names

    def test_step_wall_rise_regresses_headline_held(self):
        old = bench_diff.expand_telemetry(
            [self._us_rec(6000.0, 1914880, 2.0, 42.0)])
        new = bench_diff.expand_telemetry(
            [self._us_rec(6000.0, 1914880, 2.0, 90.0)])
        rows, n_reg, _ = bench_diff.compare(old, new, 0.1)
        bad = [r for r in rows if "REGRESSION" in r["status"]]
        assert n_reg == 1
        assert bad[0]["metric"].endswith("sharded.step_ms")
